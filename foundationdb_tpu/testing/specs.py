"""Named test specs (the analog of tests/*.txt).

Each spec composes workloads + cluster config like the reference's
declarative files: tests/fast/CycleTest.txt = Cycle + RandomClogging +
Attrition; attrition joins once recovery lands. Run via the CLI:

    python -m foundationdb_tpu.testing.runner --spec CycleTest --seed 7
    python -m foundationdb_tpu.testing.runner --list
"""
from __future__ import annotations

from typing import Callable, Dict

from ..server.cluster import ClusterConfig, DynamicClusterConfig
from .workload import Spec
from .workloads import (
    AtomicOpsWorkload,
    BackupCorrectnessWorkload,
    BulkLoadWorkload,
    ConflictRangeWorkload,
    ConsistencyCheckWorkload,
    CycleWorkload,
    DatacenterKillWorkload,
    DeviceFaultValidationWorkload,
    FullClusterRebootWorkload,
    FuzzApiCorrectnessWorkload,
    IncrementWorkload,
    InventoryWorkload,
    MachineAttritionWorkload,
    QueuePushWorkload,
    RandomCloggingWorkload,
    RandomMoveKeysWorkload,
    RandomReadWriteWorkload,
    SelectorCorrectnessWorkload,
    SerializabilityWorkload,
    ThroughputWorkload,
    VersionStampWorkload,
    WatchesWorkload,
    WriteDuringReadWorkload,
)


def _tpu_engine_factory():
    from ..ops.conflict_kernel import KernelConfig
    from ..ops.host_engine import JaxConflictEngine

    cfg = KernelConfig(key_words=4, capacity=1024, max_reads=256, max_writes=256, max_txns=64)
    return JaxConflictEngine(cfg)


def _sharded_engine_factory():
    """The north-star resolver: ONE resolver role whose conflict engine is
    sharded over the whole device mesh (8 virtual CPU devices in tests, a
    pod slice on hardware), verdicts combined by psum over ICI — device
    parallelism replacing the reference's resolver-count scaling
    (MasterProxyServer.actor.cpp:263-316 proxy-side splitting)."""
    import jax

    from ..ops.conflict_kernel import KernelConfig
    from ..parallel.sharding import KeyShardMap, ShardedConflictEngine

    n = len(jax.devices())
    cfg = KernelConfig(key_words=4, capacity=1024, max_reads=256, max_writes=256, max_txns=64)
    return ShardedConflictEngine(cfg, KeyShardMap.uniform(n))


def _nemesis_engine_factory():
    """The device-nemesis resolver engine: the reference oracle behind a
    seed-driven fault injector (exceptions, hangs, slow batches, bursty
    outages at FaultRates defaults; verdict flips off — see fault/inject.py),
    supervised by ResilientEngine, which must keep the emitted abort sets
    bit-identical throughout. The supervisor runs a tightened failover /
    probation cycle: resolver generations only live a few seconds between
    attrition kills, and the campaign needs full failover -> re-warm ->
    swap-back round trips inside one generation, not just the failover
    half."""
    from ..fault import FaultInjectingEngine, ResilienceConfig, ResilientEngine
    from ..ops.oracle import OracleConflictEngine

    return ResilientEngine(
        FaultInjectingEngine(OracleConflictEngine()),
        ResilienceConfig(dispatch_timeout=0.3, retry_budget=1,
                         retry_backoff=0.05, probe_rate=0.1,
                         probation_batches=2, failover_min_batches=2),
        record_journal=True,   # the check replays it for abort-set parity
    )


SPECS: Dict[str, Callable[[], Spec]] = {
    # the device nemesis (ISSUE 2): machine kills + clogging + a faulting
    # conflict engine, all at once. The check asserts workload invariants,
    # zero durability violations (run_spec's sim_validation gate), and that
    # every supervised engine's journal replays bit-identically through a
    # clean oracle — failover and swap-back included.
    "DeviceNemesis": lambda: Spec(
        title="DeviceNemesis",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 18, "think_time": 0.8}),
            (MachineAttritionWorkload, {"interval": 9.0, "delay_before": 4.0}),
            (RandomCloggingWorkload, {"scale": 0.02}),
            (DeviceFaultValidationWorkload, {}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=5, n_tlogs=2, n_resolvers=2,
                                     n_storage=2,
                                     engine_factory=_nemesis_engine_factory),
        client_count=2,
        timeout=900.0,
    ),
    # tests/fast/CycleTest.txt with Attrition: Cycle churn while workers
    # hosting transaction roles are killed + rebooted — every kill forces a
    # full epoch recovery (the reference's core correctness strategy)
    "CycleTestAttrition": lambda: Spec(
        title="CycleTestAttrition",
        workloads=[
            (CycleWorkload, {"nodes": 10, "transactions": 12, "think_time": 1.5}),
            (MachineAttritionWorkload, {"interval": 6.0, "delay_before": 2.0}),
            (RandomCloggingWorkload, {"scale": 0.02}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=5, n_tlogs=2, n_resolvers=2, n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # replicated storage (2 shards x 2 replicas) under kill/reboot churn;
    # the quiescent consistency check diffs every team's replicas
    "CycleReplicated": lambda: Spec(
        title="CycleReplicated",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 1.5}),
            (MachineAttritionWorkload, {"interval": 6.0, "delay_before": 2.0}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=8, n_tlogs=2, n_resolvers=2,
                                     n_storage=2, storage_replication=2),
        client_count=2,
        timeout=900.0,
    ),
    # shards move between teams while cycle churn runs (MoveKeys v0 through
    # the \xff system keyspace); the cycle + replica checks prove no
    # mutation is lost across either phase of a move
    "MoveKeysCycle": lambda: Spec(
        title="MoveKeysCycle",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 12, "think_time": 1.5}),
            (RandomMoveKeysWorkload, {"moves": 3, "interval": 4.0}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=10, n_tlogs=2, n_resolvers=2,
                                     n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # three proxies + GRV causality quorum under kill/reboot churn
    "MultiProxyAttrition": lambda: Spec(
        title="MultiProxyAttrition",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 2.0}),
            (MachineAttritionWorkload, {"interval": 6.0, "delay_before": 2.0}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=8, n_tlogs=2, n_resolvers=2,
                                     n_proxies=3, n_storage=2),
        client_count=3,
        timeout=900.0,
    ),
    # per-tag tlog subsets (R=2 of K=3) under kill/reboot churn: every
    # recovery exercises the lock-coverage quorum + merged per-tag fetch
    "CycleLogSubsets": lambda: Spec(
        title="CycleLogSubsets",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 2.0}),
            (MachineAttritionWorkload, {"interval": 6.0, "delay_before": 2.0}),
        ],
        dynamic=DynamicClusterConfig(n_workers=6, n_tlogs=3,
                                     log_replication_factor=2, n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # durability torture: any worker (storage included) can die and reboot;
    # disks with torn un-fsynced writes must always re-form the database
    "DiskAttrition": lambda: Spec(
        title="DiskAttrition",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 8, "think_time": 2.0}),
            (MachineAttritionWorkload, {"interval": 5.0, "delay_before": 2.0}),
        ],
        dynamic=DynamicClusterConfig(n_workers=6, n_tlogs=2, n_resolvers=2, n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # recovery churn without clogging, heavier kill rate
    "AttritionStress": lambda: Spec(
        title="AttritionStress",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 2.5}),
            (MachineAttritionWorkload, {"interval": 4.0, "delay_before": 1.0}),
        ],
        dynamic=DynamicClusterConfig(n_workers=6, n_tlogs=2, n_resolvers=2, n_storage=2),
        client_count=3,
        timeout=900.0,
    ),
    # multi-region: two DCs, a satellite tlog replica outside the
    # primary, cross-DC storage teams, coordinator majority outside dc0,
    # DCN latency on inter-DC hops — then dc0 DIES WHOLESALE mid-load and
    # revives later. The recovery must fail over to dc1 (satellite log =
    # complete acked history; the sim_validation oracle enforces it) and
    # the cycle invariant must hold end to end.
    # reference: TagPartitionedLogSystem satellites, LogRouter's role,
    # region config in SimulatedCluster.actor.cpp:706
    "RegionFailover": lambda: Spec(
        title="RegionFailover",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 2.0}),
            (DatacenterKillWorkload, {"dc": "dc0", "delay_before": 6.0,
                                      "revive_after": 25.0}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=10, n_coordinators=5,
                                     n_tlogs=3, satellite_logs=1,
                                     n_resolvers=2, n_storage=2,
                                     storage_replication=2, n_dcs=2,
                                     inter_dc_latency=0.003),
        client_count=2,
        timeout=900.0,
    ),
    # the durable-tier grinder (VERDICT r4 #7): volume through the LSM
    # engines + randomized knobs (eager tlog spill, tiny flush budgets,
    # BUGGIFY crash windows in compaction/manifest/WAL) under kill/reboot
    # churn AND clogging — the composed torture the round-4 tier shipped
    # without
    "DurableCycleAttrition": lambda: Spec(
        title="DurableCycleAttrition",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 1.8}),
            (BulkLoadWorkload, {"batches": 4, "batch_size": 60}),
            (MachineAttritionWorkload, {"interval": 6.0, "delay_before": 3.0}),
            (RandomCloggingWorkload, {"scale": 0.02}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=6, n_tlogs=2, n_resolvers=2,
                                     n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # DD split/merge under attrition (VERDICT r4 #7): volume drives the
    # tracker's (randomized-knob) split threshold while workers die and
    # reboot; the replica diff + cycle invariant must hold through
    # relocations racing recoveries
    "DataDistributionAttrition": lambda: Spec(
        title="DataDistributionAttrition",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 10, "think_time": 1.8}),
            (BulkLoadWorkload, {"batches": 5, "batch_size": 60}),
            (MachineAttritionWorkload, {"interval": 7.0, "delay_before": 4.0,
                                        "spare_storage": True}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=10, n_tlogs=2, n_resolvers=2,
                                     n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # tests/restarting/-class spec: the WHOLE cluster (coordinators
    # included) reboots mid-run; everything re-forms from disk and the
    # invariants hold across the gap
    "CycleTestRestart": lambda: Spec(
        title="CycleTestRestart",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 12, "think_time": 1.5}),
            (FullClusterRebootWorkload, {"delay_before": 6.0, "rounds": 2,
                                         "interval": 14.0}),
            (ConsistencyCheckWorkload, {}),
        ],
        dynamic=DynamicClusterConfig(n_workers=6, n_tlogs=2, n_resolvers=2,
                                     n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # fast/Watches.txt + rare/SelectorCorrectness + VersionStamp
    "WatchesAndSelectors": lambda: Spec(
        title="WatchesAndSelectors",
        workloads=[
            (WatchesWorkload, {"rounds": 5}),
            (SelectorCorrectnessWorkload, {"checks": 25}),
            (VersionStampWorkload, {"rounds": 6}),
        ],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2),
        client_count=2,
        timeout=600.0,
    ),
    # tests/fast/CycleTest.txt: Cycle + RandomClogging ×2 (+ replica check)
    "CycleTest": lambda: Spec(
        title="CycleTest",
        workloads=[
            (CycleWorkload, {"nodes": 12, "transactions": 15}),
            (RandomCloggingWorkload, {"scale": 0.02}),
            (ConsistencyCheckWorkload, {}),
        ],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2, storage_replication=2),
        client_count=3,
    ),
    # the north star: same cycle churn, resolvers on the TPU kernel
    "CycleTestTPU": lambda: Spec(
        title="CycleTestTPU",
        workloads=[(CycleWorkload, {"nodes": 10, "transactions": 8})],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2, engine_factory=_tpu_engine_factory),
        client_count=2,
    ),
    # the north-star 8-shard config INSIDE the simulated cluster: one
    # resolver role backed by the device-mesh ShardedConflictEngine
    # (8-way key sharding + ICI psum verdict combine)
    "CycleTestTPU8": lambda: Spec(
        title="CycleTestTPU8",
        workloads=[(CycleWorkload, {"nodes": 10, "transactions": 8})],
        cluster=ClusterConfig(
            n_resolvers=1, n_storage=2, engine_factory=_sharded_engine_factory
        ),
        client_count=2,
    ),
    # high-in-flight mixed load on the 8-shard engine: many concurrent
    # clients keep several commit batches in the pipeline at once
    "RandomReadWriteTPU8": lambda: Spec(
        title="RandomReadWriteTPU8",
        workloads=[
            (RandomReadWriteWorkload, {"transactions": 12}),
            (ConflictRangeWorkload, {"rounds": 6}),
        ],
        cluster=ClusterConfig(
            n_resolvers=1, n_storage=4, engine_factory=_sharded_engine_factory
        ),
        client_count=6,
    ),
    # fast/BackupCorrectness.txt: a live backup straddles cycle churn and
    # restores bit-identically into a second cluster
    "BackupCorrectness": lambda: Spec(
        title="BackupCorrectness",
        workloads=[
            (CycleWorkload, {"nodes": 8, "transactions": 12, "think_time": 0.3}),
            (BackupCorrectnessWorkload, {"chunks": 4}),
        ],
        dynamic=DynamicClusterConfig(n_workers=6, n_tlogs=2, n_resolvers=2,
                                     n_storage=2),
        client_count=2,
        timeout=900.0,
    ),
    # rare/FuzzApiCorrectness.txt: randomized op streams vs the model,
    # with clogging so retry/unknown-result paths actually fire
    "FuzzApiCorrectness": lambda: Spec(
        title="FuzzApiCorrectness",
        workloads=[
            (FuzzApiCorrectnessWorkload, {"transactions": 18}),
            (RandomCloggingWorkload, {"scale": 0.02}),
            (ConsistencyCheckWorkload, {}),
        ],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2, storage_replication=2),
        client_count=3,
    ),
    # write-skew + balance invariants under contention: anomalies snapshot
    # isolation allows and the resolver's read-conflict detection forbids
    "Serializability": lambda: Spec(
        title="Serializability",
        workloads=[
            (SerializabilityWorkload, {"rounds": 10}),
            (RandomCloggingWorkload, {"scale": 0.02}),
        ],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2),
        client_count=4,
    ),
    # Inventory + QueuePush + clogging: conditional RMWs and contended
    # versionstamped appends under transport loss
    "InventoryQueue": lambda: Spec(
        title="InventoryQueue",
        workloads=[
            (InventoryWorkload, {"ops": 12}),
            (QueuePushWorkload, {"pushes": 10}),
            (RandomCloggingWorkload, {"scale": 0.02}),
            (ConsistencyCheckWorkload, {}),
        ],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2, storage_replication=2),
        client_count=3,
    ),
    # sustained sequential loading + a timed 90/10 measurement pass
    "BulkLoadThroughput": lambda: Spec(
        title="BulkLoadThroughput",
        workloads=[
            (BulkLoadWorkload, {"batches": 5, "batch_size": 40}),
            (ThroughputWorkload, {"seconds": 4.0}),
        ],
        cluster=ClusterConfig(n_resolvers=2, n_storage=4),
        client_count=3,
    ),
    "IncrementTest": lambda: Spec(
        title="IncrementTest",
        workloads=[(IncrementWorkload, {"transactions": 12})],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2),
        client_count=3,
    ),
    # tests/rare/ConflictRangeCheck.txt
    "ConflictRangeCheck": lambda: Spec(
        title="ConflictRangeCheck",
        workloads=[(ConflictRangeWorkload, {"rounds": 20})],
        cluster=ClusterConfig(n_resolvers=4, n_storage=2),
        client_count=4,
    ),
    "WriteDuringRead": lambda: Spec(
        title="WriteDuringRead",
        workloads=[(WriteDuringReadWorkload, {"rounds": 12})],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2),
        client_count=2,
    ),
    "AtomicOps": lambda: Spec(
        title="AtomicOps",
        workloads=[(AtomicOpsWorkload, {"transactions": 15})],
        cluster=ClusterConfig(n_resolvers=2, n_storage=2),
        client_count=3,
    ),
    # tests/RandomReadWrite.txt: the 90/10 metric workload + clogging
    "RandomReadWrite": lambda: Spec(
        title="RandomReadWrite",
        workloads=[
            (RandomReadWriteWorkload, {"transactions": 20}),
            (RandomCloggingWorkload, {"scale": 0.02}),
        ],
        cluster=ClusterConfig(n_resolvers=4, n_storage=4),
        client_count=4,
    ),
}
