"""Token-addressed simulated message bus.

Re-design of FlowTransport + Sim2Conn (fdbrpc/FlowTransport.actor.cpp,
fdbrpc/sim2.actor.cpp:180-675) as one deterministic object: endpoints are
(process address, token) pairs; a request spawns the registered handler on
the destination process and routes the reply back; every hop pays a randomly
drawn latency from the simulation RNG; clogging and partitions delay or
strand packets; killing a process breaks outstanding replies
(request_maybe_delivered semantics, fdbrpc/fdbrpc.h NetSAV).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional, Set, Tuple

from ..core import error
from .actors import ActorCollection
from .failmon import FailureMonitor
from .loop import Future, Scheduler, TaskPriority


@dataclass(frozen=True)
class Endpoint:
    """Addressable mailbox (reference: Endpoint, FlowTransport.h:28-50)."""

    address: str   # process address, e.g. "1.0.0.1:1"
    token: str     # well-known or generated service token


Handler = Callable[[Any], Awaitable[Any]]


class SimProcess:
    """One simulated process (reference: ISimulator::ProcessInfo,
    simulator.h:47-121). Roles register token handlers; every spawned actor
    belongs to the process and dies with it."""

    def __init__(self, address: str, machine_id: str, dc_id: str, name: str = "") -> None:
        self.address = address
        self.machine_id = machine_id
        self.dc_id = dc_id
        self.name = name or address
        self.alive = True
        self.handlers: Dict[str, Handler] = {}
        self.actors = ActorCollection()
        self.globals: Dict[str, Any] = {}   # per-process globals (simulator.h:62,101)
        self.reboots = 0

    def register(self, token: str, handler: Handler) -> Endpoint:
        self.handlers[token] = handler
        return Endpoint(self.address, token)

    def unregister(self, token: str) -> None:
        self.handlers.pop(token, None)


class SimNetwork:
    """The one message bus for a simulation."""

    def __init__(self, sched: Scheduler, min_latency: float = 0.0001, max_latency: float = 0.001):
        self.sched = sched
        self.processes: Dict[str, SimProcess] = {}
        self.monitor = FailureMonitor()
        self.min_latency = min_latency
        self.max_latency = max_latency
        # (src, dst) -> virtual time until which packets are held (SimClogging)
        self._clogged_until: Dict[Tuple[str, str], float] = {}
        self._partitioned: Set[Tuple[str, str]] = set()
        #: extra one-way latency between processes in DIFFERENT DCs (the
        #: DCN tier of a multi-region topology; 0 = single-region exact)
        self.inter_dc_latency: float = 0.0

    # -- topology ------------------------------------------------------------
    def add_process(self, proc: SimProcess) -> None:
        self.processes[proc.address] = proc

    def clog_pair(self, a: str, b: str, seconds: float) -> None:
        until = self.sched.time + seconds
        for pair in ((a, b), (b, a)):
            self._clogged_until[pair] = max(self._clogged_until.get(pair, 0.0), until)

    def partition(self, a: str, b: str) -> None:
        self._partitioned.add((a, b))
        self._partitioned.add((b, a))

    def heal_partition(self, a: str, b: str) -> None:
        self._partitioned.discard((a, b))
        self._partitioned.discard((b, a))

    # -- delivery ------------------------------------------------------------
    def _latency(self) -> float:
        r = self.sched.rng.random01()
        return self.min_latency + (self.max_latency - self.min_latency) * r

    def _hop_delay(self, src: str, dst: str) -> Optional[float]:
        """Latency for one packet, or None if it can never arrive now."""
        if (src, dst) in self._partitioned:
            return None
        base = self.sched.time + self._latency()
        if self.inter_dc_latency:
            ps, pd = self.processes.get(src), self.processes.get(dst)
            if ps is not None and pd is not None and ps.dc_id != pd.dc_id:
                base += self.inter_dc_latency
        clog = self._clogged_until.get((src, dst), 0.0)
        return max(base, clog) - self.sched.time

    def request(
        self,
        src: str,
        endpoint: Endpoint,
        payload: Any,
        priority: int = TaskPriority.DEFAULT_ENDPOINT,
        timeout: Optional[float] = None,
    ) -> Future:
        """Send payload to endpoint; future of the handler's return value.

        reference: RequestStream<T>::getReply (fdbrpc/fdbrpc.h:229-249).
        Errors: connection_failed if the destination is dead, unroutable, or
        marked failed by the failure monitor (fdbrpc/FailureMonitor.h:81);
        request_maybe_delivered if it dies or is declared failed mid-flight,
        or if `timeout` virtual seconds elapse without a reply. Handler
        exceptions propagate to the caller like serialized error replies.
        """
        reply = Future()
        if self.monitor.is_failed(endpoint.address):
            reply._set_error(error.connection_failed(f"{endpoint.address} marked failed"))
            return reply
        fwd = self._hop_delay(src, endpoint.address)
        if fwd is None:
            # Partition: the packet never arrives. The failure monitor or the
            # caller's timeout must fire — the future may not hang forever.
            self._arm_watchdogs(reply, endpoint.address, timeout)
            return reply
        # Outstanding-reply breakage on process death rides the failure
        # monitor: kill marks the address failed, which errors every armed
        # reply with request_maybe_delivered (the NetSAV broken-connection
        # semantics, fdbrpc/fdbrpc.h:64-89).
        self._arm_watchdogs(reply, endpoint.address, timeout)

        def deliver() -> None:
            proc = self.processes.get(endpoint.address)
            if proc is None or not proc.alive:
                if not reply.is_ready:
                    reply._set_error(error.connection_failed())
                return
            handler = proc.handlers.get(endpoint.token)
            if handler is None:
                if not reply.is_ready:
                    reply._set_error(error.connection_failed())
                return

            async def run() -> None:
                try:
                    result = await handler(payload)
                except error.FDBError as e:
                    self._send_reply(endpoint.address, src, reply, None, e, priority)
                    return
                self._send_reply(endpoint.address, src, reply, result, None, priority)

            proc.actors.add(self.sched.spawn(run(), priority, name=f"handle:{endpoint.token}"))

        self.sched.at(self.sched.time + fwd, deliver, priority)
        return reply

    def _arm_watchdogs(self, reply: Future, dst: str, timeout: Optional[float]) -> None:
        """Error the reply if the destination is declared failed while it is
        outstanding, or after `timeout` virtual seconds (whichever first)."""
        watch = self.monitor.on_failed(
            dst,
            lambda: (not reply.is_ready)
            and reply._set_error(error.request_maybe_delivered(f"{dst} declared failed")),
        )
        if watch is not None:
            reply.on_ready(lambda _: watch.cancel())
        if timeout is not None:
            self.sched.at(
                self.sched.time + timeout,
                lambda: (not reply.is_ready)
                and reply._set_error(error.request_maybe_delivered(f"timeout to {dst}")),
                TaskPriority.DEFAULT_DELAY,
            )

    def _send_reply(
        self, src: str, dst: str, reply: Future, value: Any, err: Optional[BaseException], priority: int
    ) -> None:
        back = self._hop_delay(src, dst)
        if back is None:
            return  # reply stranded by partition; caller's reply future hangs

        def deliver() -> None:
            if reply.is_ready:
                return
            if err is not None:
                reply._set_error(err)
            else:
                reply._set(value)

        self.sched.at(self.sched.time + back, deliver, priority)

    def one_way(self, src: str, endpoint: Endpoint, payload: Any, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> None:
        """Fire-and-forget send (reference: FlowTransport::sendUnreliable)."""
        self.request(src, endpoint, payload, priority)
