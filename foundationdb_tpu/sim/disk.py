"""Simulated disks with kill-time loss of un-fsynced writes.

Re-design of the reference's IAsyncFile stack for simulation
(fdbrpc/AsyncFileNonDurable.actor.h + SimDiskSpace): every process address
owns a SimDisk of named files that SURVIVES process death and reboot (the
machine's platters), while un-synced writes live in a page-cache buffer
that a crash randomly applies, drops, or tears per write — the fault model
that forces every durable component to reason about fsync boundaries and
torn tails, exactly like the reference's correctness runs.

Latencies are drawn from the simulation RNG so disk scheduling is
deterministic per seed.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core import error
from .loop import Scheduler, TaskPriority


class SimFile:
    """One file: durable bytes + un-synced write buffer (the page cache)."""

    def __init__(self, disk: "SimDisk", name: str):
        self.disk = disk
        self.name = name
        self.durable = bytearray()
        #: ordered un-synced writes: (offset, bytes)
        self.pending: List[Tuple[int, bytes]] = []
        self._pending_truncate: Optional[int] = None

    # -- the OS view (durable + page cache) ----------------------------------
    def _view(self) -> bytearray:
        buf = bytearray(self.durable)
        if self._pending_truncate is not None:
            del buf[self._pending_truncate:]
        for off, data in self.pending:
            if len(buf) < off:
                buf.extend(b"\x00" * (off - len(buf)))
            buf[off:off + len(data)] = data
        return buf

    def size(self) -> int:
        return len(self._view())

    # -- async file API (IAsyncFile) ------------------------------------------
    async def read(self, offset: int, length: int) -> bytes:
        await self.disk._latency()
        view = self._view()
        return bytes(view[offset:offset + length])

    async def write(self, offset: int, data: bytes) -> None:
        await self.disk._latency()
        self.pending.append((offset, bytes(data)))

    async def truncate(self, size: int) -> None:
        await self.disk._latency()
        # Order matters vs pending writes; flatten what we have, then mark.
        flat = self._view()
        del flat[size:]
        self.pending = [(0, bytes(flat))]
        self._pending_truncate = 0

    async def sync(self) -> None:
        """fsync: everything written so far becomes durable."""
        await self.disk._latency(sync=True)
        self.durable = self._view()
        self.pending = []
        self._pending_truncate = None

    # -- crash semantics (AsyncFileNonDurable) --------------------------------
    def crash(self, rng) -> None:
        """Process died with this file open: each un-synced write is
        independently applied, dropped, or torn (random prefix + garbage
        tail) — reference: AsyncFileNonDurable KillMode semantics."""
        buf = bytearray(self.durable)
        if self._pending_truncate is not None:
            del buf[self._pending_truncate:]
        for off, data in self.pending:
            roll = rng.random01()
            if roll < 0.5:
                applied = data                        # made it to the platter
            elif roll < 0.8:
                continue                              # lost entirely
            else:
                keep = rng.random_int(0, len(data) + 1)
                # torn: prefix lands, the rest is garbage bits
                applied = data[:keep] + bytes(
                    rng.random_int(0, 256) for _ in range(len(data) - keep)
                )
            if len(buf) < off:
                buf.extend(b"\x00" * (off - len(buf)))
            buf[off:off + len(applied)] = applied
        self.durable = buf
        self.pending = []
        self._pending_truncate = None


class SimDisk:
    """All files for one process address; survives reboots."""

    def __init__(self, sched: Scheduler, min_latency: float = 0.00005,
                 max_latency: float = 0.0005):
        self.sched = sched
        self.files: Dict[str, SimFile] = {}
        self.min_latency = min_latency
        self.max_latency = max_latency

    async def _latency(self, sync: bool = False):
        r = self.sched.rng.random01()
        lat = self.min_latency + (self.max_latency - self.min_latency) * r
        if sync:
            lat *= 4  # fsync costs more than a buffered write
        f = self.sched.delay(lat, TaskPriority.DEFAULT_DELAY)
        await f

    def open(self, name: str, create: bool = True) -> SimFile:
        f = self.files.get(name)
        if f is None:
            if not create:
                raise error.file_not_found(name)
            f = self.files[name] = SimFile(self, name)
        return f

    def exists(self, name: str) -> bool:
        return name in self.files

    def delete(self, name: str) -> None:
        self.files.pop(name, None)

    def rename(self, src: str, dst: str) -> None:
        """Atomic rename (POSIX semantics; callers sync the source first).
        The sim treats the rename itself as immediately durable."""
        f = self.files.pop(src)
        f.name = dst
        self.files[dst] = f

    def list(self, prefix: str = "") -> List[str]:
        return sorted(n for n in self.files if n.startswith(prefix))

    def crash(self, rng) -> None:
        for f in self.files.values():
            f.crash(rng)
