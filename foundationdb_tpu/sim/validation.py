"""Magic durability assertions, usable only in simulation.

Re-design of fdbrpc/sim_validation.h:20-50 (debug_advanceMaxCommittedVersion
/ debug_checkRestoredVersion): the simulator tracks, OUT OF BAND, the
highest commit version whose tlog push fully acked. Every epoch-end
recovery must pick a recovery version at or above it — a lower one would
silently discard data the cluster already acknowledged as durable. The
check is global and unconditional in sim: it rides every spec (attrition
included) for free, catching recovery-version math bugs that workload
invariants can miss (a dropped suffix of acked-but-unread writes).

Violations are RECORDED, not raised: a raise inside the master's recovery
actor would surface as just another master failure and be retried into
silence. The spec runner asserts the violation list is empty at the end of
every run (SevError semantics: any violation fails the test).
"""
from __future__ import annotations

from typing import List, Tuple

_enabled = False
#: per-GENERATION acked-push watermark: gen_id -> max fully-acked version.
#: Scoped by generation (recovery_count, master_salt — globally unique in
#: a sim), because (a) the min(end) invariant binds a recovery to the
#: generation it LOCKED, and (b) one simulation can host several clusters
#: (backup/DR specs) whose version chains are unrelated
_max_committed: dict = {}
#: gen_id -> the recovery version its epoch END chose: any LATER
#: fully-acked push above it is a zombie ack (a deposed generation's
#: straggler completing after recovery discarded those versions)
_recovered: dict = {}
#: (gen_id, recovery_version, max_committed_at_check) per violation
violations: List[Tuple] = []


def enable() -> None:
    """Arm the oracle (the simulator's constructor calls this)."""
    global _enabled
    _enabled = True
    _max_committed.clear()
    _recovered.clear()
    violations.clear()


def disable() -> None:
    global _enabled
    _enabled = False
    _max_committed.clear()
    _recovered.clear()


def advance_max_committed(gen_id, version: int) -> None:
    """A commit's log-system push to generation `gen_id` fully acked at
    `version` (the durability point recovery must honor). An ack landing
    ABOVE a recovery that already ended this generation's epoch is itself
    a violation (zombie push: the commit is acked, the versions are
    discarded — the durable-tlog-lock bug's exact shape). No-op outside
    simulation."""
    if not _enabled:
        return
    if version > _max_committed.get(gen_id, 0):
        _max_committed[gen_id] = version
    rec = _recovered.get(gen_id)
    if rec is not None and version > rec:
        violations.append((gen_id, rec, version))


def check_restored_version(gen_id, recovery_version: int) -> None:
    """An epoch-end recovery of generation `gen_id` chose
    `recovery_version`: it must cover every fully-acked push to that
    generation (all-ack means any locked replica bounds it from above, so
    min(end) over the locked set can never be below a completed push — if
    it is, the lock/recovery math lost acknowledged data)."""
    if not _enabled:
        return
    if recovery_version < _max_committed.get(gen_id, 0):
        violations.append((gen_id, recovery_version, _max_committed[gen_id]))
    prev = _recovered.get(gen_id)
    if prev is None or recovery_version < prev:
        # min over competing recoveries of the same generation (a lower
        # later choice is the binding one)
        _recovered[gen_id] = recovery_version


def max_committed(gen_id) -> int:
    return _max_committed.get(gen_id, 0)
