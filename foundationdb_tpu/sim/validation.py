"""Magic durability assertions, usable only in simulation.

Re-design of fdbrpc/sim_validation.h:20-50 (debug_advanceMaxCommittedVersion
/ debug_checkRestoredVersion): the simulator tracks, OUT OF BAND, the
highest commit version whose tlog push fully acked. Every epoch-end
recovery must pick a recovery version at or above it — a lower one would
silently discard data the cluster already acknowledged as durable. The
check is global and unconditional in sim: it rides every spec (attrition
included) for free, catching recovery-version math bugs that workload
invariants can miss (a dropped suffix of acked-but-unread writes).

Violations are RECORDED, not raised: a raise inside the master's recovery
actor would surface as just another master failure and be retried into
silence. The spec runner asserts the violation list is empty at the end of
every run (SevError semantics: any violation fails the test).
"""
from __future__ import annotations

from typing import List, Tuple

_enabled = False
_max_committed: int = 0
#: (recovery_version, max_committed_at_check) for every violation seen
violations: List[Tuple[int, int]] = []


def enable() -> None:
    """Arm the oracle (the simulator's constructor calls this)."""
    global _enabled, _max_committed
    _enabled = True
    _max_committed = 0
    violations.clear()


def disable() -> None:
    global _enabled
    _enabled = False


def advance_max_committed(version: int) -> None:
    """A commit's log-system push fully acked at `version` (the durability
    point recovery must honor). No-op outside simulation."""
    global _max_committed
    if _enabled and version > _max_committed:
        _max_committed = version


def check_restored_version(recovery_version: int) -> None:
    """An epoch-end recovery chose `recovery_version`: it must cover every
    fully-acked push (all-ack means any locked replica bounds it from
    above, so min(end) over the locked set can never be below a completed
    push — if it is, the lock/recovery math lost acknowledged data)."""
    if _enabled and recovery_version < _max_committed:
        violations.append((recovery_version, _max_committed))


def max_committed() -> int:
    return _max_committed
