"""SystemMonitor: per-process gauges -> ProcessMetrics trace events.

Re-design of flow/SystemMonitor.cpp: the reference samples each process's
CPU/memory/network/disk and emits a periodic ProcessMetrics trace event.
The simulation's analog gauges are the quantities that exist in the
simulated world: live actor count, registered handler count, the disk
footprint (durable + page-cache bytes), scheduler tasks executed since
the last sample, and reboot count — enough for the status/trace tooling
to see a hot or leaking process, which is the component's job."""
from __future__ import annotations

from ..core.trace import TraceEvent
from .loop import TaskPriority, delay


async def system_monitor(sim, interval: float = 5.0) -> None:
    """Emit one ProcessMetrics event per alive process per interval
    (spawn on the simulator: sim.start_system_monitor())."""
    last_tasks = 0
    while True:
        await delay(interval, TaskPriority.LOW)
        tasks_now = sim.sched.tasks_run
        TraceEvent("MachineMetrics").detail(
            "TasksRun", tasks_now - last_tasks).detail(
            "Processes", sum(1 for p in sim.net.processes.values() if p.alive)).log()
        last_tasks = tasks_now
        for addr, proc in sorted(sim.net.processes.items()):
            if not proc.alive:
                continue
            disk = sim.disks.get(addr)
            disk_bytes = 0
            if disk is not None:
                disk_bytes = sum(f.size() for f in disk.files.values())
            TraceEvent("ProcessMetrics", id=proc.name).detail(
                "Address", addr).detail(
                "Actors", len(proc.actors)).detail(
                "Handlers", len(proc.handlers)).detail(
                "DiskBytes", disk_bytes).detail(
                "Reboots", proc.reboots).log()
