"""Actor combinators (reference: flow/genericactors.actor.h, 1634 LoC).

The subset the transaction system actually leans on: waitForAll, quorum,
timeout, streams (PromiseStream/FutureStream), AsyncVar/AsyncTrigger,
NotifiedVersion (the version-chaining primitive the resolver and tlog use
for `whenAtLeast` sequencing), and actorCollection.
"""
from __future__ import annotations

import heapq
from collections import deque
from typing import Any, Callable, Deque, Generic, List, Optional, TypeVar

from ..core import error
from .loop import Future, Promise, Task, TaskPriority, current_scheduler, delay, never, spawn

T = TypeVar("T")


async def all_of_cancelling(tasks: List[Task]) -> List[Any]:
    """all_of, but a fail-fast error also CANCELS the sibling tasks —
    without this, the survivors keep running (committing, writing)
    underneath the caller's error handling."""
    try:
        return await all_of(tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        raise


def all_of(futures: List[Future]) -> Future:
    """Resolves with the list of values when every input resolves; errors as
    soon as any input errors (flow: waitForAll)."""
    out = Future()
    n = len(futures)
    if n == 0:
        out._set([])
        return out
    remaining = [n]

    def one(f: Future) -> None:
        if out.is_ready:
            return
        if f.is_error:
            try:
                f.get()
            except BaseException as e:
                out._set_error(e)
            return
        remaining[0] -= 1
        if remaining[0] == 0:
            out._set([x.get() for x in futures])

    for f in futures:
        f.on_ready(one)
    return out


def any_of(futures: List[Future]) -> Future:
    """Resolves with (index, value) of the first input to resolve; errors
    propagate (flow: choose/when)."""
    out = Future()

    def mk(i: int) -> Callable[[Future], None]:
        def one(f: Future) -> None:
            if out.is_ready:
                return
            if f.is_error:
                try:
                    f.get()
                except BaseException as e:
                    out._set_error(e)
            else:
                out._set((i, f.get()))
        return one

    for i, f in enumerate(futures):
        f.on_ready(mk(i))
    return out


def quorum(futures: List[Future], count: int) -> Future:
    """Resolves (None) when `count` inputs have resolved successfully; errors
    if success becomes impossible (flow: quorum)."""
    out = Future()
    state = {"ok": 0, "err": 0}
    n = len(futures)

    def one(f: Future) -> None:
        if out.is_ready:
            return
        if f.is_error:
            state["err"] += 1
            if n - state["err"] < count:
                try:
                    f.get()
                except BaseException as e:
                    out._set_error(e)
        else:
            state["ok"] += 1
            if state["ok"] >= count:
                out._set(None)

    if count <= 0:
        out._set(None)
        return out
    for f in futures:
        f.on_ready(one)
    return out


def timeout_after(f: Future, seconds: float, timeout_value: Any = None) -> Future:
    """f's result, or timeout_value if it doesn't resolve in time
    (flow: timeout)."""
    out = Future()
    t = delay(seconds)

    def on_f(x: Future) -> None:
        if out.is_ready:
            return
        if x.is_error:
            try:
                x.get()
            except BaseException as e:
                out._set_error(e)
        else:
            out._set(x.get())

    def on_t(_: Future) -> None:
        if not out.is_ready:
            out._set(timeout_value)

    f.on_ready(on_f)
    t.on_ready(on_t)
    return out


def success_of(f: Future) -> Future:
    """Discards the value (flow: success)."""
    out = Future()

    def one(x: Future) -> None:
        if x.is_error:
            try:
                x.get()
            except BaseException as e:
                out._set_error(e)
        else:
            out._set(None)

    f.on_ready(one)
    return out


def ready_or_error(f: Future) -> Future:
    """Resolves (None) when f is ready, swallowing errors (flow: errorOr /
    ready)."""
    out = Future()
    f.on_ready(lambda _: out._set(None))
    return out


class FutureStream(Generic[T]):
    """Receive end of an unbounded ordered stream
    (flow/flow.h NotifiedQueue)."""

    def __init__(self) -> None:
        self._queue: Deque[T] = deque()
        self._waiter: Optional[Future] = None
        self._closed: Optional[BaseException] = None

    def pop(self) -> Future:
        """Future of the next element."""
        f = Future()
        if self._queue:
            f._set(self._queue.popleft())
        elif self._closed is not None:
            f._set_error(self._closed)
        else:
            assert self._waiter is None or self._waiter.is_ready, (
                "one consumer at a time"
            )
            self._waiter = f
        return f

    @property
    def size(self) -> int:
        return len(self._queue)

    def is_empty(self) -> bool:
        return not self._queue


class PromiseStream(Generic[T]):
    """Send end (flow: PromiseStream<T>)."""

    def __init__(self) -> None:
        self.stream: FutureStream[T] = FutureStream()

    def send(self, value: T) -> None:
        s = self.stream
        if s._waiter is not None and not s._waiter.is_ready:
            w, s._waiter = s._waiter, None
            w._set(value)
        else:
            s._queue.append(value)

    def send_error(self, err: BaseException) -> None:
        s = self.stream
        s._closed = err
        if s._waiter is not None and not s._waiter.is_ready:
            w, s._waiter = s._waiter, None
            w._set_error(err)

    def close(self) -> None:
        self.send_error(error.end_of_stream())


class AsyncVar(Generic[T]):
    """A variable whose changes can be awaited (flow: AsyncVar<T>)."""

    def __init__(self, value: T = None):
        self._value = value
        self._change = Future()

    def get(self) -> T:
        return self._value

    def on_change(self) -> Future:
        return self._change

    def set(self, value: T) -> None:
        if value == self._value:
            return
        self._value = value
        old, self._change = self._change, Future()
        old._set(value)


class AsyncTrigger:
    """Edge trigger (flow: AsyncTrigger)."""

    def __init__(self) -> None:
        self._f = Future()

    def on_trigger(self) -> Future:
        return self._f

    def trigger(self) -> None:
        old, self._f = self._f, Future()
        old._set(None)


class NotifiedVersion:
    """Monotone value with whenAtLeast waits — the version-chaining primitive
    (reference: NotifiedVersion flow/Notified.h; used at Resolver.actor.cpp:110
    and throughout the TLog)."""

    def __init__(self, value: int = 0):
        self._value = value
        self._waiters: List = []  # heap of (threshold, seq, Future)
        self._seq = 0

    def get(self) -> int:
        return self._value

    def when_at_least(self, threshold: int) -> Future:
        if self._value >= threshold:
            f = Future()
            f._set(None)
            return f
        f = Future()
        self._seq += 1
        heapq.heappush(self._waiters, (threshold, self._seq, f))
        return f

    def set(self, value: int) -> None:
        """Fires satisfied waiters in ascending threshold order (the
        reference's priority queue, flow/Notified.h)."""
        assert value >= self._value, "NotifiedVersion may not go backwards"
        self._value = value
        while self._waiters and self._waiters[0][0] <= value:
            _, _, f = heapq.heappop(self._waiters)
            f._set(None)

    def advance(self, value: int) -> None:
        """set(max(current, value)) — for pipelines where stages may complete
        out of order but the token only gates 'at least this far'."""
        if value > self._value:
            self.set(value)


class ActorCollection:
    """Holds tasks; errors from any of them surface on `error_future`
    (reference: flow/ActorCollection.actor.cpp)."""

    def __init__(self) -> None:
        self._tasks: dict[int, Task] = {}
        self.error_future = Future()

    def add(self, task: Task) -> Task:
        self._tasks[id(task)] = task

        def done(f: Future) -> None:
            # Self-clean like the reference collection, so per-request
            # handler tasks don't accumulate over a long simulation.
            self._tasks.pop(id(task), None)
            if f.is_error and not self.error_future.is_ready:
                try:
                    f.get()
                except BaseException as e:
                    self.error_future._set_error(e)

        task.on_ready(done)
        return task

    def cancel_all(self) -> None:
        tasks, self._tasks = list(self._tasks.values()), {}
        for t in tasks:
            t.cancel()

    def __len__(self) -> int:
        return len(self._tasks)


async def recurring(fn: Callable[[], None], interval: float, priority: int = TaskPriority.DEFAULT_DELAY):
    """Call fn every `interval` seconds forever (flow: recurring)."""
    while True:
        await delay(interval, priority)
        fn()


class AsyncMutex:
    """FIFO mutex for actors (flow: FlowLock with capacity 1): serializes
    critical sections that span awaits, e.g. a durable file's
    write-then-sync cycle against a concurrent compaction."""

    def __init__(self) -> None:
        self._locked = False
        self._waiters: Deque[Promise] = deque()

    async def __aenter__(self) -> "AsyncMutex":
        if self._locked:
            p = Promise()
            self._waiters.append(p)
            await p.future
        self._locked = True
        return self

    async def __aexit__(self, *exc) -> bool:
        self._locked = False
        while self._waiters:
            p = self._waiters.popleft()
            if not p.is_set:
                p.send(None)
                break
        return False
