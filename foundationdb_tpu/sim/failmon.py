"""Failure monitor: per-address availability state consulted by every RPC.

Re-design of IFailureMonitor/SimpleFailureMonitor (fdbrpc/FailureMonitor.h:81,
fdbrpc/FailureMonitor.actor.cpp). One monitor per simulated world; sources of
state:

  * process death/reboot (the sim's TCP-reset analog — peers learn instantly,
    as broken connections do in Sim2),
  * the cluster controller's heartbeat failure detector
    (ClusterController.actor.cpp:1314 failureDetectionServer), which marks
    partitioned-but-alive processes failed so stranded requests error out
    instead of hanging forever (round-1 VERDICT weak #4/#6).

The network consults the monitor on every request: a request against a
failed address errors immediately; a request outstanding when the address
turns failed errors with request_maybe_delivered — exactly the semantics the
proxy's commit_unknown_result path and the client's retry loop already
absorb.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..core import error
from .loop import Future


class _Watch:
    """Cancellable registration; fires once when the address is failed."""

    __slots__ = ("cb", "active")

    def __init__(self, cb: Callable[[], None]):
        self.cb = cb
        self.active = True

    def cancel(self) -> None:
        self.active = False


class FailureMonitor:
    """Per-address boolean availability with awaitable transitions."""

    def __init__(self) -> None:
        self._failed: Dict[str, bool] = {}
        self._fail_watches: Dict[str, List[_Watch]] = {}
        self._ok_futures: Dict[str, List[Future]] = {}

    def is_failed(self, address: str) -> bool:
        return self._failed.get(address, False)

    def set_status(self, address: str, failed: bool) -> None:
        if self._failed.get(address, False) == failed:
            return
        self._failed[address] = failed
        if failed:
            watches = self._fail_watches.pop(address, [])
            for w in watches:
                if w.active:
                    w.cb()
        else:
            for f in self._ok_futures.pop(address, []):
                if not f.is_ready:
                    f._set(None)

    def on_failed(self, address: str, cb: Callable[[], None]) -> Optional[_Watch]:
        """Register cb to fire when address turns failed. Fires immediately
        (returning None) if it already is."""
        if self.is_failed(address):
            cb()
            return None
        w = _Watch(cb)
        self._fail_watches.setdefault(address, []).append(w)
        # Opportunistic compaction so long-lived addresses with heavy request
        # traffic don't accumulate dead registrations.
        lst = self._fail_watches[address]
        if len(lst) > 64 and sum(1 for x in lst if x.active) * 2 < len(lst):
            self._fail_watches[address] = [x for x in lst if x.active]
        return w

    def when_ok(self, address: str) -> Future:
        """Future resolving when address is (back) available."""
        f = Future()
        if not self.is_failed(address):
            f._set(None)
        else:
            self._ok_futures.setdefault(address, []).append(f)
        return f
