"""The simulator: machines, datacenters, kill/reboot/clog APIs.

Re-design of ISimulator/Sim2 (fdbrpc/simulator.h:35-316). One Simulator owns
the scheduler, the network, the process/machine/DC topology and the fault
APIs that anti-quiescence workloads (attrition, clogging) drive. A process
carries an optional boot function so reboots restart its roles, mirroring
simulatedFDBDRebooter (SimulatedCluster.actor.cpp:198).
"""
from __future__ import annotations

import enum
from typing import Any, Callable, Coroutine, Dict, List, Optional

from ..core import buggify
from .disk import SimDisk
from .loop import Scheduler, TaskPriority, set_scheduler
from .network import SimNetwork, SimProcess


class KillType(enum.IntEnum):
    """reference: ISimulator::KillType (simulator.h:40)."""

    KILL_INSTANTLY = 0
    INJECT_FAULTS = 1
    REBOOT_AND_DELETE = 2
    REBOOT = 3


BootFn = Callable[["Simulator", SimProcess], Coroutine]


class Simulator:
    """Deterministic world: everything hangs off one seed."""

    def __init__(self, seed: int = 0, randomize_knobs: bool = False):
        self.seed = seed
        self.sched = Scheduler(seed)
        self.net = SimNetwork(self.sched)
        buggify.enable(self.sched.rng)
        from . import validation

        validation.enable()
        from .. import fault

        fault.reset_registry()
        from ..core import telemetry

        telemetry.reset()
        if randomize_knobs:
            from ..core import knobs
            knobs.randomize_all(self.sched.rng)
        # span collection follows the knob (never force-disabled here: a
        # harness may have enabled collection before building its sim)
        from ..core.knobs import SERVER_KNOBS
        from ..core.trace import g_spans

        if float(getattr(SERVER_KNOBS, "trace_span_sample_rate", 0.0)) > 0:
            g_spans.enabled = True
        self.machines: Dict[str, List[SimProcess]] = {}
        #: address -> its disk; survives kills and reboots (the platters)
        self.disks: Dict[str, SimDisk] = {}
        self._boot_fns: Dict[str, BootFn] = {}
        self._next_addr = 0
        set_scheduler(self.sched)

    def disk_for(self, address: str) -> SimDisk:
        d = self.disks.get(address)
        if d is None:
            d = self.disks[address] = SimDisk(self.sched)
        return d

    # -- topology -------------------------------------------------------------
    def new_process(
        self,
        name: str = "",
        machine_id: Optional[str] = None,
        dc_id: str = "dc0",
        boot_fn: Optional[BootFn] = None,
    ) -> SimProcess:
        self._next_addr += 1
        addr = f"1.0.0.{self._next_addr}:1"
        machine_id = machine_id or f"m{self._next_addr}"
        proc = SimProcess(addr, machine_id, dc_id, name or f"proc{self._next_addr}")
        self.net.add_process(proc)
        self.machines.setdefault(machine_id, []).append(proc)
        if boot_fn is not None:
            self._boot_fns[addr] = boot_fn
            self.boot(proc)
        return proc

    def boot(self, proc: SimProcess) -> None:
        fn = self._boot_fns.get(proc.address)
        if fn is not None:
            proc.actors.add(self.sched.spawn(fn(self, proc), name=f"boot:{proc.name}"))

    # -- fault injection (simulator.h:147-155) --------------------------------
    def kill_process(self, proc: SimProcess, kill_type: KillType = KillType.KILL_INSTANTLY) -> None:
        if not proc.alive:
            return
        proc.alive = False
        proc.handlers.clear()
        proc.actors.cancel_all()
        # Peers learn of the death the way Sim2 peers do — broken connections
        # (instant), mirrored here as failure-monitor state; marking the
        # address failed also errors every outstanding reply against it.
        self.net.monitor.set_status(proc.address, True)
        # The page cache dies with the process: un-synced writes are
        # randomly applied / lost / torn (AsyncFileNonDurable semantics).
        disk = self.disks.get(proc.address)
        if disk is not None:
            disk.crash(self.sched.rng)
        if kill_type in (KillType.REBOOT, KillType.REBOOT_AND_DELETE):
            if kill_type == KillType.REBOOT_AND_DELETE:
                proc.globals.clear()
                self.disks.pop(proc.address, None)
            reboot_delay = 0.5 + self.sched.rng.random01()

            def do_boot() -> None:
                proc.alive = True
                proc.reboots += 1
                self.net.monitor.set_status(proc.address, False)
                self.boot(proc)

            self.sched.at(self.sched.time + reboot_delay, do_boot, TaskPriority.DEFAULT_DELAY)

    def revive_process(self, proc: SimProcess) -> None:
        """Boot a process previously killed with KILL_INSTANTLY (targeted
        down-then-up scenarios; the reference's workloads drive the same
        through reboot requests after a delay)."""
        if proc.alive:
            return
        proc.alive = True
        proc.reboots += 1
        self.net.monitor.set_status(proc.address, False)
        self.boot(proc)

    def kill_machine(self, machine_id: str, kill_type: KillType = KillType.KILL_INSTANTLY) -> None:
        for proc in self.machines.get(machine_id, []):
            self.kill_process(proc, kill_type)

    def clog_pair(self, a: SimProcess, b: SimProcess, seconds: float) -> None:
        self.net.clog_pair(a.address, b.address, seconds)

    def clog_process(self, proc: SimProcess, seconds: float) -> None:
        """Clog every link touching proc (RandomClogging workload's move)."""
        for other in self.net.processes.values():
            if other.address != proc.address:
                self.net.clog_pair(proc.address, other.address, seconds)

    def start_system_monitor(self, interval: float = 5.0):
        """Spawn the per-process gauge sampler (flow/SystemMonitor.cpp's
        role); returns the task."""
        from .system_monitor import system_monitor

        return self.sched.spawn(system_monitor(self, interval),
                                TaskPriority.LOW, name="systemMonitor")

    # -- running --------------------------------------------------------------
    def run(self, until: Optional[float] = None) -> None:
        self.sched.run(until=until)

    def run_until(self, fut, until: Optional[float] = None) -> Any:
        return self.sched.run_until(fut, until=until)
