"""Futures, promises and the deterministic cooperative scheduler.

TPU-first re-design of the reference's flow runtime: instead of a C# actor
compiler generating state machines from ACTOR functions (flow/actorcompiler/),
plain Python coroutines play the actor role and a virtual-time scheduler
plays Sim2's ordered task queue (fdbrpc/sim2.actor.cpp:1518-1571). The
observable semantics we keep from the reference:

  * single-assignment futures with intrusive callback chains
    (SAV<T>, flow/flow.h:347-480)
  * a global task-priority ladder; ready tasks run in
    (time, priority, insertion-order) order (flow/network.h:30-76)
  * virtual time only advances when the ready queue drains
  * errors are values (flow/Error.h); awaiting a failed future raises

No threads anywhere: determinism comes from cooperative scheduling, exactly
like the reference (SURVEY.md §5 "race detection").
"""
from __future__ import annotations

import enum
import heapq
from typing import Any, Callable, Coroutine, List, Optional

import time as _wall

from ..core import error
from ..core.error import FDBError
from ..core.rng import DeterministicRandom

SimError = FDBError


class TaskPriority(enum.IntEnum):
    """Scheduling priorities (reference: flow/network.h:30-76). Higher runs
    first at equal virtual time."""

    MAX = 1_000_000
    RUN_LOOP = 30_000
    COORDINATION_REPLY = 8810
    COORDINATION = 8800
    FAILURE_MONITOR = 8700
    RESOLUTION_METRICS = 8700
    CLUSTER_CONTROLLER = 8650
    PROXY_COMMIT_DISPATCH = 8640
    MASTER_TLOG_REJOIN = 8646
    PROXY_STORAGE_REJOIN = 8645
    TLOG_QUEUING_METRICS = 8620
    TLOG_POP = 8610
    TLOG_PEEK_REPLY = 8600
    TLOG_PEEK = 8590
    TLOG_COMMIT_REPLY = 8580
    TLOG_COMMIT = 8570
    PROXY_GET_RAW_COMMITTED_VERSION = 8565
    PROXY_RESOLVER_REPLY = 8560
    PROXY_COMMIT_BATCHER = 8550
    PROXY_COMMIT = 8540
    TLOG_CONFIRM_RUNNING_REPLY = 8530
    TLOG_CONFIRM_RUNNING = 8520
    PROXY_GRV_TIMER = 8510
    GET_CONSISTENT_READ_VERSION = 8500
    DEFAULT_PROMISE_ENDPOINT = 8000
    DEFAULT_ON_MAIN_THREAD = 7500
    DEFAULT_ENDPOINT = 7000
    UNKNOWN_ENDPOINT = 6500
    FETCH_KEYS = 3560
    MOVE_KEYS = 3550
    DATA_DISTRIBUTION_LAUNCH = 3530
    RATEKEEPER = 3510
    DATA_DISTRIBUTION = 3500
    STORAGE = 3000
    DEFAULT_DELAY = 7010
    DEFAULT_YIELD = 7990
    UPDATE_STORAGE = 3000
    LOW = 2000
    MIN = 1000
    ZERO = 0


class Future:
    """Single-assignment value-or-error with callbacks (flow/flow.h SAV)."""

    __slots__ = ("_ready", "_value", "_error", "_callbacks")

    def __init__(self) -> None:
        self._ready = False
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._callbacks: List[Callable[["Future"], None]] = []

    # -- inspection ---------------------------------------------------------
    @property
    def is_ready(self) -> bool:
        return self._ready

    @property
    def is_error(self) -> bool:
        return self._ready and self._error is not None

    def get(self) -> Any:
        assert self._ready, "future not ready"
        if self._error is not None:
            raise self._error
        return self._value

    # -- assignment ---------------------------------------------------------
    def _set(self, value: Any) -> None:
        assert not self._ready, "future already set"
        self._ready = True
        self._value = value
        self._fire()

    def _set_error(self, err: BaseException) -> None:
        assert not self._ready, "future already set"
        self._ready = True
        self._error = err
        self._fire()

    def _fire(self) -> None:
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def on_ready(self, cb: Callable[["Future"], None]) -> None:
        """Fires immediately if already ready (callback chain semantics)."""
        if self._ready:
            cb(self)
        else:
            self._callbacks.append(cb)

    def remove_callback(self, cb: Callable[["Future"], None]) -> None:
        """Deregister a pending callback (flow's Callback::remove) — lets a
        race loser detach from a long-lived future instead of leaking."""
        try:
            self._callbacks.remove(cb)
        except ValueError:
            pass

    # -- await protocol -----------------------------------------------------
    def __await__(self):
        if not self._ready:
            yield self
        return self.get()


class Promise:
    """The write end of a Future (flow/flow.h Promise<T>)."""

    __slots__ = ("future",)

    def __init__(self) -> None:
        self.future = Future()

    def send(self, value: Any = None) -> None:
        self.future._set(value)

    def send_error(self, err: BaseException) -> None:
        self.future._set_error(err)

    @property
    def is_set(self) -> bool:
        return self.future._ready

    def break_promise(self) -> None:
        if not self.future._ready:
            self.future._set_error(error.broken_promise())


_READY_FUTURE = None


def ready_future(value: Any = None) -> Future:
    f = Future()
    f._set(value)
    return f


def error_future(err: BaseException) -> Future:
    f = Future()
    f._set_error(err)
    return f


class Task(Future):
    """A spawned coroutine; itself a Future of the coroutine's return value.
    The analog of an ACTOR's implicit return future."""

    __slots__ = ("_coro", "_sched", "priority", "_cancelled", "name")

    def __init__(self, coro: Coroutine, sched: "Scheduler", priority: int, name: str = ""):
        super().__init__()
        self._coro = coro
        self._sched = sched
        self.priority = priority
        self._cancelled = False
        self.name = name or getattr(coro, "__name__", "task")

    def cancel(self) -> None:
        """Cancel the actor (reference: actor_cancelled on future drop)."""
        if self._ready or self._cancelled:
            return
        self._cancelled = True
        if _current is not self._sched:
            # The world has been torn down (set_scheduler(None) after a
            # finished simulation) or belongs to another simulation: drop
            # the coroutine without running its cancellation path, which
            # could touch the dead scheduler.
            try:
                self._coro.close()
            except RuntimeError:
                pass
            self._finish_error(error.operation_cancelled())
            return
        try:
            self._coro.throw(error.operation_cancelled())
            # The coroutine swallowed the cancellation and awaited again.
            # Actors may not wait during cancellation (the reference's
            # actor-compiler enforces this); force it closed.
            self._coro.close()
        except StopIteration as stop:
            self._finish_value(stop.value)
        except error.OperationCancelled as e:
            self._finish_error(e)
        except FDBError as e:
            self._finish_error(e)
        except (RuntimeError, ValueError):
            # RuntimeError: already closed, or ignored GeneratorExit.
            # ValueError: "coroutine already executing" — an actor cancelled
            # itself (e.g. a role's shutdown() cancelling its own actor
            # collection mid-handler); it finishes its current synchronous
            # stretch, then _step's _cancelled guard parks it forever.
            pass
        finally:
            # Whatever happened above, the task is finished now.
            self._finish_error(error.operation_cancelled())

    def _finish_value(self, v: Any) -> None:
        if not self._ready:
            self._set(v)

    def _finish_error(self, e: BaseException) -> None:
        if not self._ready:
            self._set_error(e)

    def _step(self, fut: Optional[Future]) -> None:
        """Advance the coroutine one hop (deliver fut's value/error)."""
        if self._ready or self._cancelled:
            return
        try:
            if fut is not None and fut.is_error:
                try:
                    fut.get()
                except BaseException as e:
                    waited = self._coro.throw(e)
            else:
                waited = self._coro.send(None)
        except StopIteration as stop:
            self._finish_value(stop.value)
            return
        except error.OperationCancelled as e:
            self._finish_error(e)
            return
        except FDBError as e:
            self._finish_error(e)
            return
        # The coroutine yielded a Future it is waiting on.
        assert isinstance(waited, Future), f"actors may only await Futures, got {waited!r}"
        waited.on_ready(lambda f: self._sched._schedule_step(self, f, self.priority))


class Scheduler:
    """Deterministic virtual-time run loop (Sim2's task queue,
    sim2.actor.cpp:1518-1571). Ties break (time, -priority, seq)."""

    def __init__(self, seed: int = 0, start_time: float = 0.0):
        self.time = start_time
        self.rng = DeterministicRandom(seed)
        self._queue: List = []  # (time, -priority, seq, fn)
        self._seq = 0
        self._stopped = False
        self.tasks_run = 0
        #: slow-task profiling (flow/Profiler.actor.cpp's slow-task side):
        #: a single cooperative step burning more WALL time than this
        #: blocks the whole world — trace it. 0 disables.
        self.slow_task_threshold: float = 0.0
        self.slow_tasks: List = []   # (virtual_time, wall_seconds, fn_name)

    # -- core queue ---------------------------------------------------------
    def at(self, when: float, fn: Callable[[], None], priority: int = TaskPriority.DEFAULT_DELAY) -> None:
        assert when >= self.time
        self._seq += 1
        heapq.heappush(self._queue, (when, -int(priority), self._seq, fn))

    def _schedule_step(self, task: Task, fut: Optional[Future], priority: int) -> None:
        self.at(self.time, lambda: task._step(fut), priority)

    # -- public api ---------------------------------------------------------
    def spawn(self, coro: Coroutine, priority: int = TaskPriority.DEFAULT_YIELD, name: str = "") -> Task:
        t = Task(coro, self, int(priority), name)
        self._schedule_step(t, None, int(priority))
        return t

    def delay(self, seconds: float, priority: int = TaskPriority.DEFAULT_DELAY) -> Future:
        f = Future()
        self.at(self.time + max(seconds, 0.0), lambda: (not f.is_ready) and f._set(None), priority)
        return f

    def stop(self) -> None:
        self._stopped = True

    def run(self, until: Optional[float] = None, max_tasks: Optional[int] = None) -> None:
        """Run until the queue drains, `until` virtual seconds pass, or
        max_tasks events execute."""
        self._stopped = False
        while self._queue and not self._stopped:
            when, negp, seq, fn = self._queue[0]
            if until is not None and when > until:
                self.time = until
                return
            heapq.heappop(self._queue)
            self.time = when
            self.tasks_run += 1
            if self.slow_task_threshold > 0.0:
                t0 = _wall.perf_counter()
                fn()
                dt = _wall.perf_counter() - t0
                if dt >= self.slow_task_threshold:
                    self._trace_slow_task(dt, fn)
            else:
                fn()
            if max_tasks is not None and self.tasks_run >= max_tasks:
                return

    def _trace_slow_task(self, wall_seconds: float, fn) -> None:
        """Record + trace a cooperative step that hogged the (real) CPU —
        the deterministic world's analog of the reference's SlowTask
        profiling (FLOW_KNOBS->SLOWTASK_PROFILING_*): one long step stalls
        every simulated process at once."""
        name = getattr(fn, "__qualname__", None) or repr(fn)
        closure = getattr(fn, "__closure__", None)
        code = getattr(fn, "__code__", None)
        if closure and code is not None:
            # the step lambda closes over the RUNNING Task as 'task'; it
            # may also close over 'fut' — which is itself a Task when the
            # step resumed from awaiting one, so match cells by freevar
            # name rather than taking the first Task-typed cell (cells
            # are ordered alphabetically: 'fut' would win)
            for var, cell in zip(code.co_freevars, closure):
                try:
                    obj = cell.cell_contents
                except ValueError:
                    continue   # unbound cell: a crash here would abort
                    #            the whole run loop for a LOG line
                if var == "task" and isinstance(obj, Task):
                    name = f"task:{obj.name}"
                    break
        self.slow_tasks.append((self.time, wall_seconds, name))
        del self.slow_tasks[:-100]
        from ..core.trace import TraceEvent

        TraceEvent("SlowTask").detail("WallSeconds", round(wall_seconds, 4)).detail(
            "Fn", name).log()

    def run_until(self, fut: Future, until: Optional[float] = None) -> Any:
        """Drive the loop until `fut` resolves; returns its value."""
        fut.on_ready(lambda _: self.stop())
        self.run(until=until)
        if not fut.is_ready:
            raise error.timed_out(f"future unresolved at t={self.time}")
        return fut.get()


# -- module-level conveniences (the g_network pattern) -----------------------

_current: Optional[Scheduler] = None


def set_scheduler(s: Optional[Scheduler]) -> None:
    global _current
    _current = s


def current_scheduler() -> Scheduler:
    assert _current is not None, "no Scheduler active (call set_scheduler)"
    return _current


def now() -> float:
    return current_scheduler().time


def delay(seconds: float, priority: int = TaskPriority.DEFAULT_DELAY) -> Future:
    return current_scheduler().delay(seconds, priority)


def yield_now(priority: int = TaskPriority.DEFAULT_YIELD) -> Future:
    """Re-queue at current time (flow: yield())."""
    return current_scheduler().delay(0.0, priority)


def spawn(coro: Coroutine, priority: int = TaskPriority.DEFAULT_YIELD, name: str = "") -> Task:
    return current_scheduler().spawn(coro, priority, name)


def never() -> Future:
    return Future()
