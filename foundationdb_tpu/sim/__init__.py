"""Deterministic simulation runtime.

The reference's single most load-bearing design decision (SURVEY.md §1, §4)
is that flow/ + fdbrpc/ virtualize the entire world — time, network, disk,
randomness — behind one seam (INetwork / ISimulator), making a whole
multi-datacenter cluster simulable deterministically inside one process.
This package is the TPU framework's version of that seam:

  loop.py       Future/Promise + cooperative scheduler with virtual time and
                task priorities (flow/flow.h, flow/network.h:30-76, Net2/Sim2)
  actors.py     combinator library (flow/genericactors.actor.h)
  network.py    token-addressed endpoints + simulated message bus with
                latency/clogging/partitions (fdbrpc/FlowTransport, Sim2Conn)
  simulator.py  processes/machines/DCs, kill/reboot/clog APIs
                (fdbrpc/simulator.h:35-316)

Determinism contract: given a seed, every run produces the identical event
sequence. All scheduling ties break on (virtual time, -priority, insertion
seq); all randomness flows from one DeterministicRandom; TPU/JAX calls are
dispatched from exactly one logical queue.
"""
from .loop import (
    Future,
    Promise,
    Scheduler,
    SimError,
    Task,
    TaskPriority,
    current_scheduler,
    delay,
    never,
    now,
    spawn,
    yield_now,
)

__all__ = [
    "Future", "Promise", "Scheduler", "SimError", "Task", "TaskPriority",
    "current_scheduler", "delay", "never", "now", "spawn", "yield_now",
]
