"""ResolverPipeline: windowed multi-batch in-flight conflict resolution.

The serial resolve() path synchronizes the host on every batch: pack, run
the device program, BLOCK on the verdicts, repeat — the device idles while
the host packs and the host idles while the device runs. Harmonia (arxiv
1904.08964) and SmartNIC ordered-KV offloads (arxiv 2601.06231) get
near-linear throughput from the same hardware by keeping the offload
deeply pipelined with several requests in flight; this is that pipeline
for the TPU resolver:

  * submit() packs a batch on the host (inline or on a thread-pool
    executor) while the PREVIOUS batch's device program is still running,
    then dispatches via JAX async dispatch — nothing is forced;
  * at most `depth` dispatched batches stay un-forced (double buffering at
    depth 2, triple at 3); submit() forces the oldest beyond that, so the
    window also bounds host memory and staleness;
  * results are forced strictly in submission (= commit-version) order, so
    abort sets are bit-identical to the serial path: the device programs
    run in the same order on the same device queue either way, only the
    host's blocking points move.

Depth 1 degenerates to the serial path (each batch is forced before the
next is packed). Engines without the columnar pack/dispatch split (the
oracle, the native C++ engine) fall back to synchronous resolve() per
batch — the pipeline still preserves ordering, it just cannot overlap.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..core.trace import g_spans, span_event, span_now
from ..core.types import CommitTransaction, TransactionCommitResult, Version

#: submit-side states of a PendingResolve
_PACKING, _DISPATCHED, _DONE = 0, 1, 2


class BudgetBatcher:
    """Budget-driven batch sizing over a bucketed kernel ladder.

    Replaces the static production-point choice (a batch size picked once
    from an offline latency curve) with an adaptive target: an EWMA of the
    OBSERVED per-bucket service latency predicts what a client would see
    with `depth` batches in flight — pack(T) + depth * device(T) — and the
    batcher targets the largest ladder bucket whose prediction fits the
    `resolver_p99_budget_ms` knob. Under the fault path's depth collapse
    (pipeline/service.py: a degraded engine serves at depth 1 through
    watchdog retries or the CPU failover oracle) the EWMA balloons and the
    target degrades toward the smallest bucket; a degraded engine is
    additionally clamped there outright.

    Shared by the wall-clock ResolverPipeline (observing force() wall
    times) and the sim PipelinedResolverService (observing virtual-time
    service delays); seed_ms pre-loads bench-measured device times so the
    first batches are not sized blind.

    EWMAs are keyed per (bucket, history-search mode, dispatch mode): the
    two kernel history paths (docs/perf.md "History search modes") have
    genuinely different device-time floors for the same bucket shape, and
    so do the two DISPATCH paths — step dispatch pays a per-batch
    launch+force round trip the device-resident loop (docs/perf.md
    "Device-resident loop") does not — so flipping either axis (knob
    change, engine rebuild under a different pick, enabling the device
    loop) must never poison the other key's estimate. `bucket_modes` maps
    each bucket to its engine's resolved search mode
    (RoutedConflictEngineBase.history_search_modes()); unmapped buckets
    default to "fused_sort", the pre-ladder behavior. `dispatch_mode` is
    the engine family's serving path ("step" | "loop" | "mesh"), one
    value per batcher (an engine serves through exactly one at a time) —
    a multi-device mesh batch carries collective time a single-chip step
    never pays, so its estimates file under their own key too."""

    def __init__(self, ladder: Sequence[int], budget_ms: Optional[float] = None,
                 pack_ms_per_txn: float = 0.0, alpha: Optional[float] = None,
                 seed_ms: Optional[Dict[int, float]] = None,
                 bucket_modes: Optional[Dict[int, str]] = None,
                 dispatch_mode: str = "step"):
        from ..core.knobs import SERVER_KNOBS

        self.ladder = sorted(set(int(t) for t in ladder))
        if not self.ladder:
            raise ValueError("BudgetBatcher needs a non-empty bucket ladder")
        self.budget_ms = (float(SERVER_KNOBS.resolver_p99_budget_ms)
                          if budget_ms is None else float(budget_ms))
        self.pack_ms_per_txn = pack_ms_per_txn
        self.alpha = (float(SERVER_KNOBS.resolver_latency_ewma_alpha)
                      if alpha is None else float(alpha))
        self.bucket_modes: Dict[int, str] = {
            int(t): str(m) for t, m in (bucket_modes or {}).items()}
        self.dispatch_mode = str(dispatch_mode)
        #: (bucket, search mode, dispatch mode) -> EWMA of observed ms
        self.ewma_ms: Dict[Tuple[int, str, str], float] = {
            self.key_of(int(t)): float(v) for t, v in (seed_ms or {}).items()}
        # unified telemetry (core/telemetry.py): the per-bucket EWMAs the
        # whole cluster steers by become persistable TDMetric series
        from ..core import telemetry

        telemetry.hub().register_batcher(self)

    def mode_of(self, bucket: int) -> str:
        """The history-search mode a bucket's observations file under."""
        return self.bucket_modes.get(bucket, "fused_sort")

    def key_of(self, bucket: int, mode: Optional[str] = None) -> tuple:
        """The full EWMA key a bucket's observations file under."""
        return (bucket, mode if mode is not None else self.mode_of(bucket),
                self.dispatch_mode)

    def set_bucket_modes(self, modes: Dict[int, str]) -> None:
        """Adopt an engine's resolved per-bucket modes. A seed recorded
        under a bucket's PREVIOUS mode migrates iff the new mode has no
        estimate of its own — a seed is 'this bucket's best prior', while
        a real observation under the old mode stays where it belongs."""
        for t, m_new in modes.items():
            t = int(t)
            m_old = self.mode_of(t)
            self.bucket_modes[t] = str(m_new)
            old_key, new_key = self.key_of(t, m_old), self.key_of(t, str(m_new))
            if old_key != new_key and old_key in self.ewma_ms \
                    and new_key not in self.ewma_ms:
                self.ewma_ms[new_key] = self.ewma_ms.pop(old_key)

    def set_dispatch_mode(self, dispatch: str) -> None:
        """Adopt an engine family's dispatch path ("step" | "loop" |
        "mesh") —
        mirrors set_bucket_modes: seeds filed under the previous dispatch
        mode migrate iff the new key has no estimate, so enabling the
        device loop starts from the prior without ever overwriting a real
        step-path observation (and vice versa on failover back to step)."""
        old = self.dispatch_mode
        self.dispatch_mode = str(dispatch)
        if old == self.dispatch_mode:
            return
        for (t, m, d), v in list(self.ewma_ms.items()):
            if d != old:
                continue
            new_key = (t, m, self.dispatch_mode)
            if new_key not in self.ewma_ms:
                self.ewma_ms[new_key] = v

    def bucket_of(self, n_txns: int) -> int:
        """Smallest ladder bucket holding an n_txns batch (top if none)."""
        for t in self.ladder:
            if n_txns <= t:
                return t
        return self.ladder[-1]

    def observe(self, bucket: int, service_ms: float,
                mode: Optional[str] = None) -> None:
        key = self.key_of(bucket, mode)
        cur = self.ewma_ms.get(key)
        self.ewma_ms[key] = (service_ms if cur is None
                             else cur + self.alpha * (service_ms - cur))

    def predicted_ms(self, bucket: int, depth: int,
                     mode: Optional[str] = None) -> Optional[float]:
        """Client-visible latency estimate at `depth` in flight: own pack +
        up to `depth` device services ahead of the verdict (the in-order
        device chain). None until the (bucket, mode) has an observation."""
        dev = self.ewma_ms.get(self.key_of(bucket, mode))
        if dev is None:
            return None
        return self.pack_ms_per_txn * bucket + max(1, depth) * dev

    def target_batch_txns(self, depth: int, degraded: bool = False) -> int:
        """The adaptive production point: largest bucket predicted to fit
        the budget. Unobserved buckets don't qualify (never size batches on
        guesses); if nothing fits — or the engine is degraded — the
        smallest bucket wins (minimum service quantum, fastest drain)."""
        if degraded:
            return self.ladder[0]
        best = None
        for t in self.ladder:
            p = self.predicted_ms(t, depth)
            if p is not None and p <= self.budget_ms:
                best = t
        return best if best is not None else self.ladder[0]

    def as_dict(self) -> dict:
        return {
            "ladder": list(self.ladder),
            "budget_ms": self.budget_ms,
            "pack_ms_per_txn": round(self.pack_ms_per_txn, 6),
            "bucket_modes": {str(t): m
                             for t, m in sorted(self.bucket_modes.items())},
            "dispatch_mode": self.dispatch_mode,
            "ewma_ms": {f"{t}:{m}:{d}": round(v, 4)
                        for (t, m, d), v in sorted(self.ewma_ms.items())},
        }


class PendingResolve:
    """Handle for one submitted batch; result() forces it (and every
    earlier in-flight batch first — commit-version order)."""

    __slots__ = ("pipeline", "version", "n_txns", "_state", "_pack",
                 "_force", "_result", "_error", "_txns", "_buckets")

    def __init__(self, pipeline: "ResolverPipeline", version: Version, n_txns: int):
        self.pipeline = pipeline
        self.version = version
        self.n_txns = n_txns
        self._state = _PACKING
        self._pack = None          # future/immediate of columnar_pack's plan
        self._force = None         # engine.columnar_dispatch force fn
        self._result: Optional[List[TransactionCommitResult]] = None
        self._error: Optional[BaseException] = None
        self._txns = None
        self._buckets = None       # plan chunk buckets (BudgetBatcher feed)

    @property
    def is_done(self) -> bool:
        return self._state == _DONE

    def result(self) -> List[TransactionCommitResult]:
        self.pipeline._force_through(self)
        if self._error is not None:
            raise self._error
        return self._result


class _Immediate:
    """Executor-future shim for inline packing."""

    __slots__ = ("_value", "_exc")

    def __init__(self, fn, *args):
        self._value = None
        self._exc = None
        try:
            self._value = fn(*args)
        except BaseException as e:   # re-raised at dispatch, like a Future
            self._exc = e

    def result(self):
        if self._exc is not None:
            raise self._exc
        return self._value


class ResolverPipeline:
    """Single-producer pipeline over one conflict engine.

    `depth`    — max dispatched-but-unforced batches in flight (>= 1).
    `executor` — optional concurrent.futures.Executor; when given, the
                 host pack of batch i+1 runs on it while the main thread
                 returns from submit() and the device runs batch i.
    """

    def __init__(self, engine, depth: int = 2, executor=None,
                 batcher: Optional[BudgetBatcher] = None,
                 transport_degraded_fn=None, conflict_sched=None):
        assert depth >= 1
        self.engine = engine
        self.depth = depth
        #: optional ConflictScheduler (pipeline/scheduler.py) to train on
        #: every forced batch's verdicts: the wall-clock pipeline is the
        #: resolution point, so its feedback keeps the admission-side doom
        #: model current whichever layer did the scheduling
        self.conflict_sched = conflict_sched
        #: optional transport-health probe (RealNetwork.transport_degraded):
        #: while it reports True the pipeline collapses to depth 1, exactly
        #: as it does for a degraded ResilientEngine — keeping batches in
        #: flight across a flapping link only multiplies the replay/requeue
        #: work when it resets (docs/real_cluster.md)
        self._transport_degraded_fn = transport_degraded_fn
        self._executor = executor
        #: batches in submission order, any mix of states; DONE batches are
        #: popped from the left as the window advances
        self._queue: deque = deque()
        self._can_overlap = hasattr(engine, "columnar_pack")
        #: budget-driven batch sizing: when set, force() wall times feed the
        #: per-(bucket, mode) EWMA and suggested_batch_txns() tracks the
        #: largest in-budget bucket (callers size their submissions to it)
        self.batcher = batcher
        if batcher is not None and hasattr(engine, "history_search_modes"):
            # the engine is the authority on which history-search mode each
            # bucket's compiled program traces; observations file under it
            batcher.set_bucket_modes(engine.history_search_modes())
        if batcher is not None:
            # likewise for the dispatch path (step vs device loop): keyed
            # so enabling the loop never poisons the step path's estimates
            batcher.set_dispatch_mode(getattr(engine, "dispatch_mode", "step"))

    @property
    def degraded(self) -> bool:
        """Engine-degraded OR transport-degraded: either collapses depth."""
        if getattr(self.engine, "degraded", False):
            return True
        fn = self._transport_degraded_fn
        return bool(fn()) if fn is not None else False

    @property
    def effective_depth(self) -> int:
        """`depth` while healthy; 1 while the engine or the transport is
        degraded (mirrors pipeline/service.py's engine-side collapse)."""
        return 1 if self.degraded else self.depth

    def suggested_batch_txns(self) -> Optional[int]:
        if self.batcher is None:
            return None
        return self.batcher.target_batch_txns(
            self.effective_depth, degraded=self.degraded)

    @property
    def in_flight(self) -> int:
        return sum(1 for pb in self._queue if not pb.is_done)

    def submit(self, transactions: Sequence[CommitTransaction], now: Version,
               new_oldest: Version) -> PendingResolve:
        """Accept one batch at commit version `now`. Batches MUST be
        submitted in ascending version order (the resolver's version chain
        guarantees it)."""
        # 1. Dispatch every earlier batch first: packing reads the engine's
        #    base/oldest bookkeeping, which the earlier dispatch advances.
        self._dispatch_pending()
        # 2. Window backpressure: force the oldest beyond depth-1 so this
        #    batch's dispatch keeps at most `effective_depth` un-forced
        #    (1 while the engine or transport is degraded).
        while self.in_flight >= self.effective_depth:
            self._force_oldest()
        pb = PendingResolve(self, now, len(transactions))
        if not self._can_overlap:
            # Opaque engine: synchronous resolve, still in version order.
            try:
                pb._result = self.engine.resolve(transactions, now, new_oldest)
            except BaseException as e:
                pb._error = e
            pb._state = _DONE
            self._observe(pb, list(transactions))
            self._queue.append(pb)
            return pb
        if self._executor is not None:
            pb._pack = self._executor.submit(
                self.engine.columnar_pack, list(transactions), now, new_oldest)
        else:
            pb._pack = _Immediate(
                self.engine.columnar_pack, list(transactions), now, new_oldest)
        # Fallback batches need the raw transactions at dispatch time.
        pb._txns = (list(transactions), now, new_oldest)
        self._queue.append(pb)
        return pb

    def drain(self) -> None:
        """Force everything in flight (e.g. before an engine clear())."""
        while self._queue:
            self._force_oldest()

    # -- internals ----------------------------------------------------------
    def _dispatch_pending(self) -> None:
        for pb in self._queue:
            if pb._state == _PACKING:
                self._dispatch(pb)

    def _dispatch(self, pb: PendingResolve) -> None:
        try:
            plan = pb._pack.result()
        except BaseException as e:
            pb._error = e
            pb._state = _DONE
            return
        pb._pack = None
        if plan is None:
            # Range rows / long keys: the general router path is
            # synchronous and may couple with the host long-key tier —
            # force everything earlier, then resolve inline.
            for other in self._queue:
                if other is pb:
                    break
                self._force(other)
            txns, now, new_oldest = pb._txns
            try:
                pb._result = self.engine.resolve(txns, now, new_oldest)
            except BaseException as e:
                pb._error = e
            pb._state = _DONE
            self._observe(pb, txns)
            return
        pb._force = self.engine.columnar_dispatch(plan)
        pb._buckets = plan.get("chunk_buckets")
        pb._state = _DISPATCHED

    def _force(self, pb: PendingResolve) -> None:
        if pb._state == _PACKING:
            self._dispatch(pb)
        if pb._state == _DISPATCHED:
            t0 = time.perf_counter() if self.batcher is not None else 0.0
            t_span = span_now() if g_spans.enabled else 0.0
            try:
                pb._result = pb._force()
            except BaseException as e:
                pb._error = e
            else:
                if self.batcher is not None and pb._buckets:
                    # observed service time split across the batch's chunks
                    # pro-rata by bucket size (device time scales with T):
                    # a flat mean would charge a small-bucket tail chunk a
                    # big chunk's cost and vice versa, skewing the EWMA the
                    # budget target is computed from
                    wall = (time.perf_counter() - t0) * 1e3
                    total = sum(pb._buckets)
                    for t in pb._buckets:
                        self.batcher.observe(t, wall * t / total)
            if g_spans.enabled:
                # the wall-clock analog of the sim service's force segment:
                # host blocked on the dispatched batch's device values
                span_event("pipeline.force", pb.version, t_span, span_now(),
                           txns=pb.n_txns, parent="resolver.queue_wait")
            pb._force = None
            pb._state = _DONE
            self._observe(pb)

    def _observe(self, pb: PendingResolve, txns=None) -> None:
        """Feed one completed batch's verdicts to the conflict predictor
        (no-op without an enabled scheduler or on an errored batch)."""
        cs = self.conflict_sched
        if cs is None or not cs.enabled or pb._error is not None:
            return
        if txns is None:
            txns = pb._txns[0] if pb._txns is not None else None
        if txns:
            cs.observe_batch(txns, pb._result, pb.version)

    def _force_oldest(self) -> None:
        while self._queue and self._queue[0].is_done:
            self._queue.popleft()
        if self._queue:
            self._force(self._queue[0])

    def _force_through(self, pb: PendingResolve) -> None:
        """Force pb and everything submitted before it, in order."""
        while not pb.is_done:
            # also drops already-done heads
            self._force_oldest()
        while self._queue and self._queue[0].is_done:
            self._queue.popleft()
