"""Conflict-aware batch scheduling: predict, separate, serialize, pre-abort.

BENCH_r06 `served_under_chaos` measures abort_frac climbing 16% -> 43% as
Zipf skew rises to 1.2 — optimistic concurrency collapses exactly where
load piles onto hot keys — while the observability stack already KNOWS
where conflicts come from: per-key-range heat and first-witness abort
attribution (core/heatmap.py), and the full transaction+verdict journal
(core/blackbox.py). Nothing acted on that knowledge before a doomed
transaction burned a device dispatch. Proust (PAPERS.md) frames this
design space — concurrency structures layered ABOVE a serializable core —
and Harmonia partitions conflict handling by key range; this module is
that layer for the TPU resolver: a deterministic scheduler between
admission and the batcher that schedules AROUND predicted conflicts
instead of paying for them.

Four mechanisms, all knob-gated (`resolver_sched*`, docs/scheduling.md):

  * **predictor** — a decayed per-key-range conflict score fed by the heat
    aggregator's consumable first-witness stream (`drain_witnesses()`) and
    by the verdict feedback of every resolved batch, plus a bounded
    last-committed-write version per hot range. A transaction reading a
    hot range whose last write is newer than its read snapshot is
    predicted DOOMED — under strict-serializable validation that verdict
    is already decided, the device dispatch would only discover it.
  * **separation** — within the pending window, two transactions writing
    the same hot range are split into different batches (the follower is
    deferred one tick, bounded by `resolver_sched_defer_max`), so a batch
    carries at most one writer per hot range and intra-batch conflict
    cascades stop.
  * **serialization lanes** — hot-key write chains conflict with each
    other, not the world: captured into a per-range lane that drains in
    arrival (= version) order as single-writer sub-batches, one head per
    tick, they stop competing for slots that general traffic can use.
  * **pre-abort** — a predicted-doomed transaction is answered with the
    typed retryable `transaction_conflict_predicted` BEFORE device
    dispatch; the client refreshes its read version and retries with a
    snapshot that can actually win. A deterministic 1-in-N counter probe
    dispatches a predicted-doomed transaction anyway; a probe that
    COMMITS increments the mispredict counter the watchdog's
    `sched_mispredict` rule alerts on (core/watchdog.py).

Correctness invariant: scheduling only changes WHICH transactions reach
the resolver in WHICH batch — for any schedule, the resolver's verdicts
on the unscheduled submission order remain the bit-identical parity
baseline, and journal replay of the schedule actually dispatched stays
bit-for-bit through the clean serial oracle (tests/test_scheduler.py).
The fully-off path (`resolver_sched` = "") hands batches through
untouched: no predictor state, no reorder, no extra telemetry series,
byte-identical compiled programs.

Determinism discipline (this package is policed by fdbtpu-lint's
determinism rule): no wall clock, no rng — probing is counter-based,
ties break on arrival order, and every map iterates in insertion order,
so the same seed always yields the same schedule.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: sched.* span segments (policed by fdbtpu-lint's span-registry rule,
#: like reshard.py's RESHARD_SEGMENTS): the scheduler's own arc names,
#: NOT part of the commit waterfall's telescoping-sum registry — a
#: select tick happens outside any one transaction's latency.
SCHED_SEGMENTS = ("select", "preabort", "lane_drain", "observe",
                  "epoch_flip")

#: per-transaction decision codes (journaled in aggregate per version —
#: core/blackbox.py BBSched — and counted in snapshot()/telemetry)
DECISION_DISPATCH = "dispatch"
DECISION_DEFER = "defer"
DECISION_LANE = "lane"
DECISION_PREABORT = "preabort"
DECISION_PROBE = "probe"
DECISION_FORCED = "forced"

#: predictor score increments: an attributed first-witness abort is a
#: stronger contention signal than one host-observed conflict verdict,
#: and every committed write keeps a range's hotness tracking its WRITE
#: traffic — conflict probability scales with write rate x snapshot
#: staleness, so a range the scheduler is successfully protecting must
#: not decay cold and oscillate back into aborting
_WITNESS_WEIGHT = 2.0
_CONFLICT_WEIGHT = 1.0
_WRITE_WEIGHT = 1.0
#: scores below this after decay are dropped (bounds the map together
#: with _MAX_TRACKED without losing any range that still matters)
_SCORE_FLOOR = 1e-3


def _hex(b: bytes) -> str:
    return bytes(b).hex()


@dataclass
class SchedConfig:
    """Resolved `resolver_sched*` knob family (docs/scheduling.md knob
    table). Constructed from SERVER_KNOBS by default; tests and the
    smoke harness override fields directly."""

    enabled: bool = False
    window: int = 256
    hot_score: float = 4.0
    decay: float = 0.98
    preabort: bool = True
    probe_interval: int = 16
    lane_max: int = 8
    lane_depth: int = 32
    defer_max: int = 4
    mispredict_frac: float = 0.5

    @classmethod
    def from_knobs(cls) -> "SchedConfig":
        from ..core.knobs import SERVER_KNOBS as k

        mode = str(k.resolver_sched or "").strip().lower()
        return cls(
            enabled=bool(mode) and mode != "off",
            window=int(k.resolver_sched_window),
            hot_score=float(k.resolver_sched_hot_score),
            decay=float(k.resolver_sched_decay),
            preabort=bool(k.resolver_sched_preabort),
            probe_interval=max(1, int(k.resolver_sched_probe_interval)),
            lane_max=int(k.resolver_sched_lane_max),
            lane_depth=int(k.resolver_sched_lane_depth),
            defer_max=int(k.resolver_sched_defer_max),
            mispredict_frac=float(k.resolver_sched_mispredict_frac),
        )

    def as_dict(self) -> dict:
        return {"enabled": self.enabled, "window": self.window,
                "hot_score": self.hot_score, "decay": self.decay,
                "preabort": self.preabort,
                "probe_interval": self.probe_interval,
                "lane_max": self.lane_max, "lane_depth": self.lane_depth,
                "defer_max": self.defer_max,
                "mispredict_frac": self.mispredict_frac}


class ConflictPredictor:
    """Decayed per-key-range conflict scores + last-committed-write
    versions for hot ranges — the doom model.

    Fed two ways: the heat aggregator's consumable first-witness stream
    (attributed aborts, strongest signal, carries the convicting write
    version) and plain verdict feedback from every resolved batch
    (conflict verdicts bump the aborted read ranges; commit verdicts
    advance `last_write` for tracked write ranges). Both feeds key on the
    RAW conflict-range begin key, the same key the heat map and the shard
    map use, so a lane and a shard speak about the same range.

    Doom rule: a transaction is predicted doomed iff some read range's
    begin key is hot (score >= hot_score) AND that range's last committed
    write version exceeds the transaction's read snapshot. Under
    strict-serializable validation that transaction cannot commit — the
    prediction can only be WRONG when the tracked last_write is stale
    (e.g. the writer's version was GC'd into a fresh engine), which is
    exactly what the probe/mispredict counters measure."""

    #: retained scored ranges (load-ranked prune, like the heat map's
    #: MAX_RANGES — bounded state is the contract of every core map here)
    MAX_TRACKED = 1024

    def __init__(self, hot_score: float, decay: float):
        self.hot_score = float(hot_score)
        self.decay = float(decay)
        #: range begin key -> decayed conflict score
        self.scores: Dict[bytes, float] = {}
        #: range begin key -> newest committed write version (hot ranges)
        self.last_write: Dict[bytes, int] = {}
        self.witnesses_consumed = 0

    def tick(self) -> None:
        """One scheduling tick: decay every score, drop the dust."""
        if self.decay < 1.0 and self.scores:
            dead: List[bytes] = []
            for k in self.scores:
                s = self.scores[k] * self.decay
                if s < _SCORE_FLOOR:
                    dead.append(k)
                else:
                    self.scores[k] = s
            for k in dead:
                del self.scores[k]
                self.last_write.pop(k, None)

    def observe_witness(self, range_begin: bytes,
                        witness_version: Optional[int] = None) -> None:
        """One drained first-witness sample (core/heatmap.py
        drain_witnesses): the attributed range gains witness weight and,
        when the device named the convicting write's version, the
        last-write map learns it."""
        b = bytes(range_begin)
        self.scores[b] = self.scores.get(b, 0.0) + _WITNESS_WEIGHT
        self.witnesses_consumed += 1
        if witness_version is not None:
            lw = self.last_write.get(b)
            if lw is None or int(witness_version) > lw:
                self.last_write[b] = int(witness_version)

    def observe_conflict(self, range_begin: bytes) -> None:
        b = bytes(range_begin)
        self.scores[b] = self.scores.get(b, 0.0) + _CONFLICT_WEIGHT

    def note_commit(self, range_begin: bytes, version: int) -> None:
        """A committed write advances the range's last-write version —
        the fact the doom rule compares snapshots against — and adds the
        (small) write weight to its score, so sustained write traffic
        keeps a contended range hot even while pre-aborts are preventing
        the conflicts that would otherwise re-score it. Cold ranges'
        residue decays below _SCORE_FLOOR within a few ticks and the
        load-ranked prune bounds the map either way."""
        b = bytes(range_begin)
        self.scores[b] = self.scores.get(b, 0.0) + _WRITE_WEIGHT
        lw = self.last_write.get(b)
        if lw is None or int(version) > lw:
            self.last_write[b] = int(version)

    def score_of(self, range_begin: bytes) -> float:
        return self.scores.get(bytes(range_begin), 0.0)

    def is_hot(self, range_begin: bytes) -> bool:
        return self.scores.get(bytes(range_begin), 0.0) >= self.hot_score

    def doomed_range(self, txn) -> Optional[bytes]:
        """The convicting hot range when `txn` is predicted doomed, else
        None. First match in the transaction's own read-range order —
        deterministic, and the journaled `why` names a single range."""
        snap = int(txn.read_snapshot)
        for r in txn.read_conflict_ranges:
            b = bytes(r.begin)
            lw = self.last_write.get(b)
            if (lw is not None and lw > snap
                    and self.scores.get(b, 0.0) >= self.hot_score):
                return b
        return None

    def hot_ranges(self, n: int = 8) -> List[Tuple[bytes, float]]:
        """Hottest tracked ranges, score-descending (key ascending on
        ties — stable across runs)."""
        ranked = sorted(self.scores.items(),
                        key=lambda kv: (-kv[1], kv[0]))
        return [(k, v) for k, v in ranked[:n] if v >= self.hot_score]

    def prune(self) -> None:
        if len(self.scores) <= self.MAX_TRACKED:
            return
        ranked = sorted(self.scores.items(),
                        key=lambda kv: (-kv[1], kv[0]))
        self.scores = dict(ranked[: self.MAX_TRACKED])
        for k in [k for k in self.last_write if k not in self.scores]:
            del self.last_write[k]

    def snapshot(self) -> dict:
        return {
            "tracked_ranges": len(self.scores),
            "hot_ranges": [{"range_begin": _hex(k),
                            "score": round(v, 3)}
                           for k, v in self.hot_ranges(4)],
            "witnesses_consumed": self.witnesses_consumed,
        }


class SerializationLane:
    """One hot range's single-writer queue.

    Hot-key write chains conflict with each other, not the world: queued
    here they drain in arrival (= version) order, one head per scheduling
    tick, so each tick's batch carries at most one writer for the range —
    the rest stop burning dispatch slots they were doomed to lose. A lane
    goes DRAINING on a shard-map epoch flip (docs/scheduling.md "Lane
    state machine"): it accepts no new captures but keeps draining, so a
    reshard never strands a queued transaction; it retires once empty."""

    __slots__ = ("range_begin", "epoch", "entries", "draining",
                 "captured", "drained")

    def __init__(self, range_begin: bytes, epoch: int):
        self.range_begin = bytes(range_begin)
        self.epoch = int(epoch)
        self.entries: deque = deque()
        self.draining = False
        self.captured = 0
        self.drained = 0

    def as_dict(self) -> dict:
        return {"range_begin": _hex(self.range_begin),
                "epoch": self.epoch, "depth": len(self.entries),
                "state": "draining" if self.draining else "open",
                "captured": self.captured, "drained": self.drained}


@dataclass
class SchedPlan:
    """One select() tick's outcome: what to dispatch now, what to answer
    `transaction_conflict_predicted`, what stays pending — plus the
    aggregate decision counts the caller journals against the batch's
    commit version (core/blackbox.py record_sched)."""

    dispatch: List[Any] = field(default_factory=list)
    #: (entry, convicting range begin) pairs to pre-abort
    preaborts: List[Tuple[Any, bytes]] = field(default_factory=list)
    #: still-pending entries, arrival order preserved
    remaining: List[Any] = field(default_factory=list)
    #: decision code -> count this tick
    decided: Dict[str, int] = field(default_factory=dict)
    #: distinct convicting ranges behind this tick's pre-aborts (hex)
    preabort_ranges: Tuple[str, ...] = ()
    #: distinct lane ranges that captured or drained this tick (hex)
    lane_ranges: Tuple[str, ...] = ()


class ConflictScheduler:
    """The deterministic scheduler between admission and the batcher.

    Owns a ConflictPredictor and the serialization lanes; `select()` runs
    once per batching tick over the caller's pending window, and
    `observe_batch()` feeds every resolved batch's verdicts back. The
    heat aggregator, when attached, contributes its first-witness abort
    attributions through the consumable `drain_witnesses()` stream —
    never the peek-only display ring, so `cli heat` and the scheduler
    cannot double-count a sample.

    `entry_txn` adapts the caller's pending-entry shape (the wall-clock
    commit server queues `(txn, promise, t, meta)` tuples, the sim proxy
    `(txn, promise)`); everything else is shape-agnostic. Disabled
    (cfg.enabled False) the scheduler is inert: select() slices the
    window FIFO exactly as the caller would have, touching no state."""

    def __init__(self, cfg: Optional[SchedConfig] = None, heat=None,
                 entry_txn: Optional[Callable[[Any], Any]] = None,
                 name: str = "sched"):
        self.cfg = cfg if cfg is not None else SchedConfig.from_knobs()
        #: KeyRangeHeatAggregator (or None): witness feed + weight seed
        self.heat = heat
        self.entry_txn = entry_txn if entry_txn is not None else (
            lambda e: e)
        self.name = name
        self.predictor = ConflictPredictor(self.cfg.hot_score,
                                           self.cfg.decay)
        #: range begin key -> lane, insertion-ordered (drain order)
        self.lanes: Dict[bytes, SerializationLane] = {}
        #: shard-map epoch the lanes were derived under (-1 = static map)
        self.epoch = -1
        #: id(entry) -> ticks deferred (separation starvation bound)
        self._defers: Dict[int, int] = {}
        #: id(txn) -> convicting range for in-flight probes
        self._probes: Dict[int, bytes] = {}
        #: predicted-doomed occurrences, drives the 1-in-N probe cadence
        self._doomed_seen = 0
        self.counters: Dict[str, int] = {
            "ticks": 0, "examined": 0, "dispatched": 0, "deferred": 0,
            "laned": 0, "lane_drained": 0, "preaborts": 0, "probes": 0,
            "probe_ok": 0, "mispredicts": 0, "forced": 0, "reordered": 0,
            "epoch_flips": 0, "lanes_opened": 0, "lanes_retired": 0,
        }
        if self.cfg.enabled:
            # unified telemetry (core/telemetry.py): counters + predictor
            # gauges become `sched.<label>.*` series, the `fdbtpu_sched`
            # exposition family and the sched_mispredict rule's feed.
            # Only the enabled path registers: fully-off must add no
            # series (the byte-identical-off contract).
            from ..core import telemetry

            self.label = telemetry.hub().register_scheduler(self, name)
        else:
            self.label = None

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # -- scheduling ----------------------------------------------------------
    def select(self, pending: Sequence[Any], cap: int) -> SchedPlan:
        """One batching tick: pick up to `cap` entries to dispatch from
        `pending` (arrival order), route hot writers through lanes,
        pre-abort the predicted-doomed, defer separation losers. The
        input is not mutated; `plan.remaining` is the caller's new
        pending queue (arrival order preserved among kept entries)."""
        if not self.cfg.enabled or cap <= 0:
            return SchedPlan(dispatch=list(pending[:max(0, cap)]),
                             remaining=list(pending[max(0, cap):]))
        self.counters["ticks"] += 1
        self.predictor.tick()
        self._drain_heat_witnesses()
        decided: Dict[str, int] = {}
        preaborts: List[Tuple[Any, bytes]] = []
        preabort_ranges: List[str] = []
        lane_ranges: List[str] = []
        dispatch: List[Any] = []

        window = list(pending[: self.cfg.window])
        tail = list(pending[self.cfg.window:])
        self.counters["examined"] += len(window)

        # 1. lane capture: a hot-range writer joins its range's lane (one
        #    writer per range per batch is the lane's whole point). Lanes
        #    open lazily up to lane_max; draining lanes and full lanes
        #    capture nothing — overflow rides the normal flow.
        normal: List[Any] = []
        for e in window:
            lane = self._lane_for(self.entry_txn(e))
            if lane is not None:
                lane.entries.append(e)
                lane.captured += 1
                self.counters["laned"] += 1
                decided[DECISION_LANE] = decided.get(DECISION_LANE, 0) + 1
                if _hex(lane.range_begin) not in lane_ranges:
                    lane_ranges.append(_hex(lane.range_begin))
            else:
                normal.append(e)

        # 2. lane candidates: one head per lane per tick, lane-creation
        #    order. A doomed head is pre-aborted (it queued behind the
        #    writer that convicts it; a fresh snapshot is its only way
        #    through) and the next head takes the slot. The surviving
        #    head is a CANDIDATE only — whether it drains this tick is
        #    decided after the normal flow is known (3b): its reads must
        #    not land behind a same-batch hot write or it aborts
        #    in-batch, the exact cascade lanes exist to prevent.
        lane_candidates: List[Tuple[bytes, SerializationLane, Any, int]] = []
        for key in list(self.lanes):
            lane = self.lanes[key]
            while lane.entries and len(lane_candidates) < cap:
                e = lane.entries[0]
                act = self._doom_action(self.entry_txn(e))
                if act == DECISION_PREABORT:
                    lane.entries.popleft()
                    self._forget(e)
                    preaborts.append((e, key))
                    if _hex(key) not in preabort_ranges:
                        preabort_ranges.append(_hex(key))
                    decided[DECISION_PREABORT] = \
                        decided.get(DECISION_PREABORT, 0) + 1
                    continue
                lane_candidates.append((key, lane, e, act))
                break   # single writer per lane per tick

        # 3. normal flow: pre-abort the doomed, separate likely
        #    in-batch-conflicting pairs into different ticks, dispatch
        #    the rest FIFO. Two separation rules, both bounded by
        #    defer_max: a second WRITER of a hot range already written
        #    by this tick's dispatch set waits a tick (write-write), and
        #    a hot writer whose READS intersect the hot ranges written
        #    by already-accepted back entries waits a tick — it would be
        #    ordered into the back of the batch BEHIND the write that
        #    convicts it (read-write; the dominant in-batch abort under
        #    multi-key hot transactions).
        kept: List[Any] = []
        #: hot ranges written by this tick's dispatch set (lane
        #: candidates included: their heads are hot-range writers by
        #: construction) — the write-write separation set
        written_hot = set()
        for _k, _l, e, _a in lane_candidates:
            for r in self.entry_txn(e).write_conflict_ranges:
                b = bytes(r.begin)
                if self.predictor.is_hot(b):
                    written_hot.add(b)
        #: hot ranges written by accepted NORMAL-flow back entries only:
        #: lane heads dispatch after the back, so lane writes cannot
        #: convict back reads — only back writes convict back reads
        back_written: set = set()
        budget = max(0, cap - len(lane_candidates))
        for e in normal:
            if len(dispatch) >= budget:
                kept.append(e)   # FIFO overflow: no decision, no defer
                continue
            txn = self.entry_txn(e)
            forced = self._defers.get(id(e), 0) >= self.cfg.defer_max
            act = DECISION_DISPATCH if forced else self._doom_action(txn)
            if forced:
                self.counters["forced"] += 1
                decided[DECISION_FORCED] = \
                    decided.get(DECISION_FORCED, 0) + 1
            if act == DECISION_PREABORT:
                doomed = self.predictor.doomed_range(txn)
                self._forget(e)
                preaborts.append((e, doomed))
                if _hex(doomed) not in preabort_ranges:
                    preabort_ranges.append(_hex(doomed))
                decided[DECISION_PREABORT] = \
                    decided.get(DECISION_PREABORT, 0) + 1
                continue
            if act == DECISION_DEFER:
                self._defer(e, kept, decided)
                continue
            hot_writes = {bytes(r.begin)
                          for r in txn.write_conflict_ranges
                          if self.predictor.is_hot(bytes(r.begin))}
            if hot_writes & written_hot and not forced:
                # write-write separation: a second writer of an
                # already-written hot range waits for the next batch
                self._defer(e, kept, decided)
                continue
            if hot_writes and not forced:
                hot_reads = {bytes(r.begin)
                             for r in txn.read_conflict_ranges
                             if self.predictor.is_hot(bytes(r.begin))}
                if hot_reads & back_written:
                    # read-write separation: this writer would be
                    # reordered behind the very write that convicts it
                    self._defer(e, kept, decided)
                    continue
            written_hot |= hot_writes
            back_written |= hot_writes
            self._forget(e)
            dispatch.append(e)
            if act == DECISION_PROBE:
                decided[DECISION_PROBE] = \
                    decided.get(DECISION_PROBE, 0) + 1

        # 3b. lane drain: a candidate head whose reads intersect the
        #     batch's accepted hot writes (normal back entries + earlier
        #     lane heads) stays queued a tick instead of aborting
        #     in-batch — bounded by defer_max like any separation loser.
        lane_dispatch: List[Any] = []
        lane_written: set = set()
        for key, lane, e, act in lane_candidates:
            txn = self.entry_txn(e)
            hot_reads = {bytes(r.begin)
                         for r in txn.read_conflict_ranges
                         if self.predictor.is_hot(bytes(r.begin))}
            if hot_reads & (back_written | lane_written):
                if self._defers.get(id(e), 0) < self.cfg.defer_max:
                    self._defers[id(e)] = self._defers.get(id(e), 0) + 1
                    self.counters["deferred"] += 1
                    decided[DECISION_DEFER] = \
                        decided.get(DECISION_DEFER, 0) + 1
                    continue   # head stays queued; the lane skips a tick
                self.counters["forced"] += 1
                decided[DECISION_FORCED] = \
                    decided.get(DECISION_FORCED, 0) + 1
            lane.entries.popleft()
            lane.drained += 1
            self.counters["lane_drained"] += 1
            self._forget(e)
            lane_dispatch.append(e)
            lane_written |= {bytes(r.begin)
                            for r in txn.write_conflict_ranges
                            if self.predictor.is_hot(bytes(r.begin))}
            if act == DECISION_PROBE:
                decided[DECISION_PROBE] = \
                    decided.get(DECISION_PROBE, 0) + 1
        for key in list(self.lanes):
            lane = self.lanes[key]
            if lane.draining and not lane.entries:
                del self.lanes[key]
                self.counters["lanes_retired"] += 1

        # 4. window reorder (separation of likely-conflicting PAIRS): a
        #    batch resolves in list order, so every hot-range writer —
        #    normal-flow stragglers first, then the laned single-writers
        #    — moves to the back of the batch. Cold entries and hot-range
        #    readers keep their arrival order in front of them: a
        #    fresh-snapshot reader ordered before the batch's writer of
        #    its range commits; ordered after it, it aborts.
        def _writes_hot(e) -> bool:
            return any(self.predictor.is_hot(bytes(r.begin))
                       for r in self.entry_txn(e).write_conflict_ranges)

        front = [e for e in dispatch if not _writes_hot(e)]
        back = [e for e in dispatch if _writes_hot(e)]
        if back or lane_dispatch:
            self.counters["reordered"] += len(back) + len(lane_dispatch)
        dispatch = front + back + lane_dispatch

        decided[DECISION_DISPATCH] = len(dispatch)
        self.counters["dispatched"] += len(dispatch)
        self.counters["preaborts"] += len(preaborts)
        self.predictor.prune()
        return SchedPlan(dispatch=dispatch, preaborts=preaborts,
                         remaining=kept + tail, decided=decided,
                         preabort_ranges=tuple(preabort_ranges),
                         lane_ranges=tuple(lane_ranges))

    def _defer(self, e, kept: List[Any], decided: Dict[str, int]) -> None:
        self._defers[id(e)] = self._defers.get(id(e), 0) + 1
        self.counters["deferred"] += 1
        decided[DECISION_DEFER] = decided.get(DECISION_DEFER, 0) + 1
        kept.append(e)

    def _forget(self, e) -> None:
        self._defers.pop(id(e), None)

    def _lane_for(self, txn) -> Optional[SerializationLane]:
        """The open lane that should capture `txn` (None = normal flow):
        first hot write range with lane capacity, lazily opening a lane
        while under lane_max. Read-only transactions and cold writers
        never lane."""
        for r in txn.write_conflict_ranges:
            b = bytes(r.begin)
            if not self.predictor.is_hot(b):
                continue
            lane = self.lanes.get(b)
            if lane is None:
                if len(self.lanes) >= self.cfg.lane_max:
                    continue
                lane = self.lanes[b] = SerializationLane(b, self.epoch)
                self.counters["lanes_opened"] += 1
            if lane.draining or len(lane.entries) >= self.cfg.lane_depth:
                continue
            return lane
        return None

    def _doom_action(self, txn) -> str:
        """Classify one transaction against the doom model: DISPATCH,
        PREABORT, PROBE (counter-based 1-in-N doomed dispatch that keeps
        the predictor honest), or DEFER (pre-abort knob off: separation
        is the only tool, the defer_max bound still applies)."""
        doomed = self.predictor.doomed_range(txn)
        if doomed is None:
            return DECISION_DISPATCH
        self._doomed_seen += 1
        if self._doomed_seen % self.cfg.probe_interval == 0:
            self.counters["probes"] += 1
            if len(self._probes) >= 4096:
                # bound the in-flight probe map: a probe whose verdict
                # never came back (dispatch error) must not pin memory
                self._probes.pop(next(iter(self._probes)))
            self._probes[id(txn)] = doomed
            return DECISION_PROBE
        if self.cfg.preabort:
            return DECISION_PREABORT
        return DECISION_DEFER

    # -- feedback ------------------------------------------------------------
    def observe_batch(self, transactions: Sequence[Any],
                      verdicts: Sequence[Any], version: int) -> None:
        """One resolved batch's verdicts: conflicts bump the predictor's
        scores on the aborted read ranges, commits advance last-write on
        tracked write ranges, and in-flight probes settle — a probe that
        committed is a MISPREDICT (the model said doomed)."""
        if not self.cfg.enabled:
            return
        from ..core.types import TransactionCommitResult

        committed = int(TransactionCommitResult.COMMITTED)
        too_old = int(TransactionCommitResult.TOO_OLD)
        v = int(version)
        for t, txn in enumerate(transactions):
            verdict = int(verdicts[t])
            probe_range = self._probes.pop(id(txn), None)
            if verdict == committed:
                for r in txn.write_conflict_ranges:
                    self.predictor.note_commit(r.begin, v)
                if probe_range is not None:
                    self.counters["mispredicts"] += 1
            elif verdict != too_old:
                for r in txn.read_conflict_ranges:
                    self.predictor.observe_conflict(r.begin)
                if probe_range is not None:
                    self.counters["probe_ok"] += 1

    def _drain_heat_witnesses(self) -> None:
        if self.heat is None:
            return
        drain = getattr(self.heat, "drain_witnesses", None)
        if drain is None:
            return
        for sample in drain():
            rb = sample.get("range_begin")
            if rb is None:
                continue
            self.predictor.observe_witness(rb,
                                           sample.get("witness_version"))

    # -- reshard interplay ---------------------------------------------------
    def notify_epoch(self, epoch: int) -> None:
        """Shard-map epoch flip (server/reshard.py): lane assignments
        were derived under the OLD map, so every open lane flips to
        DRAINING — it keeps draining (never strands a queued transaction)
        but captures nothing; fresh captures re-derive lanes under the
        new epoch as ranges prove hot again."""
        epoch = int(epoch)
        if epoch == self.epoch:
            return
        self.epoch = epoch
        self.counters["epoch_flips"] += 1
        for lane in self.lanes.values():
            lane.draining = True

    def flush(self) -> List[Any]:
        """Hand back EVERY entry still queued in a lane, lane-creation
        order, and retire the lanes — the shutdown/teardown path, so a
        stopping server can answer or dispatch each queued transaction
        instead of dropping its promise."""
        out: List[Any] = []
        for lane in self.lanes.values():
            out.extend(lane.entries)
            lane.entries.clear()
        self.counters["lanes_retired"] += len(self.lanes)
        self.lanes.clear()
        return out

    # -- read model ----------------------------------------------------------
    def mispredict_frac(self) -> float:
        settled = self.counters["probe_ok"] + self.counters["mispredicts"]
        if settled == 0:
            return 0.0
        return self.counters["mispredicts"] / settled

    def pending_laned(self) -> int:
        return sum(len(lane.entries) for lane in self.lanes.values())

    def snapshot(self) -> dict:
        return {
            "config": self.cfg.as_dict(),
            "epoch": self.epoch,
            "counters": dict(self.counters),
            "mispredict_frac": round(self.mispredict_frac(), 4),
            "lanes": [lane.as_dict() for lane in self.lanes.values()],
            "pending_laned": self.pending_laned(),
            "predictor": self.predictor.snapshot(),
        }
