"""Pipelined resolver service: multi-batch in-flight conflict resolution.

Three pieces (see docs/pipeline.md):

  * ResolverPipeline — wall-clock engine pipeline: host packing (inline or
    executor) overlapped with JAX async device dispatch, a configurable
    in-flight window, results forced in commit-version order.
  * PipelineConfig / PipelinedResolverService — the sim-cluster resolver's
    virtual-time twin: same window/stage structure with measured pack and
    device times injected as delays (server/resolver.py drains its queue
    through it instead of blocking per batch).
  * latency_harness (imported lazily — it pulls in the whole sim cluster):
    open-loop arrivals through the e2e sim cluster, reporting
    client-observed commit-latency percentiles + sustained throughput for
    bench.py's `latency_under_load` section.
"""
from .resolver_pipeline import BudgetBatcher, PendingResolve, ResolverPipeline
from .service import PipelineConfig, PipelinedResolverService

__all__ = [
    "BudgetBatcher",
    "PendingResolve",
    "ResolverPipeline",
    "PipelineConfig",
    "PipelinedResolverService",
]
