"""Virtual-time pipelined resolution service for the sim cluster.

The deterministic simulation runs the conflict engine's host compute in
zero virtual time, so the one-batch-at-a-time resolver shows NO service
time at all — nothing in the e2e sim ever measured what the resolver's
real pack/device costs do to client-observed commit latency (VERDICT r5
weak #2). This service is the sim analog of ResolverPipeline: the same
window/stage structure, with the wall-clock pack and device times
INJECTED as virtual-time delays (bench.py measures them on the real chip
with the scan methodology and feeds them in), so the e2e cluster's
commit-latency distribution reflects the measured hardware.

Stage model, exactly the overlap the wall-clock pipeline gives:

  * a window of `depth` batches may be in service at once (acquire());
  * each batch pays a host pack delay (linear in its transaction count) —
    packs of different batches overlap each other and the device;
  * the DEVICE is serial: batch i+1's program starts only after batch i's
    finished, in commit-version order — verdicts are computed by the real
    engine at that point, so abort sets are bit-identical to the serial
    resolver (same engine calls, same order);
  * depth 1 degenerates to pack + device back-to-back with no overlap —
    the serial baseline.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from ..core import buggify
from ..sim.actors import NotifiedVersion
from ..sim.loop import Promise, TaskPriority, delay


@dataclass
class PipelineConfig:
    """Knobs of the pipelined resolver service (docs/pipeline.md).

    depth               — in-flight window: 1 = serial, 2 = double
                          buffering (pack overlaps device), 3 = triple.
    pack_ms_per_txn     — host packing cost, linear in batch size
                          (bench.py: host_pack_ms_per_batch / batch_txns).
    device_ms_per_batch — device program time for the compiled batch shape
                          (constant per dispatch; bench.py measure_scan).
    max_batch_txns      — the compiled kernel's T: proxies must not send
                          larger batches (server/proxy.py max_commit_batch
                          is sized to it).
    """

    depth: int = 2
    pack_ms_per_txn: float = 0.0
    device_ms_per_batch: float = 0.0
    max_batch_txns: int = 4096

    def as_dict(self) -> dict:
        return {"depth": self.depth,
                "pack_ms_per_txn": self.pack_ms_per_txn,
                "device_ms_per_batch": self.device_ms_per_batch,
                "max_batch_txns": self.max_batch_txns}


class PipelinedResolverService:
    """One resolver role's service pipeline (owned by server/resolver.py)."""

    def __init__(self, cfg: PipelineConfig, engine):
        self.cfg = cfg
        self.engine = engine
        self._in_use = 0
        self._waiters: deque = deque()
        self._seq = 0
        #: sequence number of the newest batch whose device stage finished
        self._device_done = NotifiedVersion(0)

    @property
    def in_flight(self) -> int:
        return self._in_use

    def _capacity(self) -> int:
        """Effective window: a degraded engine (fault/resilient.py —
        retrying, failed over, or on probation) collapses the pipeline to
        depth 1 so we stop piling dispatches onto a sick device; the full
        window re-opens on swap-back."""
        if getattr(self.engine, "degraded", False):
            return 1
        return max(1, self.cfg.depth)

    async def acquire(self) -> None:
        """Take a window slot; blocks while the effective window is full
        (the resolver's backpressure onto the proxy's commit window)."""
        while self._in_use >= self._capacity():
            p = Promise()
            self._waiters.append(p)
            try:
                await p.future   # woken by release(); capacity re-checked
            except BaseException:
                if p.is_set:
                    # release() woke us while we were being cancelled:
                    # pass the wake-up on rather than losing it
                    self._wake()
                else:
                    self._waiters.remove(p)
                raise
        self._in_use += 1

    def release(self) -> None:
        self._in_use -= 1
        self._wake()

    def _wake(self) -> None:
        if self._waiters and self._in_use < self._capacity():
            self._waiters.popleft().send(None)

    async def resolve(self, transactions, version, new_oldest):
        """Run one accepted batch through pack -> device -> verdicts.
        Callers hold a window slot and enter in commit-version order (the
        resolver's version chain guarantees it); the slot is released here
        when the batch completes."""
        self._seq += 1
        seq = self._seq
        try:
            pack_ms = self.cfg.pack_ms_per_txn * len(transactions)
            if buggify.buggify():
                # jittered host pack: batches arrive at the device stage
                # out of rhythm, stressing the in-order device chain
                pack_ms = pack_ms * 5 + 0.05
            if pack_ms > 0:
                await delay(pack_ms / 1e3, TaskPriority.PROXY_RESOLVER_REPLY)
            await self._device_done.when_at_least(seq - 1)
            verdicts = self.engine.resolve(transactions, version, new_oldest)
            if hasattr(verdicts, "__await__"):
                # supervised engine (fault/resilient.py): the dispatch may
                # retry/fail over under its watchdog before verdicts land
                verdicts = await verdicts
            if self.cfg.device_ms_per_batch > 0:
                await delay(self.cfg.device_ms_per_batch / 1e3,
                            TaskPriority.PROXY_RESOLVER_REPLY)
            return verdicts
        finally:
            # On any exit (including cancellation mid-wait) unblock the
            # successor's device wait and hand the slot on — a wedged chain
            # would stall every later batch forever.
            self._device_done.advance(seq)
            self.release()
