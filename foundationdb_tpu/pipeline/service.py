"""Virtual-time pipelined resolution service for the sim cluster.

The deterministic simulation runs the conflict engine's host compute in
zero virtual time, so the one-batch-at-a-time resolver shows NO service
time at all — nothing in the e2e sim ever measured what the resolver's
real pack/device costs do to client-observed commit latency (VERDICT r5
weak #2). This service is the sim analog of ResolverPipeline: the same
window/stage structure, with the wall-clock pack and device times
INJECTED as virtual-time delays (bench.py measures them on the real chip
with the scan methodology and feeds them in), so the e2e cluster's
commit-latency distribution reflects the measured hardware.

Stage model, exactly the overlap the wall-clock pipeline gives:

  * a window of `depth` batches may be in service at once (acquire());
  * each batch pays a host pack delay (linear in its transaction count) —
    packs of different batches overlap each other and the device;
  * the DEVICE is serial: batch i+1's program starts only after batch i's
    finished, in commit-version order — verdicts are computed by the real
    engine at that point, so abort sets are bit-identical to the serial
    resolver (same engine calls, same order);
  * depth 1 degenerates to pack + device back-to-back with no overlap —
    the serial baseline.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

from ..core import buggify
from ..core.trace import g_spans, span_event, span_now
from ..sim.actors import NotifiedVersion
from ..sim.loop import Promise, TaskPriority, delay
from .resolver_pipeline import BudgetBatcher


@dataclass
class PipelineConfig:
    """Knobs of the pipelined resolver service (docs/pipeline.md,
    docs/perf.md).

    depth               — in-flight window: 1 = serial, 2 = double
                          buffering (pack overlaps device), 3 = triple.
    pack_ms_per_txn     — host packing cost, linear in batch size
                          (bench.py: host_pack_ms_per_batch / batch_txns).
    device_ms_per_batch — device program time for the compiled batch shape
                          (constant per dispatch; bench.py measure_scan).
    max_batch_txns      — the compiled kernel's top-bucket T: proxies must
                          not send larger batches (server/proxy.py
                          max_commit_batch is sized to it).
    device_ms_by_bucket — bucketed kernel ladder: measured device ms per
                          compiled bucket shape {T: ms} (bench.py
                          bucket_ladder section). When set, a batch pays
                          its own bucket's device time — not the top
                          shape's — and the service's BudgetBatcher
                          adaptively targets the largest bucket whose
                          predicted latency fits p99_budget_ms.
    p99_budget_ms       — commit-latency budget the adaptive target fits
                          (None = the resolver_p99_budget_ms knob).
    search_mode_by_bucket — resolved history-search mode per bucket
                          {T: "fused_sort" | "bsearch"} (docs/perf.md;
                          an engine's history_search_modes()). Keys the
                          BudgetBatcher's per-(bucket, mode) EWMAs so a
                          mode flip never poisons the other mode's
                          latency estimate.
    sched               — conflict-aware admission scheduling
                          (pipeline/scheduler.py, docs/scheduling.md):
                          "" = the resolver_sched knob decides, "on" /
                          "off" force it for this service. The service
                          resolves batches whose versions are already
                          assigned, so it never reorders; it OWNS the
                          shared ConflictScheduler instance — admission
                          layers call service.conflict_sched.select(),
                          and resolve() trains the predictor on every
                          batch's verdicts regardless of who admitted it.
    dispatch_mode       — how batches reach the device (docs/perf.md
                          "Device-resident loop"): "step" is the
                          launch-per-batch path whose device segment is
                          one opaque span; "device_loop" models the
                          device-resident server loop — the device span
                          splits into queue_enqueue / device_resident /
                          result_drain segments and the BudgetBatcher
                          files EWMAs under the "loop" dispatch key.
                          "mesh" is the multi-device engine (docs/perf.md
                          "Measured mesh resolution"): the same ring
                          segment split — its enqueue/drain shares are
                          the split dispatch + non-blocking exchange
                          retirement — with EWMAs filed under "mesh" and
                          the engine's mesh_stats snapshot riding the
                          device span next to loop_stats.
    queue_enqueue_ms    — loop mode: host cost to pack a queue slot and
                          async-dispatch the server step (no sync).
    result_drain_ms     — loop mode: host cost to poll + decode the
                          batch's abort bitmaps from the result ring
                          (non-blocking in steady state).
    """

    depth: int = 2
    pack_ms_per_txn: float = 0.0
    device_ms_per_batch: float = 0.0
    max_batch_txns: int = 4096
    device_ms_by_bucket: Optional[Dict[int, float]] = None
    p99_budget_ms: Optional[float] = None
    search_mode_by_bucket: Optional[Dict[int, str]] = None
    dispatch_mode: str = "step"
    queue_enqueue_ms: float = 0.0
    result_drain_ms: float = 0.0
    sched: str = ""

    def as_dict(self) -> dict:
        return {"depth": self.depth,
                "pack_ms_per_txn": self.pack_ms_per_txn,
                "device_ms_per_batch": self.device_ms_per_batch,
                "max_batch_txns": self.max_batch_txns,
                "device_ms_by_bucket": (dict(self.device_ms_by_bucket)
                                        if self.device_ms_by_bucket else None),
                "p99_budget_ms": self.p99_budget_ms,
                "search_mode_by_bucket": (dict(self.search_mode_by_bucket)
                                          if self.search_mode_by_bucket
                                          else None),
                "dispatch_mode": self.dispatch_mode,
                "queue_enqueue_ms": self.queue_enqueue_ms,
                "result_drain_ms": self.result_drain_ms,
                "sched": self.sched}


class PipelinedResolverService:
    """One resolver role's service pipeline (owned by server/resolver.py)."""

    def __init__(self, cfg: PipelineConfig, engine):
        self.cfg = cfg
        self.engine = engine
        self._in_use = 0
        self._waiters: deque = deque()
        self._seq = 0
        #: sequence number of the newest batch whose device stage finished
        self._device_done = NotifiedVersion(0)
        #: budget-driven batch sizing over the bucket ladder (None without
        #: a per-bucket device-time table): virtual-time service delays
        #: feed the EWMA; target_batch_txns() is the adaptive production
        #: point the proxy's commit batcher is capped to (via ratekeeper)
        #: shared conflict scheduler (pipeline/scheduler.py): the service
        #: owns the instance and trains its predictor on every resolved
        #: batch; admission layers consult it for select()/pre-abort.
        #: Config "" defers to the resolver_sched knob, "on"/"off" force.
        from .scheduler import ConflictScheduler, SchedConfig

        sched_cfg = SchedConfig.from_knobs()
        if cfg.sched:
            sched_cfg.enabled = cfg.sched.strip().lower() == "on"
        self.conflict_sched = ConflictScheduler(
            sched_cfg, heat=getattr(engine, "heat", None))
        self.batcher: Optional[BudgetBatcher] = None
        if cfg.device_ms_by_bucket:
            bucket_modes = dict(cfg.search_mode_by_bucket or {})
            if not bucket_modes and hasattr(engine, "history_search_modes"):
                bucket_modes = engine.history_search_modes()
            self.batcher = BudgetBatcher(
                ladder=list(cfg.device_ms_by_bucket),
                budget_ms=cfg.p99_budget_ms,
                pack_ms_per_txn=cfg.pack_ms_per_txn,
                seed_ms={int(t): float(v)
                         for t, v in cfg.device_ms_by_bucket.items()},
                bucket_modes=bucket_modes,
                # EWMAs file under the dispatch path serving this
                # resolver, so a device-loop rollout never poisons the
                # step path's estimates (docs/perf.md)
                dispatch_mode=("loop" if cfg.dispatch_mode == "device_loop"
                               else "mesh" if cfg.dispatch_mode == "mesh"
                               else getattr(engine, "dispatch_mode", "step")),
            )

    @property
    def in_flight(self) -> int:
        return self._in_use

    def target_batch_txns(self) -> int:
        """Adaptive batch-size target (falls back to the static top shape
        without a ladder). Degradation (fault/resilient.py) clamps to the
        smallest bucket on top of the depth-1 window collapse."""
        if self.batcher is None:
            return self.cfg.max_batch_txns
        return self.batcher.target_batch_txns(
            self.cfg.depth, degraded=getattr(self.engine, "degraded", False))

    def _device_ms(self, n_txns: int) -> float:
        """Injected device time for one batch: its own bucket's measured
        program time under a ladder (a light batch no longer pays the top
        shape's device time), else the flat per-batch figure."""
        if self.batcher is None:
            return self.cfg.device_ms_per_batch
        bucket = self.batcher.bucket_of(n_txns)
        ms = (self.cfg.device_ms_by_bucket or {}).get(bucket)
        return self.cfg.device_ms_per_batch if ms is None else ms

    def _capacity(self) -> int:
        """Effective window: a degraded engine (fault/resilient.py —
        retrying, failed over, or on probation) collapses the pipeline to
        depth 1 so we stop piling dispatches onto a sick device; the full
        window re-opens on swap-back."""
        if getattr(self.engine, "degraded", False):
            return 1
        return max(1, self.cfg.depth)

    async def acquire(self) -> None:
        """Take a window slot; blocks while the effective window is full
        (the resolver's backpressure onto the proxy's commit window)."""
        while self._in_use >= self._capacity():
            p = Promise()
            self._waiters.append(p)
            try:
                await p.future   # woken by release(); capacity re-checked
            except BaseException:
                if p.is_set:
                    # release() woke us while we were being cancelled:
                    # pass the wake-up on rather than losing it
                    self._wake()
                else:
                    self._waiters.remove(p)
                raise
        self._in_use += 1

    def release(self) -> None:
        self._in_use -= 1
        self._wake()

    def _wake(self) -> None:
        if self._waiters and self._in_use < self._capacity():
            self._waiters.popleft().send(None)

    async def resolve(self, transactions, version, new_oldest):
        """Run one accepted batch through pack -> device -> verdicts.
        Callers hold a window slot and enter in commit-version order (the
        resolver's version chain guarantees it); the slot is released here
        when the batch completes. With span collection on (core/trace.py)
        each stage emits a segment keyed by the commit version: host pack,
        pipeline wait (the in-order device chain), device dispatch, and the
        force/verdict-materialization tail — the decomposition bench.py's
        `latency_attribution` reassembles against client-observed latency."""
        self._seq += 1
        seq = self._seq
        spans_on = g_spans.enabled
        try:
            t0 = span_now() if spans_on else 0.0
            pack_ms = self.cfg.pack_ms_per_txn * len(transactions)
            if buggify.buggify():
                # jittered host pack: batches arrive at the device stage
                # out of rhythm, stressing the in-order device chain
                pack_ms = pack_ms * 5 + 0.05
            if pack_ms > 0:
                await delay(pack_ms / 1e3, TaskPriority.PROXY_RESOLVER_REPLY)
            if spans_on:
                t1 = span_now()
                span_event("resolver.host_pack", version, t0, t1,
                           txns=len(transactions),
                           parent="resolver.queue_wait")
            await self._device_done.when_at_least(seq - 1)
            from ..sim.loop import now as _now

            # the mesh engine shares the device loop's ring discipline
            # (enqueue share, non-blocking drain share, loop_stats), so
            # it gets the same segment split and snapshot attachment
            loop_mode = self.cfg.dispatch_mode in ("device_loop", "mesh")
            if spans_on:
                t2 = span_now()
                span_event("resolver.pipeline_wait", version, t1, t2,
                           parent="resolver.queue_wait")
            if loop_mode and self.cfg.queue_enqueue_ms > 0:
                # loop mode: the host's enqueue share — pack the queue
                # slot + async-dispatch the server step (no sync)
                await delay(self.cfg.queue_enqueue_ms / 1e3,
                            TaskPriority.PROXY_RESOLVER_REPLY)
            if spans_on and loop_mode:
                t2 = span_now()
                span_event("resolver.queue_enqueue", version,
                           t2 - self.cfg.queue_enqueue_ms / 1e3, t2,
                           txns=len(transactions),
                           parent="resolver.queue_wait")
            t_dev = _now()
            verdicts = self.engine.resolve(transactions, version, new_oldest)
            if hasattr(verdicts, "__await__"):
                # supervised engine (fault/resilient.py): the dispatch may
                # retry/fail over under its watchdog before verdicts land
                verdicts = await verdicts
            device_ms = self._device_ms(len(transactions))
            if device_ms > 0:
                await delay(device_ms / 1e3, TaskPriority.PROXY_RESOLVER_REPLY)
            if spans_on:
                t3 = span_now()
                # step mode: the device segment covers the engine dispatch
                # (including any supervisor watchdog/retry time — the retry
                # share is emitted separately as resolver.retry by
                # fault/resilient.py) plus the injected program time for
                # this batch's bucket. Loop mode splits the same interval:
                # the device-resident share here, the host's enqueue/drain
                # shares as their own segments — the attribution that
                # latency_attribution reassembles for the loop path. A real
                # loop engine behind this service (device_loop service
                # mode) attaches its batch-time loop_stats snapshot —
                # queue/ring occupancy and the sync accounting — to the
                # device_resident span, so a slow batch's trace says
                # whether the ring was backed up when it ran.
                extra = {}
                if loop_mode:
                    snap_fn = getattr(self.engine, "loop_stats_snapshot",
                                      None)
                    snap = snap_fn() if snap_fn is not None else None
                    if snap is not None:
                        extra["loop_stats"] = snap
                    mesh_fn = getattr(self.engine, "mesh_stats_snapshot",
                                      None)
                    if mesh_fn is not None:
                        # mesh engines: shard fan-out + measured exchange
                        # intervals ride the span too, so a slow batch's
                        # trace says what the collectives cost it
                        extra["mesh_stats"] = mesh_fn()
                # keyspace-heat context (core/heatmap.py): the batch-time
                # hot-range pressure rides the device span, so a slow
                # batch's trace says whether the keyspace was hot
                heat_fn = getattr(self.engine, "heat_snapshot", None)
                if heat_fn is not None:
                    heat = heat_fn(brief=True)
                    if heat is not None:
                        extra["heat"] = heat
                span_event("resolver.device_resident" if loop_mode
                           else "resolver.device_dispatch",
                           version, t2, t3, txns=len(transactions),
                           parent="resolver.queue_wait", **extra)
            if loop_mode and self.cfg.result_drain_ms > 0:
                # loop mode: the host's drain share — non-blocking poll +
                # bitmap decode off the result ring
                await delay(self.cfg.result_drain_ms / 1e3,
                            TaskPriority.PROXY_RESOLVER_REPLY)
            if spans_on and loop_mode:
                t3b = span_now()
                span_event("resolver.result_drain", version, t3, t3b,
                           parent="resolver.queue_wait")
                t3 = t3b   # the force tail starts after the drain segment
            if self.batcher is not None:
                # observed device-stage time: injected program time plus any
                # real engine/supervisor stalls (watchdog retries, failover)
                # — exactly what balloons the EWMA and degrades the target
                self.batcher.observe(
                    self.batcher.bucket_of(len(transactions)),
                    (_now() - t_dev) * 1e3)
            if spans_on:
                # verdict materialization / readback tail: zero virtual time
                # in the sim model (readback rides the injected device
                # figure); named so the wall-clock pipeline's real force
                # segment and the sim's line up in attribution output
                span_event("resolver.force", version, t3, span_now(),
                           parent="resolver.queue_wait")
            if self.conflict_sched.enabled and transactions:
                # predictor feedback at the resolution point: every batch
                # trains the doom model, whichever layer admitted it
                self.conflict_sched.observe_batch(
                    list(transactions), verdicts, version)
            return verdicts
        finally:
            # On any exit (including cancellation mid-wait) unblock the
            # successor's device wait and hand the slot on — a wedged chain
            # would stall every later batch forever.
            self._device_done.advance(seq)
            self.release()
