"""LatencyHarness: client-observed commit latency under open-loop load.

The missing measurement behind the headline txn/s numbers (VERDICT r5
weak #2): the 1.34M txn/s point needs 4096-txn batches whose device time
alone is outside the 1.5–2.5 ms commit budget, and nothing measured what
a CLIENT sees when several batches are in flight. This harness drives an
open-loop (Poisson) arrival process through the full e2e sim cluster —
proxy batching, master version chain, pipelined resolver, tlog push,
ordered replies — and reports client-observed commit-latency percentiles
next to sustained throughput.

Time model: the sim runs in virtual time. The resolver's pack and device
service times are INJECTED from on-chip measurements (bench.py measures
them with the same scan methodology as the headline number — this dev
chip's ~100 ms tunnel RTT would otherwise drown every number; production
resolvers sit next to their chip). Every other delay — batching, version
chaining, network hops (fixed datacenter-profile latency), tlog commit —
is the sim cluster's own. Client-observed commit latency is the virtual
time from commit submission to CommitReply, the reference's commit
budget quantity (performance.rst:36,49).

Verdicts come from the reference-exact oracle engine; the TPU engines are
parity-locked to it (parity_configs_ok in bench.py), so the abort profile
matches what the device path would produce.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import math


def p99_budget_ms() -> float:
    """The client-observed p99 commit budget (the resolver-inclusive share
    of the reference's < 3 ms end-to-end commit target). Was a hard-coded
    2.5 in bench.py; now the `resolver_p99_budget_ms` knob, shared with
    the BudgetBatcher's adaptive batch sizing (docs/perf.md) so the bench
    filter and the serving-path batcher can never disagree."""
    from ..core.knobs import SERVER_KNOBS

    return float(SERVER_KNOBS.resolver_p99_budget_ms)


def in_any_window(t: float, windows) -> bool:
    """True when t falls inside any (t0, t1) interval."""
    return any(w0 <= t <= w1 for w0, w1 in windows)


def percentile_index(n: int, p: float) -> int:
    """THE quantile convention every SLO consumer shares (the nearest-rank
    index the harness has always used); one definition so a future change
    to the rule cannot leave two p99s disagreeing over the same data."""
    return min(n - 1, int(p * n))


def percentile_ms(sorted_ms, p: float) -> float:
    """Percentile of an ascending latency list (ms); nan when empty."""
    if not sorted_ms:
        return float("nan")
    return sorted_ms[percentile_index(len(sorted_ms), p)]


def percentile_outside_windows(records, windows, p: float = 0.99):
    """SLO percentile over ack records whose LIFETIME [t_submit,
    t_submit + latency] intersects no excluded window — the chaos
    campaign's assertion primitive (docs/real_cluster.md): p99 must hold
    outside injected-fault windows; inside them the contract is graceful
    degradation, not the budget. Interval intersection (not submit-time
    membership) is the honest filter: a request submitted just before a
    partition but caught inside it is a window casualty, while one
    submitted earlier that completed before the window counts.

    `records` are (t_submit, latency_s, ok, version) tuples — the same
    shape run_latency_under_load accumulates and real/workload.py records.
    Returns (percentile_ms, n_outside); (nan, 0) when nothing qualifies."""
    lat_ms = sorted(
        l * 1e3 for t0, l, _ok, _v in records
        if not any(t0 <= w1 and t0 + l >= w0 for w0, w1 in windows))
    return percentile_ms(lat_ms, p), len(lat_ms)


@dataclass
class HarnessResult:
    depth: int
    batch_txns: int
    device_ms: float
    pack_ms_per_txn: float
    offered_txns_per_sec: float
    #: RESOLVED rate in the steady window (every acked verdict, committed
    #: or not) — the comparable quantity to the bench latency_curve's
    #: verdict-agnostic txns_per_sec
    sustained_txns_per_sec: float
    #: committed-only rate (the workload's conflict profile discounts it)
    sustained_committed_per_sec: float
    p50_ms: float
    p99_ms: float
    committed: int
    conflicted: int
    errors: int
    mean_batch_fill: float
    #: span-based phase decomposition of client-observed latency
    #: (collect_spans=True; docs/observability.md)
    attribution: Optional[dict] = None

    def as_dict(self) -> dict:
        out = {
            "depth": self.depth,
            "batch_txns": self.batch_txns,
            "device_ms": round(self.device_ms, 4),
            "offered_txns_per_sec": round(self.offered_txns_per_sec, 1),
            "sustained_txns_per_sec": round(self.sustained_txns_per_sec, 1),
            "sustained_committed_per_sec": round(self.sustained_committed_per_sec, 1),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "committed": self.committed,
            "conflicted": self.conflicted,
            "errors": self.errors,
            "mean_batch_fill": round(self.mean_batch_fill, 1),
        }
        if self.attribution is not None:
            out["attribution"] = self.attribution
        return out


#: the named phase segments a client-observed commit latency decomposes
#: into (docs/observability.md). Together they PARTITION the submit->reply
#: interval: batch_wait and the two residuals (resolve_overhead: resolver
#: RPC time outside the resolver's own spans; reply_net: phase-5 reply
#: delivery) absorb network/marshalling, so the segment sum equals the
#: client-observed latency by construction — what the acceptance check
#: verifies end to end through real span timestamps.
ATTRIBUTION_SEGMENTS = (
    "batch_wait",        # client submit -> proxy commit batch dispatched
    "get_version",       # proxy phase 1: master version fetch (+ batch order)
    "queue_wait",        # resolver: version chain + service window slot
    "host_pack",         # resolver service: host pack stage
    "pipeline_wait",     # resolver service: in-order device chain wait
    "device_dispatch",   # step dispatch: device program (retry share removed)
    "queue_enqueue",     # device loop: slot pack + async dispatch (no sync)
    "device_resident",   # device loop: on-device server-step share
    "result_drain",      # device loop: non-blocking abort-bitmap drain
    "retry",             # supervisor watchdog retries (fault/resilient.py)
    "force",             # verdict materialization / readback tail
    "resolve_overhead",  # resolver RPC residual: network + marshalling
    "meta_drain",        # proxy phase 3.5: metadata stream drain
    "log_push",          # proxy phase 4: tlog push (+ logging order wait)
    "reply_net",         # phase 5 reply delivery back to the client
    "device_time",       # OVERLAY: sampled measured enqueue->ready device
                         # interval (ops/host_engine.py, the
                         # resolver_device_time_sample_rate knob) — its
                         # own Chrome device track; overlaps
                         # device_dispatch/device_resident, so the
                         # partition sum excludes it (OVERLAY_SEGMENTS)
)

#: segments that are measured OVERLAYS of the partition, not members of
#: it: they ride the attribution tables and the Chrome export but are
#: excluded from the telescoping sum — including them would double-count
#: the device interval they overlap and break the sum identity.
OVERLAY_SEGMENTS = ("device_time",)


def _attribute(records, by_trace) -> Optional[dict]:
    """Per-txn phase decomposition from the span record (core/trace.py).

    `records` are steady-window (submit_t, latency_s, committed?, version)
    acks; `by_trace` maps a commit version to its summed span durations.
    Only committed acks with a complete span set attribute (a conflict
    verdict has no CommitReply version to join on)."""
    rows = []
    for t0, lat, ok, v in records:
        if not ok or v is None:
            continue
        tr = by_trace.get(v)
        if tr is None:
            continue
        if any(k not in tr for k in ("proxy.commit_batch.t0",
                                     "proxy.get_version", "proxy.resolve_rpc",
                                     "proxy.meta_drain", "proxy.log_push")):
            continue
        qw = tr.get("resolver.queue_wait", 0.0)
        hp = tr.get("resolver.host_pack", 0.0)
        pw = tr.get("resolver.pipeline_wait", 0.0)
        dd = tr.get("resolver.device_dispatch", 0.0)
        # device-loop dispatch (docs/perf.md "Device-resident loop"): the
        # device_dispatch interval splits into enqueue / device-resident /
        # drain segments; a step-dispatch run carries zeros here (and vice
        # versa), so the partition identity holds in either mode
        qe = tr.get("resolver.queue_enqueue", 0.0)
        dr = tr.get("resolver.device_resident", 0.0)
        rd = tr.get("resolver.result_drain", 0.0)
        fc = tr.get("resolver.force", 0.0)
        rt = tr.get("resolver.retry", 0.0)
        seg = {
            "batch_wait": tr["proxy.commit_batch.t0"] - t0,
            "get_version": tr["proxy.get_version"],
            "queue_wait": qw,
            "host_pack": hp,
            "pipeline_wait": pw,
            "device_dispatch": (dd - rt) if dd else 0.0,
            "queue_enqueue": qe,
            "device_resident": (dr - rt) if dr else 0.0,
            "result_drain": rd,
            "retry": rt,
            "force": fc,
            "resolve_overhead": tr["proxy.resolve_rpc"]
                - (qw + hp + pw + dd + qe + dr + rd + fc),
            "meta_drain": tr["proxy.meta_drain"],
            "log_push": tr["proxy.log_push"],
        }
        seg["reply_net"] = lat - sum(seg.values())
        # overlay segments join AFTER the partition closed over reply_net:
        # they are reported, never summed (OVERLAY_SEGMENTS)
        seg["device_time"] = tr.get("engine.device_time", 0.0)
        rows.append((lat, seg))
    if not rows:
        return None
    rows.sort(key=lambda r: r[0])

    def at(p: float) -> dict:
        idx = percentile_index(len(rows), p)
        w = max(1, int(0.02 * len(rows)))
        sel = rows[max(0, idx - w): idx + w + 1]
        segs = {k: sum(s[k] for _, s in sel) / len(sel) * 1e3
                for k in ATTRIBUTION_SEGMENTS}
        client = sum(l for l, _ in sel) / len(sel) * 1e3
        total = sum(v for k, v in segs.items()
                    if k not in OVERLAY_SEGMENTS)
        for k in OVERLAY_SEGMENTS:
            # an overlay nobody measured is not a 0ms measurement — the
            # sim harness injects device time and emits no engine spans,
            # so a structural 0.0 row would read as a (wrong) figure
            if not segs.get(k):
                segs.pop(k, None)
        return {
            "client_ms": round(client, 4),
            "segments_ms": {k: round(v, 4) for k, v in segs.items()},
            "sum_ms": round(total, 4),
            "sum_over_client": round(total / client, 4) if client else None,
        }

    return {
        "n_attributed": len(rows),
        "segments": list(ATTRIBUTION_SEGMENTS),
        "p50": at(0.50),
        "p99": at(0.99),
        "mean": at(0.50) if len(rows) < 3 else {
            "client_ms": round(sum(l for l, _ in rows) / len(rows) * 1e3, 4),
            "segments_ms": {
                k: round(sum(s[k] for _, s in rows) / len(rows) * 1e3, 4)
                for k in ATTRIBUTION_SEGMENTS
                if k not in OVERLAY_SEGMENTS
                or any(s[k] for _, s in rows)},
        },
    }


def run_latency_under_load(
    *,
    depth: int,
    batch_txns: int,
    device_ms: float,
    pack_ms_per_txn: float,
    offered_txns_per_sec: float,
    n_txns: int = 20_000,
    warmup_frac: float = 0.25,
    seed: int = 2026,
    pool: int = 8192,
    reads_per_txn: int = 2,
    writes_per_txn: int = 2,
    net_latency_ms: float = 0.01,
    fsync_ms: float = 0.05,
    snapshot_refresh_ms: float = 0.2,
    sim_timeout_s: float = 120.0,
    proxy_window: Optional[int] = None,
    batch_interval_ms: Optional[float] = None,
    device_ms_by_bucket: Optional[Dict[int, float]] = None,
    budget_ms: Optional[float] = None,
    search_mode_by_bucket: Optional[Dict[int, str]] = None,
    dispatch_mode: str = "step",
    queue_enqueue_ms: float = 0.0,
    result_drain_ms: float = 0.0,
    collect_spans: bool = False,
    engine_factory=None,
    resilient: bool = False,
) -> HarnessResult:
    """One harness point: an e2e sim cluster whose resolver runs the
    pipelined service at `depth` with the given measured service times,
    under open-loop Poisson arrivals at `offered_txns_per_sec`.

    The arrival process is OPEN-LOOP (Harmonia-style offered load): a txn
    is submitted at its arrival time regardless of outstanding ones, so
    queueing shows up as latency, never as reduced offered load. The
    workload is the bench shape — `reads_per_txn` point reads +
    `writes_per_txn` point writes over a `pool`-key hot pool, snapshots
    from a client-side cached read version refreshed every
    `snapshot_refresh_ms` (a GRV cache, so commit latency is measured
    from commit submission like the reference's commit budget).

    `collect_spans=True` turns on commit-path span collection
    (core/trace.py) for the run and attaches a `latency_attribution`
    decomposition to the result: named phase segments that sum to the
    client-observed latency (docs/observability.md). `engine_factory` /
    `resilient` override the resolver's conflict engine (e.g. a
    FaultInjectingEngine under the ResilientEngine supervisor, to measure
    what watchdog retries do to the decomposition)."""
    # Imported here: the harness pulls in the whole sim cluster, and
    # bench.py imports this module lazily.
    from ..core import buggify
    from ..core.knobs import SERVER_KNOBS
    from ..core.types import CommitTransaction, KeyRange
    from ..sim.loop import Promise, TaskPriority, delay, now, set_scheduler
    from ..sim.network import Endpoint
    from ..sim.simulator import Simulator
    from ..server.cluster import Cluster, ClusterConfig
    from ..server.messages import CommitTransactionRequest
    from ..server.proxy import COMMIT_TOKEN, COMMITTED_VERSION_TOKEN
    from .service import PipelineConfig

    from ..core.trace import g_spans
    from ..ops.oracle import OracleConflictEngine

    sim = Simulator(seed)
    spans_were_enabled = g_spans.enabled
    # Benchmark profile: no fault injection, fixed datacenter-scale hops
    # (in-rack RTT), NVMe-class tlog fsync, and a device-paced batch
    # deadline. The reference's dynamic batcher tunes its interval to track
    # the commit pipeline's service rate; for a pipelined TPU resolver the
    # natural operating point is one batch per device program — closing
    # batches faster than the device drains them only deepens the queue,
    # closing slower starves it — so the auto interval is the measured
    # device time plus a small dispatch margin.
    if batch_interval_ms is None:
        batch_interval_ms = max(0.2, 1.04 * device_ms)
    buggify.disable()
    sim.net.min_latency = sim.net.max_latency = net_latency_ms / 1e3
    saved_knobs = {
        "commit_transaction_batch_interval":
            SERVER_KNOBS.commit_transaction_batch_interval,
        "tlog_fsync_seconds": SERVER_KNOBS.tlog_fsync_seconds,
    }
    SERVER_KNOBS._values["commit_transaction_batch_interval"] = batch_interval_ms / 1e3
    SERVER_KNOBS._values["tlog_fsync_seconds"] = fsync_ms / 1e3

    cluster = Cluster(sim, ClusterConfig(
        n_resolvers=1,
        n_proxies=1,
        n_storage=2,
        engine_factory=engine_factory or OracleConflictEngine,
        resilient_resolver=resilient,
        resolver_pipeline=PipelineConfig(
            depth=depth,
            pack_ms_per_txn=pack_ms_per_txn,
            device_ms_per_batch=device_ms,
            max_batch_txns=batch_txns,
            # bucket ladder (docs/perf.md): a batch pays its own bucket's
            # measured device time, and the service's BudgetBatcher reports
            # the adaptive target that — via ratekeeper — caps the proxy's
            # commit batches to the largest in-budget bucket
            device_ms_by_bucket=device_ms_by_bucket,
            p99_budget_ms=budget_ms,
            # per-(bucket, mode) EWMA keying (docs/perf.md history search
            # modes); None = whatever the resolver engine reports
            search_mode_by_bucket=search_mode_by_bucket,
            # device-loop dispatch model (docs/perf.md "Device-resident
            # loop"): splits the device span into enqueue / resident /
            # drain segments with the given injected host shares
            dispatch_mode=dispatch_mode,
            queue_enqueue_ms=queue_enqueue_ms,
            result_drain_ms=result_drain_ms,
        ),
        max_commit_batch=batch_txns,
        # One slot beyond the service depth: `depth` batches in service at
        # the resolver plus one accumulating/in transit at the proxy.
        commit_pipeline_window=proxy_window or depth + 1,
    ))
    net = sim.net
    client = sim.new_process("latency-client")
    proxy_addr = cluster.proxy_proc.address
    commit_ep = Endpoint(proxy_addr, COMMIT_TOKEN)
    cv_ep = Endpoint(proxy_addr, COMMITTED_VERSION_TOKEN)
    rng = sim.sched.rng

    lam = offered_txns_per_sec
    cached_version = [cluster.cfg.start_version]
    #: (submit_time, latency_s, committed?, commit version | None)
    latencies: list = []
    counts = {"committed": 0, "conflicted": 0, "errors": 0, "acked": 0}
    done = Promise()

    async def version_cache() -> None:
        """Client-side GRV cache (the staleness a real client's batched
        GRV would have at this refresh interval)."""
        while not done.is_set:
            try:
                v = await net.request(client.address, cv_ep, None,
                                      TaskPriority.PROXY_GRV_TIMER, timeout=1.0)
                cached_version[0] = max(cached_version[0], v)
            except Exception:
                pass
            await delay(snapshot_refresh_ms / 1e3, TaskPriority.PROXY_GRV_TIMER)

    def make_txn() -> CommitTransaction:
        t = CommitTransaction(read_snapshot=cached_version[0])
        for _ in range(reads_per_txn):
            k = b"lat/%010d" % rng.random_int(0, pool)
            t.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
        for _ in range(writes_per_txn):
            k = b"lat/%010d" % rng.random_int(0, pool)
            t.set(k, b"v" * 8)
        return t

    async def one_txn() -> None:
        from ..core import error as _error

        t0 = now()
        ok = False
        version = None
        try:
            reply = await net.request(client.address, commit_ep,
                                      CommitTransactionRequest(make_txn()),
                                      TaskPriority.PROXY_COMMIT, timeout=30.0)
            ok = True
            version = getattr(reply, "version", None)
            counts["committed"] += 1
        except _error.FDBError as e:
            # a conflict verdict is a real reply (its latency is honest);
            # anything else is a transport/cluster error
            if e.name in ("not_committed", "transaction_too_old"):
                counts["conflicted"] += 1
            else:
                counts["errors"] += 1
        latencies.append((t0, now() - t0, ok, version))
        counts["acked"] += 1
        if counts["acked"] >= n_txns and not done.is_set:
            done.send(None)

    async def generator() -> None:
        for _ in range(n_txns):
            # exponential interarrival: open-loop Poisson at rate lam
            u = rng.random01()
            await delay(-math.log(max(u, 1e-12)) / lam,
                        TaskPriority.DEFAULT_DELAY)
            sim.sched.spawn(one_txn(), TaskPriority.DEFAULT_DELAY)

    if collect_spans:
        # enabled just before the run (restored in the finally below):
        # the instrumentation only matters while the sim executes
        g_spans.enabled = True
        g_spans.clear()
    try:
        from ..core import error as _error

        sim.sched.spawn(version_cache(), TaskPriority.PROXY_GRV_TIMER)
        sim.sched.spawn(generator(), TaskPriority.DEFAULT_DELAY)
        try:
            sim.run_until(done.future, until=sim_timeout_s)
        except _error.FDBError:
            pass   # saturated point: report whatever acked in the window
    finally:
        for name, val in saved_knobs.items():
            SERVER_KNOBS._values[name] = val
        set_scheduler(None)
        # restore here, not after attribution: an exception mid-run (sim
        # timeout, cluster build failure) must not leak collection enabled
        # into the rest of the process; the recorded spans survive for the
        # attribution pass below
        if collect_spans:
            g_spans.enabled = spans_were_enabled

    # Steady-state window: drop the warmup head (pipeline fill, empty
    # tables, cold batcher) before computing percentiles and throughput.
    latencies.sort(key=lambda r: r[0])
    skip = int(len(latencies) * warmup_frac)
    window = latencies[skip:]
    if not window:
        window = latencies
    attribution = None
    if collect_spans:
        attribution = _attribute(window, g_spans.durations_by_trace())
    # Percentiles over EVERY acked reply, committed or conflicted — the
    # same population the sustained rate counts (a conflict verdict rides
    # the full commit path and is an honest client-observed latency).
    lat_ms = sorted(l * 1e3 for _, l, _ok, _v in window)
    span = window[-1][0] - window[0][0] if len(window) > 1 else 1.0
    sustained = len(window) / max(span, 1e-9)
    sustained_committed = sum(1 for _, _, ok, _v in window if ok) / max(span, 1e-9)

    def pct(p: float) -> float:
        return percentile_ms(lat_ms, p)

    stats = cluster.resolvers[0].stats.as_dict()
    n_batches = max(1, stats.get("batches_resolved", 1))
    return HarnessResult(
        depth=depth,
        batch_txns=batch_txns,
        device_ms=device_ms,
        pack_ms_per_txn=pack_ms_per_txn,
        offered_txns_per_sec=lam,
        sustained_txns_per_sec=sustained,
        sustained_committed_per_sec=sustained_committed,
        p50_ms=pct(0.50),
        p99_ms=pct(0.99),
        committed=counts["committed"],
        conflicted=counts["conflicted"],
        errors=counts["errors"],
        mean_batch_fill=stats.get("txns_in", 0) / n_batches,
        attribution=attribution,
    )
