"""Device-resident resolver loop: a persistent on-device batch server.

PR 5 left the production point (512-txn batches, ~1.46 ms end to end)
dispatch-shaped, not compute-shaped: every batch still paid a host->device
program launch plus a BLOCKING readback of its verdicts before the next
batch could advance. This module moves the steady state onto the device —
the SmartNIC-DPA move from PAPERS.md (push ordered-KV conflict work next
to the data path; the host does I/O only), Harmonia's "stop synchronizing
with the coordinator per request" applied to the accelerator link:

  * the interval-table state lives on device and is owned by the server
    step `conflict_kernel.resolve_server_loop` — a `lax.while_loop` that
    consumes the filled prefix of a Q-chunk packed batch queue slot under
    ONE dispatch (chunk count is a runtime scalar, so one AOT program per
    ladder bucket serves every fill level; state is donated to the step
    off-CPU, so the table never round-trips);
  * a DOUBLE-BUFFERED device queue: `LoopSlotPool` keeps `queue_depth`
    pinned host slot buffer sets per bucket shape — while slot A's
    program runs asynchronously on the device, the host packs the next
    batch's columns into slot B (`HostPackArena` feeds the chunk arrays;
    the slot copy is the enqueue's device_put payload). A slot is reused
    only after its program's outputs landed — the zero-copy keepalive
    contract, enforced by the pool;
  * a RESULT RING the host drains WITHOUT forcing a sync per batch: the
    server step emits packed abort bitmaps (committed/too-old bit planes,
    `status_words` — a 16x smaller readback than [T] int32 statuses),
    and `poll()` decodes exactly the ready prefix via the non-blocking
    `jax.Array.is_ready()` probe. Steady-state host work per batch is
    therefore: pack columns into a slot, dispatch (async), poll.

Sync accounting (the "zero blocking host syncs" acceptance):
`loop_stats` counts every drain by kind — `drained_nonblocking` (result
was ready when the host looked), `forced_waits` (the host needed a result
that had not landed yet and poll-waited for readiness — the depth-1 /
drain path), and `blocking_syncs` (the poll-wait deadline expired and the
host fell back to a genuinely blocking device sync; 0 in any healthy
run). `make bench-smoke` asserts blocking_syncs == 0 and a fully
non-blocking drain of a pipelined drive; tools/floor_bench.run_loop_floor
compares per-batch host time step vs loop at the production point.

Failure/rebuild contract (docs/fault_tolerance.md): `drain_loop()` blocks
until every in-flight slot's results landed and runs before anything
touches the donated table from the host — enforced ENGINE-SIDE inside
`clear()` (which is how `fault/resilient.py`'s shadow rebuild quiesces
the loop before replaying the committed write history into it) and the
split-step long-key path, so callers never carry the invariant. Failover
collapses to step dispatch: the ResilientEngine's CPU oracle serves while
the loop's table is rebuilt, bit-identically (tests/test_device_loop.py).

Exactness: the loop body IS resolve_step — same programs phase for phase
— and the bitmap decode is the same pure function of (committed,
t_too_old) as `status_of`, so abort sets are bit-identical to the
step-dispatch engines and the CPU oracle (the parity suite drives both
across bucket boundaries, GC cadences and failover).
"""
from __future__ import annotations

import dataclasses
import functools
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import telemetry
from ..core.knobs import SERVER_KNOBS
from ..core.types import TransactionCommitResult, Version
from . import conflict_kernel as ck
from .conflict_kernel import KernelConfig
from .host_engine import JaxConflictEngine, donate_state_kwargs

#: legal values of the `resolver_device_loop` knob: "" (off — the router
#: keeps step dispatch), "on" (loop engine, xla fixpoint), "pallas" (loop
#: engine with the fused Pallas fixpoint baked into every loop body —
#: interpreter fallback off-TPU, where the 0.4.3x dtype workaround in
#: ops/fixpoint_pallas.py applies)
DEVICE_LOOP_MODES = ("", "on", "pallas")


def device_loop_requested() -> bool:
    """True iff the `resolver_device_loop` knob asks for the loop engine."""
    return bool(_loop_knob())


def _loop_knob() -> str:
    raw = str(getattr(SERVER_KNOBS, "resolver_device_loop", "") or "").strip()
    if raw not in DEVICE_LOOP_MODES:
        raise ValueError(
            f"unknown resolver_device_loop mode {raw!r}; expected one of "
            f"{DEVICE_LOOP_MODES}")
    return raw


def loop_kernel_config(cfg: KernelConfig) -> KernelConfig:
    """Fold the `resolver_device_loop` knob into the loop engine's config:
    "pallas" revives ops/fixpoint_pallas.py inside the loop bodies — the
    fused sort+search+fixpoint chain runs as resolve_step's phases with
    the commit fixpoint a single fused kernel instead of ~5 launch-bound
    while_loop iterations. Off-TPU the interpreter fallback applies (the
    int32-cast workaround makes it run rather than xfail); an explicit
    non-xla cfg.fixpoint is always respected."""
    if _loop_knob() != "pallas" or cfg.fixpoint != "xla":
        return cfg
    from . import fixpoint_pallas as fp

    if not fp.supported(cfg):
        return cfg
    fixpoint = ("pallas" if jax.default_backend() == "tpu"
                else "pallas_interpret")
    return dataclasses.replace(cfg, fixpoint=fixpoint)


def decode_status_bits(commit_words: np.ndarray, too_words: np.ndarray,
                       n_txns: int) -> np.ndarray:
    """[C, status_words] committed/too-old bit planes -> [C, T] int32
    statuses. The same pure function of (committed, t_too_old) as
    conflict_kernel.status_of, so decoded abort sets are bit-identical to
    the step path's."""
    idx = np.arange(n_txns)
    w, b = idx >> 5, (idx & 31).astype(np.uint32)
    commit = (commit_words[:, w] >> b) & 1
    too = (too_words[:, w] >> b) & 1
    return np.where(
        too, np.int32(int(TransactionCommitResult.TOO_OLD)),
        np.where(commit, np.int32(int(TransactionCommitResult.COMMITTED)),
                 np.int32(int(TransactionCommitResult.CONFLICT)))
    ).astype(np.int32)


class _LoopTicket:
    """One dispatched queue slot's place in the result ring."""

    __slots__ = ("commit_dev", "too_dev", "ov_dev", "heat_dev", "heat_base",
                 "heat_version", "n_txns", "n_chunks", "slot", "status",
                 "overflow", "done", "sample")

    def __init__(self, commit_dev, too_dev, ov_dev, n_txns: int,
                 n_chunks: int, slot: "_LoopSlot", heat_dev=None,
                 heat_base: int = 0, heat_version=None):
        self.commit_dev = commit_dev
        self.too_dev = too_dev
        self.ov_dev = ov_dev
        #: the slot's stacked [Q, ...] heat planes (None when heat is off);
        #: decoded alongside the bitmaps in the same non-blocking drain
        self.heat_dev = heat_dev
        self.heat_base = heat_base
        self.heat_version = heat_version
        self.n_txns = n_txns
        self.n_chunks = n_chunks
        self.slot = slot
        self.status: Optional[np.ndarray] = None
        self.overflow = False
        self.done = False
        #: sampled device timing (t0_wall, t0_span, version) or None —
        #: stamped at enqueue, recorded when _finish sees the results
        self.sample = None

    def ready(self) -> bool:
        """Non-blocking: have this slot's abort bitmaps (and heat planes,
        when heat is on) landed?"""
        r = (self.commit_dev.is_ready() and self.too_dev.is_ready()
             and self.ov_dev.is_ready())
        if r and self.heat_dev is not None:
            r = all(v.is_ready() for v in self.heat_dev.values())
        return r


class _LoopSlot:
    """One pinned host buffer set for a Q-chunk queue slot: the arrays a
    dispatched server step reads (zero-copy on backends that alias
    well-aligned numpy inputs), reused only after its program completed."""

    __slots__ = ("arrays", "ticket")

    def __init__(self, cfg: KernelConfig, q: int):
        self.arrays: Dict[str, np.ndarray] = {
            name: np.zeros(s.shape, s.dtype)
            for name, s in ck.batch_struct(cfg, stack=(q,)).items()}
        self.ticket: Optional[_LoopTicket] = None

    def fill(self, chunks: List[Dict[str, np.ndarray]]) -> None:
        for i, chunk in enumerate(chunks):
            for name, dst in self.arrays.items():
                dst[i] = chunk[name]


class LoopSlotPool:
    """`queue_depth` slots per bucket shape, handed out round-robin — the
    double buffer: the host packs into one slot while the other's program
    is still in flight. acquire() hands back a slot only once its previous
    ticket drained (the engine drains through it first)."""

    def __init__(self, queue_depth: int, slot_chunks: int):
        self.queue_depth = max(2, int(queue_depth))
        self.slot_chunks = max(1, int(slot_chunks))
        self._slots: Dict[int, List[_LoopSlot]] = {}
        self._next: Dict[int, int] = {}

    def acquire(self, bucket: KernelConfig) -> _LoopSlot:
        key = bucket.max_txns
        slots = self._slots.get(key)
        if slots is None:
            slots = [_LoopSlot(bucket, self.slot_chunks)
                     for _ in range(self.queue_depth)]
            self._slots[key] = slots
            self._next[key] = 0
        i = self._next[key]
        self._next[key] = (i + 1) % len(slots)
        return slots[i]


class DeviceLoopEngine(JaxConflictEngine):
    """Fourth engine mode (alongside Jax / Subsharded / mesh-Sharded):
    step dispatch replaced by the device-resident server loop. Drop-in for
    JaxConflictEngine everywhere — resolve(), the columnar pack/dispatch
    split the ResolverPipeline drives, the ladder/warmup contract, the
    split-step long-key path (which drains the loop first) — with
    bit-identical abort sets and `dispatch_mode = "loop"` telemetry."""

    name = "device_loop"
    dispatch_mode = "loop"

    def __init__(self, cfg: KernelConfig = KernelConfig(),
                 initial_version: Version = 0,
                 ladder: Optional[Sequence[int]] = None,
                 arena: bool = True,
                 history_search: Optional[str] = None,
                 heat_buckets: Optional[int] = None,
                 device_time_sample_rate: Optional[float] = None,
                 queue_slots: int = 4,
                 queue_depth: int = 2,
                 drain_deadline_s: float = 5.0,
                 history_structure: Optional[str] = None):
        #: chunks per queue slot (Q): one compiled loop body per bucket
        #: serves any fill 1..Q, so Q bounds chunks-per-dispatch, not
        #: compile count
        self.queue_slots = max(1, int(queue_slots))
        self._pool = LoopSlotPool(queue_depth, self.queue_slots)
        #: FIFO of dispatched-but-undrained tickets — the result ring
        self._ring: deque = deque()
        self.drain_deadline_s = drain_deadline_s
        #: the sync-accounting shim (module docstring): every drain files
        #: under exactly one of the three kinds
        self.loop_stats = {"enqueued_chunks": 0, "units": 0,
                           "drained_nonblocking": 0, "forced_waits": 0,
                           "blocking_syncs": 0, "wait_ms": 0.0,
                           #: measured host shares per side of the loop —
                           #: what bench.py injects as the sim service's
                           #: queue_enqueue_ms / result_drain_ms
                           "enqueue_ms": 0.0, "decode_ms": 0.0}
        #: armed by _dispatch_sampled for the ticket the next
        #: _dispatch_unit creates (sampled device timing: readiness is
        #: discovered in poll()/_finish, so the ticket carries the stamp)
        self._sample_pending = None
        super().__init__(loop_kernel_config(cfg),
                         initial_version=initial_version, ladder=ladder,
                         scan_sizes=(), arena=arena,
                         history_search=history_search,
                         heat_buckets=heat_buckets,
                         device_time_sample_rate=device_time_sample_rate,
                         history_structure=history_structure)
        # the loop's queue/ring gauges flow into the unified telemetry hub
        # (docs/observability.md): `loop.<label>.*` series alongside the
        # EnginePerf counters the base class registered above
        self._loop_telemetry_label = telemetry.hub().register_loop(
            self, name=self.name)

    # -- telemetry ------------------------------------------------------------
    def ring_depth(self) -> int:
        """Dispatched-but-undrained tickets in the result ring."""
        return len(self._ring)

    def slots_in_flight(self) -> int:
        """Queue slots whose program may still read their host buffers —
        the occupancy side of the double buffer."""
        return sum(1 for slots in self._pool._slots.values() for s in slots
                   if s.ticket is not None and not s.ticket.done)

    def loop_stats_snapshot(self) -> Dict[str, float]:
        """One batch-attachable snapshot of the sync accounting plus the
        live queue/ring occupancy gauges — what rides the
        `resolver.device_resident` / `engine.result_drain` spans and the
        flight recorder's per-dispatch records."""
        snap = {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self.loop_stats.items()}
        snap["ring_depth"] = self.ring_depth()
        snap["slots_in_flight"] = self.slots_in_flight()
        return snap

    # -- programs ------------------------------------------------------------
    def _program(self, bucket: KernelConfig, n_chunks: int):
        # every chunk count maps to the ONE loop body per bucket (the fill
        # level is a runtime scalar) — warmup() therefore compiles exactly
        # len(buckets) programs
        key = (bucket.max_txns, -1)
        prog = self._programs.get(key)
        if prog is None:
            # _build_and_record times the build and files it in the
            # compile & memory ledger exactly like the step engines
            prog = self._build_and_record(bucket, self.queue_slots)
            self._programs[key] = prog
        return prog

    def _make_program(self, bucket: KernelConfig, n_chunks: int):
        fn = functools.partial(ck.resolve_server_loop, bucket)
        st = ck.state_struct(bucket)
        bt = ck.batch_struct(bucket, stack=(self.queue_slots,))
        nc = jax.ShapeDtypeStruct((), jnp.int32)
        return jax.jit(fn, **donate_state_kwargs()).lower(st, bt, nc).compile()

    def _split_run(self, n: int) -> List[int]:
        """Same-bucket runs split into queue-slot fills (≤ Q chunks each);
        no scan-size ladder — the loop body takes any fill level."""
        out = [self.queue_slots] * (n // self.queue_slots)
        if n % self.queue_slots:
            out.append(n % self.queue_slots)
        return out

    # -- enqueue / result ring -----------------------------------------------
    def _dispatch_unit(self, bucket: KernelConfig,
                       per_chunks: List[List[Dict[str, np.ndarray]]]):
        C = len(per_chunks)
        assert C <= self.queue_slots
        prog = self._program(bucket, C)
        slot = self._acquire_slot(bucket)
        t_enq = time.perf_counter()
        # the enqueue: pack the chunks' columns into the pinned slot (the
        # chunk arrays came from the HostPackArena; after this copy the
        # device program reads the SLOT, so arena leases are only pinned
        # by the base force contract, never by the loop)
        slot.fill([per[0] for per in per_chunks])
        self.state, out = prog(self.state, slot.arrays, np.int32(C))
        self.loop_stats["enqueue_ms"] += (time.perf_counter() - t_enq) * 1e3
        ticket = _LoopTicket(out["commit_bits"], out["too_old_bits"],
                             out["overflow"], bucket.max_txns, C, slot,
                             heat_dev=out.get("heat"), heat_base=self.base,
                             heat_version=self._heat_version)
        ticket.sample = self._sample_pending
        self._sample_pending = None
        slot.ticket = ticket
        self._ring.append(ticket)
        self.loop_stats["units"] += 1
        self.loop_stats["enqueued_chunks"] += C
        # steady-state non-blocking poll: decode whatever already landed
        self.poll()

        def force() -> Tuple[np.ndarray, bool]:
            self._drain_through(ticket)
            return ticket.status, ticket.overflow

        return force

    def _dispatch_sampled(self, bucket: KernelConfig, per_chunks):
        """Loop-mode sampled device timing: the enqueue stamp rides the
        TICKET and is recorded in _finish — when the non-blocking drain
        actually sees the results — not at force() time, which in steady
        state runs long after the results landed in the ring."""
        from ..core.trace import g_spans, span_now

        self._sample_pending = (time.perf_counter(),
                                span_now() if g_spans.enabled else 0.0,
                                self._heat_version)
        try:
            return self._dispatch_unit(bucket, per_chunks)
        finally:
            self._sample_pending = None

    def _acquire_slot(self, bucket: KernelConfig) -> _LoopSlot:
        slot = self._pool.acquire(bucket)
        if slot.ticket is not None and not slot.ticket.done:
            # the double buffer wrapped around onto a still-in-flight slot:
            # drain through its ticket before overwriting the arrays the
            # device may still read (steady state never hits this — by the
            # time the host wraps, that program finished)
            self._drain_through(slot.ticket)
        return slot

    def poll(self) -> int:
        """Drain the READY prefix of the result ring — the non-blocking
        steady-state path. Returns the number of tickets completed."""
        n = 0
        while self._ring and self._ring[0].ready():
            self._finish(self._ring.popleft())
            self.loop_stats["drained_nonblocking"] += 1
            n += 1
        return n

    def drain_loop(self) -> None:
        """Block until every in-flight slot drained — the explicit barrier
        before host code touches the donated table (clear, shadow rebuild,
        split-step long-key path)."""
        if self._ring:
            self._drain_through(self._ring[-1])

    def _drain_through(self, ticket: _LoopTicket) -> None:
        while not ticket.done:
            head = self._ring[0]
            if not head.ready():
                # the host needs a result that has not landed: poll-wait
                # for readiness (the host is never inside a device sync
                # call and could pack; only the deadline fallback is a
                # true blocking sync)
                self.loop_stats["forced_waits"] += 1
                t0 = time.perf_counter()
                deadline = t0 + self.drain_deadline_s
                while not head.ready() and time.perf_counter() < deadline:
                    time.sleep(2e-5)
                self.loop_stats["wait_ms"] += (time.perf_counter() - t0) * 1e3
                if not head.ready():
                    self.loop_stats["blocking_syncs"] += 1
            self._finish(self._ring.popleft())

    # fdbtpu-lint: drain-point — only reached once ticket.ready() (or the
    # deadline fallback, which loop_stats charges as a blocking sync): the
    # asarray below copies a COMPLETED buffer, it never parks in the device
    def _finish(self, ticket: _LoopTicket) -> None:
        t_dec = time.perf_counter()
        commit = np.asarray(ticket.commit_dev)[:ticket.n_chunks]
        too = np.asarray(ticket.too_dev)[:ticket.n_chunks]
        ticket.status = decode_status_bits(commit, too, ticket.n_txns)
        ticket.overflow = bool(np.asarray(ticket.ov_dev))
        if ticket.heat_dev is not None:
            # heat planes landed with the same program's outputs: merge the
            # filled prefix into the aggregator (still no blocking sync —
            # the bitmaps above were already ready)
            self._merge_heat(
                {k: np.asarray(v)[:ticket.n_chunks]
                 for k, v in ticket.heat_dev.items()},
                version=ticket.heat_version, base=ticket.heat_base,
                layout="c")
        self.loop_stats["decode_ms"] += (time.perf_counter() - t_dec) * 1e3
        if ticket.sample is not None:
            # sampled enqueue->ready interval: the results were ALREADY
            # ready when this drain decoded them, so the clock reads add
            # no sync — the loop's zero-blocking-sync contract holds with
            # sampling enabled (tests/test_perf_ledger.py pins it)
            t0_wall, t0_span, version = ticket.sample
            ticket.sample = None
            self._record_device_sample(ticket.n_txns, ticket.n_chunks,
                                       t0_wall, t0_span, version)
        ticket.done = True
        if ticket.slot.ticket is ticket:
            ticket.slot.ticket = None
        ticket.commit_dev = ticket.too_dev = ticket.ov_dev = None
        ticket.heat_dev = None

    # -- host access to the donated table ------------------------------------
    def _reset_device_state(self, version_rel: int) -> None:
        if getattr(self, "_ring", None):
            self.drain_loop()
        super()._reset_device_state(version_rel)

    def _device_states_for_snapshot(self):
        # quiesce the loop first: an in-flight slot's program still owns
        # the (donated) table, and a run snapshot must see a consistent
        # post-apply state
        self.drain_loop()
        return super()._device_states_for_snapshot()

    def _run_detect(self, per_shard):
        # split-step (long-key tier) path reads/writes self.state through
        # the detect/fix/apply jits: the loop must be quiesced first
        self.drain_loop()
        return super()._run_detect(per_shard)
