"""Reference-exact conflict-resolution oracle (pure Python).

This is the *logical model* of the reference resolver's versioned skip list
(fdbserver/SkipList.cpp). The skip list's observable state is a
piecewise-constant map key -> Version ("the last write version of the
interval containing this key") plus a scalar oldestVersion; per-batch verdicts
{CONFLICT, TOO_OLD, COMMITTED} are a pure function of that model:

  1. too-old check at add time          (SkipList.cpp:985)
  2. reads vs. history                  (checkReadConflictRanges:1210)
  3. intra-batch sweep in index order   (checkIntraBatchConflicts:1133)
  4. write union of committed txns applied at version `now`
                                        (combineWriteConflictRanges:1320,
                                         mergeWriteConflictRanges:1260)
  5. oldestVersion advance + GC         (detectConflicts:1199-1206)

The oracle exists to pin the TPU kernel's outputs bit-for-bit: every engine
(JAX, native C++) must match it on every stream. GC (removeBefore:665) only
changes the *representation* (merging sub-oldest intervals), never query
results, because any read that passes the too-old gate has
read_snapshot >= oldestVersion > every merged version; we therefore run the
reference's one-pass keep rule eagerly instead of amortizing it.

Edge semantics reproduced deliberately:
  * empty read range [b,b): the skip list's CheckMax (SkipList.cpp:773-835)
    degenerates to checking the interval strictly below b; we mirror that.
  * empty write ranges never change the map (they cancel out in
    combineWriteConflictRanges's active-count sweep).
  * a transaction with reads=[] is never too-old regardless of snapshot.
"""
from __future__ import annotations

import bisect
from typing import List, Optional, Sequence, Tuple

from ..core.types import (
    CommitTransaction,
    Key,
    KeyRange,
    TransactionCommitResult,
    Version,
)


class VersionIntervalMap:
    """Sorted boundary list: interval [keys[i], keys[i+1]) has version vers[i];
    the last interval extends to +inf. keys[0] is always b''."""

    __slots__ = ("keys", "vers")

    def __init__(self, version: Version = 0):
        self.keys: List[Key] = [b""]
        self.vers: List[Version] = [version]

    def __len__(self) -> int:
        return len(self.keys)

    def version_at(self, key: Key) -> Version:
        return self.vers[bisect.bisect_right(self.keys, key) - 1]

    def version_strictly_below(self, key: Key) -> Version:
        """Version of the interval owned by the last boundary < key."""
        i = bisect.bisect_left(self.keys, key) - 1
        return self.vers[max(i, 0)]

    def range_max(self, begin: Key, end: Key) -> Version:
        """Max version over intervals intersecting non-empty [begin, end)."""
        lo = bisect.bisect_right(self.keys, begin) - 1
        hi = bisect.bisect_left(self.keys, end)
        return max(self.vers[lo:hi])

    def write(self, begin: Key, end: Key, version: Version) -> None:
        """Set [begin, end) to version, preserving the value at end."""
        if begin >= end:
            return
        keys, vers = self.keys, self.vers
        v_end = vers[bisect.bisect_right(keys, end) - 1]
        lo = bisect.bisect_left(keys, begin)
        hi = bisect.bisect_left(keys, end)
        repl_k: List[Key] = [begin]
        repl_v: List[Version] = [version]
        if hi == len(keys) or keys[hi] != end:
            repl_k.append(end)
            repl_v.append(v_end)
        keys[lo:hi] = repl_k
        vers[lo:hi] = repl_v

    def gc(self, oldest: Version) -> None:
        """Reference keep rule (removeBefore, SkipList.cpp:686-698): boundary i
        survives iff its version or its *original* predecessor's version is
        >= oldest. Representation-only; queries are unchanged for any read
        that passes the too-old gate."""
        keys, vers = self.keys, self.vers
        n = len(keys)
        nk: List[Key] = [keys[0]]
        nv: List[Version] = [vers[0]]
        for i in range(1, n):
            if vers[i] >= oldest or vers[i - 1] >= oldest:
                nk.append(keys[i])
                nv.append(vers[i])
        self.keys, self.vers = nk, nv


def _overlaps(a: KeyRange, b: KeyRange) -> bool:
    return a.begin < b.end and b.begin < a.end


class OracleConflictEngine:
    """Pluggable engine implementing the reference ConflictSet semantics
    (fdbserver/ConflictSet.h:27-60): resolve one ordered batch at version
    `now`, advance the GC horizon to `new_oldest`."""

    name = "oracle"

    def __init__(self, initial_version: Version = 0):
        self.map = VersionIntervalMap(initial_version)
        self.oldest_version: Version = 0

    def clear(self, version: Version) -> None:
        """reference: clearConflictSet (SkipList.cpp:957-959)."""
        self.map = VersionIntervalMap(version)

    def resolve(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> List[TransactionCommitResult]:
        n = len(transactions)
        too_old = [False] * n
        conflict = [False] * n

        for t, tr in enumerate(transactions):
            if tr.read_snapshot < self.oldest_version and tr.read_conflict_ranges:
                too_old[t] = True

        # Phase: reads vs. history
        for t, tr in enumerate(transactions):
            if too_old[t]:
                continue
            for r in tr.read_conflict_ranges:
                if r.begin >= r.end:
                    hit = self.map.version_strictly_below(r.begin) > tr.read_snapshot
                else:
                    hit = self.map.range_max(r.begin, r.end) > tr.read_snapshot
                if hit:
                    conflict[t] = True
                    break

        # Phase: intra-batch, strictly in submission order; earlier wins.
        written: List[KeyRange] = []
        for t, tr in enumerate(transactions):
            if conflict[t] or too_old[t]:
                continue
            hit = False
            for r in tr.read_conflict_ranges:
                # An empty read range never intra-conflicts: its begin point
                # sorts after its end point, so MiniConflictSet::any sees an
                # inverted index range and scans nothing (SkipList.cpp:1020-1025).
                if r.begin < r.end and any(_overlaps(r, w) for w in written):
                    hit = True
                    break
            if hit:
                conflict[t] = True
                continue
            for w in tr.write_conflict_ranges:
                if w.begin < w.end:
                    written.append(w)

        # Phase: apply committed writes at `now`
        for t, tr in enumerate(transactions):
            if conflict[t] or too_old[t]:
                continue
            for w in tr.write_conflict_ranges:
                self.map.write(w.begin, w.end, now)

        # Phase: advance horizon + GC
        if new_oldest > self.oldest_version:
            self.oldest_version = new_oldest
            self.map.gc(new_oldest)

        out: List[TransactionCommitResult] = []
        for t in range(n):
            if too_old[t]:
                out.append(TransactionCommitResult.TOO_OLD)
            elif conflict[t]:
                out.append(TransactionCommitResult.CONFLICT)
            else:
                out.append(TransactionCommitResult.COMMITTED)
        return out
