"""NativeConflictEngine: the C++ resolver engine behind the shared
ConflictSet contract.

The third pluggable engine next to OracleConflictEngine (logical model)
and JaxConflictEngine (TPU kernel): an ordered-boundary-map resolver in
C++ (native/conflict_engine.cpp), fed the same columnar conflict-wire
bytes the client serialized. It is the framework's CPU-native analog of
the reference's SkipList resolver — and the baseline the TPU kernel's
throughput is judged against (`-r skiplisttest`, SkipList.cpp:1412).
"""
from __future__ import annotations

import ctypes
from typing import List, Sequence

import numpy as np

from ..core.types import CommitTransaction, TransactionCommitResult, Version
from ..native.build import load_conflict_engine


class NativeConflictEngine:
    name = "native-cpp"

    def __init__(self, initial_version: Version = 0):
        self._lib = load_conflict_engine()
        if self._lib is None:
            raise RuntimeError("no C++ toolchain: native conflict engine unavailable")
        self._h = self._lib.cse_new(initial_version)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.cse_free(h)
            self._h = None

    def clear(self, version: Version) -> None:
        self._lib.cse_clear(self._h, version)

    @property
    def boundary_count(self) -> int:
        return int(self._lib.cse_boundary_count(self._h))

    def resolve(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> List[TransactionCommitResult]:
        n = len(transactions)
        if n == 0:
            return []
        # conflict_wire_block is cached on the transaction (core/types.py),
        # so a txn the client already serialized encodes zero times here
        blocks = [tr.conflict_wire_block() for tr in transactions]
        snaps = [tr.read_snapshot for tr in transactions]
        return self.resolve_wire(blocks, snaps, now, new_oldest)

    def resolve_wire(self, blocks: Sequence[bytes], snaps: Sequence[int],
                     now: Version, new_oldest: Version) -> List[TransactionCommitResult]:
        """Resolve pre-encoded conflict-wire blocks (the resolver-side
        entry: bytes in, verdicts out, no Python per-range objects)."""
        n = len(blocks)
        offs = np.zeros(n + 1, np.int64)
        np.cumsum([len(b) for b in blocks], out=offs[1:])
        blob = b"".join(blocks)
        snaps_arr = np.asarray(snaps, np.int64)
        out = np.zeros(n, np.uint8)
        rc = self._lib.cse_resolve(
            self._h, blob,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            snaps_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            now, new_oldest,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if rc != 0:
            raise ValueError("malformed conflict-wire batch")
        return [TransactionCommitResult(int(s)) for s in out]
