"""Fixed-width order-preserving key packing for the TPU conflict kernel.

A key (bytes) is packed into ``key_words`` big-endian uint32 words (zero
padded) plus a final length word. Lexicographic comparison of the resulting
(words..., length) tuple is *exactly* the reference's key order — bytewise,
shorter-is-less on equal prefix (fdbserver/SkipList.cpp:113-120) — for all
keys of length <= 4*key_words. Longer keys raise; the engine's exact-compare
width is a configuration knob (production configs size it to the schema's
conflict-key width; a digest+host-verify tier for unbounded keys is a later
milestone, cf. SURVEY.md §7 hard parts).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..core import error


def max_key_bytes(key_words: int) -> int:
    return 4 * key_words


def pack_keys(keys: Sequence[bytes], key_words: int) -> np.ndarray:
    """Pack N keys -> uint32 [N, key_words + 1] (words..., length).

    Fully vectorized: one join + one scatter + a big-endian uint32 view.
    This sits on the resolver's host hot path (every conflict range of
    every transaction passes through here), where a per-key Python loop
    measured ~10x the device's whole resolve time."""
    n = len(keys)
    kb = max_key_bytes(key_words)
    if n == 0:
        return np.zeros((0, key_words + 1), np.uint32)
    lens = np.fromiter((len(k) for k in keys), np.int64, count=n)
    if int(lens.max()) > kb:
        raise error.key_too_large(
            f"key of {int(lens.max())} bytes > engine width {kb}")
    flat = np.frombuffer(
        b"".join(k.ljust(kb, b"\0") for k in keys), dtype=np.uint8
    ).reshape(n, kb)
    packed = flat.view(">u4").astype(np.uint32)
    return np.concatenate([packed, lens[:, None].astype(np.uint32)], axis=1)


def pack_key(key: bytes, key_words: int) -> np.ndarray:
    return pack_keys([key], key_words)[0]


def unpack_key(packed: np.ndarray, key_words: int) -> bytes:
    """Inverse of pack_key (for debugging/tests)."""
    length = int(packed[key_words])
    words = packed[:key_words].astype(np.uint32)
    raw = bytearray()
    for w in words:
        raw += bytes([(int(w) >> 24) & 0xFF, (int(w) >> 16) & 0xFF, (int(w) >> 8) & 0xFF, int(w) & 0xFF])
    return bytes(raw[:length])
