"""Fixed-width order-preserving key packing for the TPU conflict kernel.

A key (bytes) is packed into ``key_words`` big-endian uint32 words (zero
padded) plus a final length word. Lexicographic comparison of the resulting
(words..., length) tuple is *exactly* the reference's key order — bytewise,
shorter-is-less on equal prefix (fdbserver/SkipList.cpp:113-120) — for all
keys of length <= 4*key_words.

Longer keys never reach pack_keys: the routed host engine sends long POINT
rows to its exact host tier (host_engine.py), and long RANGE ENDPOINTS are
packed by pack_endpoint_keys, which truncates to the window with length
window+1. The truncated form compares identically to the original against
every in-window key q: any byte difference inside the window decides both,
and when q is a prefix of the long key the length lane (len(q) <= window <
window+1) gives q < key either way — so device-side interval membership of
short keys is exact under truncation, and long-key membership is owned by
the host tier (SURVEY.md §7's digest/host-verify hard part, solved by
exact tiering instead of digests).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..core import error


def max_key_bytes(key_words: int) -> int:
    return 4 * key_words


def pack_keys(keys: Sequence[bytes], key_words: int) -> np.ndarray:
    """Pack N keys -> uint32 [N, key_words + 1] (words..., length).

    This sits on the resolver's host hot path (every conflict range of
    every transaction passes through here). Prefers the native C packer
    (native/fastpack.c via ctypes) — the analog of the reference's C++
    host data plane — and falls back to a vectorized numpy path (one join
    + a big-endian uint32 view) when no toolchain is available."""
    n = len(keys)
    kb = max_key_bytes(key_words)
    if n == 0:
        return np.zeros((0, key_words + 1), np.uint32)

    lib = _fastpack()
    if lib is not None:
        import ctypes

        blob = b"".join(keys)
        offs = np.zeros((n + 1,), np.int64)
        np.cumsum(np.fromiter((len(k) for k in keys), np.int64, count=n), out=offs[1:])
        out = np.empty((n, key_words + 1), np.uint32)
        rc = lib.pack_keys(
            blob,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, key_words,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        if rc != 0:
            raise error.key_too_large(f"key exceeds engine width {kb}")
        return out

    lens = np.fromiter((len(k) for k in keys), np.int64, count=n)
    if int(lens.max()) > kb:
        raise error.key_too_large(
            f"key of {int(lens.max())} bytes > engine width {kb}")
    flat = np.frombuffer(
        b"".join(k.ljust(kb, b"\0") for k in keys), dtype=np.uint8
    ).reshape(n, kb)
    packed = flat.view(">u4").astype(np.uint32)
    return np.concatenate([packed, lens[:, None].astype(np.uint32)], axis=1)


def _fastpack():
    global _FASTPACK, _FASTPACK_TRIED
    if not _FASTPACK_TRIED:
        _FASTPACK_TRIED = True
        try:
            from ..native import load_fastpack

            _FASTPACK = load_fastpack()
        except Exception:
            _FASTPACK = None
    return _FASTPACK


_FASTPACK = None
_FASTPACK_TRIED = False


def pack_endpoint_keys(keys: Sequence[bytes], key_words: int) -> np.ndarray:
    """pack_keys for RANGE ENDPOINTS: keys longer than the window are
    truncated to (first window bytes, length=window+1) — see module
    docstring for why this is exact for in-window membership."""
    kb = max_key_bytes(key_words)
    if all(len(k) <= kb for k in keys):
        return pack_keys(keys, key_words)
    out = pack_keys([k[:kb] for k in keys], key_words)
    for i, k in enumerate(keys):
        if len(k) > kb:
            out[i, key_words] = kb + 1
    return out


def pack_key(key: bytes, key_words: int) -> np.ndarray:
    return pack_keys([key], key_words)[0]


def unpack_key(packed: np.ndarray, key_words: int) -> bytes:
    """Inverse of pack_key (for debugging/tests)."""
    length = int(packed[key_words])
    words = packed[:key_words].astype(np.uint32)
    raw = bytearray()
    for w in words:
        raw += bytes([(int(w) >> 24) & 0xFF, (int(w) >> 16) & 0xFF, (int(w) >> 8) & 0xFF, int(w) & 0xFF])
    return bytes(raw[:length])
