"""Fixed-width order-preserving key packing for the TPU conflict kernel.

A key (bytes) is packed into ``key_words`` big-endian uint32 words (zero
padded) plus a final length word. Lexicographic comparison of the resulting
(words..., length) tuple is *exactly* the reference's key order — bytewise,
shorter-is-less on equal prefix (fdbserver/SkipList.cpp:113-120) — for all
keys of length <= 4*key_words. Longer keys raise; the engine's exact-compare
width is a configuration knob (production configs size it to the schema's
conflict-key width; a digest+host-verify tier for unbounded keys is a later
milestone, cf. SURVEY.md §7 hard parts).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..core import error


def max_key_bytes(key_words: int) -> int:
    return 4 * key_words


def pack_keys(keys: Sequence[bytes], key_words: int) -> np.ndarray:
    """Pack N keys -> uint32 [N, key_words + 1] (words..., length)."""
    n = len(keys)
    kb = max_key_bytes(key_words)
    out_bytes = np.zeros((n, kb), dtype=np.uint8)
    lens = np.empty((n,), dtype=np.uint32)
    for i, k in enumerate(keys):
        lk = len(k)
        if lk > kb:
            raise error.key_too_large(f"key of {lk} bytes > engine width {kb}")
        out_bytes[i, :lk] = np.frombuffer(k, dtype=np.uint8)
        lens[i] = lk
    words = out_bytes.reshape(n, key_words, 4).astype(np.uint32)
    packed = (
        (words[:, :, 0] << 24) | (words[:, :, 1] << 16) | (words[:, :, 2] << 8) | words[:, :, 3]
    )
    return np.concatenate([packed, lens[:, None]], axis=1)


def pack_key(key: bytes, key_words: int) -> np.ndarray:
    return pack_keys([key], key_words)[0]


def unpack_key(packed: np.ndarray, key_words: int) -> bytes:
    """Inverse of pack_key (for debugging/tests)."""
    length = int(packed[key_words])
    words = packed[:key_words].astype(np.uint32)
    raw = bytearray()
    for w in words:
        raw += bytes([(int(w) >> 24) & 0xFF, (int(w) >> 16) & 0xFF, (int(w) >> 8) & 0xFF, int(w) & 0xFF])
    return bytes(raw[:length])
