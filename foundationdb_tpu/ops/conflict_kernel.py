"""TPU-native batched conflict detection — the north-star kernel.

Re-design of the reference resolver's versioned skip list
(fdbserver/SkipList.cpp) as a data-parallel, fixed-shape XLA program:

  reference                      this kernel
  ---------                      -----------
  skip-list nodes                sorted boundary table hkeys[H, K] in HBM
  per-level maxVersion pyramid   sparse table (block-max) over hvers[H]
  16-way pipelined CheckMax      one fused vectorized binary search per step
  radix sortPoints (:227)        one lax.sort of all endpoints w/ tie codes
  MiniConflictSet sweep (:1133)  bit-packed overlap words + DAG fixpoint
  skip-list insert/remove        sort-free merge: searchsorted + scatter
  removeBefore GC (:665)         vectorized keep rule + compaction

The batch schema splits conflict ranges into POINT rows (exactly
[k, k+'\\x00') — the dominant shape in the reference's workloads) and RANGE
rows. A point row costs one search query plus one equality gather; a range
row costs two queries. In the packed-key domain pack(k + '\\x00') ==
_bump(pack(k)), so point end keys are synthesized on device and never packed
or searched. Binary-search volume is the kernel's dominant cost on TPU
(per-row gathers), so this roughly halves step time on point-heavy batches.

Exactness: verdicts are a pure function of the logical version-interval map
(see ops/oracle.py); every op here (max, OR, integer compares) is
order-insensitive, so results are bit-identical to the oracle and hence to
the reference CPU resolver, for keys within the configured exact width.

Versions on device are int32 offsets from a host-tracked base (the 5-second
MVCC window MAX_WRITE_TRANSACTION_LIFE_VERSIONS = 5e6 << 2^31); versions at
or below the base are clamped to -1, which is semantics-preserving because
any read that passes the too-old gate has snapshot >= base.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.types import TransactionCommitResult
from . import keypack

NEG_VERSION = jnp.int32(-(2**30))

#: history-query strategies of local_phases (docs/perf.md):
#:   fused_sort — ONE lax.sort of table ++ batch rows yields every lower
#:                bound positionally (the original path; cost scales with
#:                the capacity-H table on every step),
#:   bsearch    — sort ONLY the O(T) batch rows and recover every lower
#:                bound into hkeys[0:n] with a branchless vectorized
#:                K-word binary search (cost scales with the batch),
#:   auto       — pick per config: bsearch when the batch is small
#:                relative to the table (T << H, i.e. small ladder
#:                buckets on a large capacity).
HISTORY_SEARCH_MODES = ("fused_sort", "bsearch", "auto")

#: history-structure of the device interval table (docs/perf.md
#: "Incremental history maintenance"):
#:   monolithic — ONE key-sorted boundary table; apply_writes_and_gc
#:                re-merges the full capacity-H table every batch (the
#:                original path; exact, but apply cost scales with H),
#:   tiered     — LSM-style sorted runs: each batch's committed-write
#:                union appends as one run (O(batch)); queries probe
#:                base + active runs with the same branchless K-word
#:                comparators; a device-side merge folds every run into
#:                the base only when the run slots fill, and GC becomes
#:                a range deletion (elementwise horizon rebase, physical
#:                reclamation deferred to the merge).
#: Abort sets are bit-identical across structures (tests/test_history_
#: tiered.py pins monolithic == tiered == the serial oracle).
HISTORY_STRUCTURES = ("monolithic", "tiered")


@dataclass(frozen=True)
class KernelConfig:
    key_words: int = 4          # exact-compare width = 4*key_words bytes
    capacity: int = 1 << 16     # H: max boundaries in the interval table
    max_reads: int = 1 << 12    # Rr: RANGE read rows per device batch
    max_writes: int = 1 << 12   # Wr: RANGE write rows per device batch
    max_txns: int = 1 << 12     # T: transactions per device batch
    max_point_reads: int = -1   # Rp: POINT read rows (-1: same as max_reads)
    max_point_writes: int = -1  # Wp: POINT write rows (-1: same as max_writes)
    #: commit-fixpoint engine: "xla" (while_loop of small kernels; the only
    #: option for the mesh engine, whose psum is its collective round),
    #: "pallas" (one fused TPU kernel, fixpoint_pallas.py), or
    #: "pallas_interpret" (the same kernel on the interpreter, for CPU CI)
    fixpoint: str = "xla"
    #: history-query strategy (HISTORY_SEARCH_MODES); "auto" resolves per
    #: config at trace time via pick_history_search, so a bucket ladder
    #: built from an auto config picks bsearch for its small buckets and
    #: fused_sort for shapes whose batch rivals the table
    history_search: str = "auto"
    #: keyspace-heat observability (docs/observability.md "Keyspace heat &
    #: occupancy"): number of key-range histogram buckets the resolve step
    #: aggregates on device (boundary keys sampled from the interval table
    #: delimit the buckets, so binning adapts to the served keyspace).
    #: 0 (default) disables — programs emit no heat outputs and the step
    #: is byte-for-byte today's program; > 0 adds a `heat` subtree to
    #: every step/scan/loop output. Abort sets are bit-identical either
    #: way (the heat pass only READS the verdict path's values).
    heat_buckets: int = 0
    #: history-structure of the interval table (HISTORY_STRUCTURES):
    #: "monolithic" re-merges the capacity-H table every batch;
    #: "tiered" appends each batch as a sorted run and merges lazily,
    #: so steady-state apply cost scales with the batch, not capacity
    history_structure: str = "monolithic"
    #: tiered only: run slots (tiers) before the lazy merge fires. The
    #: slot count bounds the size ratio runs:base at history_runs *
    #: run_rows / capacity by construction — filling the last slot IS
    #: the compaction trigger
    history_runs: int = 8
    #: tiered only: rows per run slot; 0 derives 2*w_all (one batch's
    #: union can never exceed a begin+end row per committed write row).
    #: bucket() materializes the derived value so every ladder bucket
    #: shares the exact device state shape (the loop engine lowers its
    #: programs against state_struct(bucket))
    history_run_rows: int = 0

    @property
    def lanes(self) -> int:     # K: words per packed key incl. length
        return self.key_words + 1

    @property
    def rp(self) -> int:
        return self.max_point_reads if self.max_point_reads >= 0 else self.max_reads

    @property
    def wp(self) -> int:
        return self.max_point_writes if self.max_point_writes >= 0 else self.max_writes

    @property
    def r_all(self) -> int:     # total read rows (point ++ range)
        return self.rp + self.max_reads

    @property
    def w_all(self) -> int:     # total write rows (point ++ range)
        return self.wp + self.max_writes

    @property
    def wr_words(self) -> int:  # RANGE write rows as uint32 bit-words
        return (self.max_writes + 31) // 32

    @property
    def wp_words(self) -> int:  # POINT write rows as uint32 bit-words
        return (self.wp + 31) // 32

    @property
    def batch_rows(self) -> int:  # rows the fused sort adds to the table
        return self.rp + 3 * self.max_reads + self.wp + 2 * self.max_writes

    @property
    def gid_space(self) -> int:  # upper bound on per-key group ids
        return self.capacity + self.batch_rows

    @property
    def levels(self) -> int:    # sparse-table levels
        return int(math.ceil(math.log2(self.capacity))) + 1

    @property
    def run_slots(self) -> int:  # NR: tiered run slots
        return self.history_runs

    @property
    def run_rows(self) -> int:   # RC: rows per tiered run slot
        return self.history_run_rows if self.history_run_rows > 0 else 2 * self.w_all

    @property
    def run_levels(self) -> int:  # binary-search rounds into one run
        return int(math.ceil(math.log2(max(2, self.run_rows)))) + 1

    def bucket(self, t: int) -> "KernelConfig":
        """Sub-capacity clone for a bucketed kernel ladder: batch-side
        shapes (txns + read/write row caps) scale down to `t` transactions
        while the `capacity`-sized interval-table state stays SHAPE-
        INVARIANT — every bucket's program runs against the same device
        state, so a ladder of compiled programs shares one history.

        Row caps scale pro-rata, rounded up to a multiple of 32 (keeps the
        bit-word packing and the Pallas fixpoint's T%32 layout happy).
        t == max_txns returns self (the top bucket IS the base config)."""
        if t == self.max_txns:
            return self
        if not (0 < t < self.max_txns):
            raise ValueError(f"bucket size {t} outside (0, {self.max_txns}]")
        if t % 32:
            raise ValueError(f"bucket size {t} must be a multiple of 32")

        def scale(rows: int) -> int:
            if rows <= 0:
                return rows
            return min(rows, max(32, -(-rows * t // self.max_txns) + 31 & ~31))

        return KernelConfig(
            key_words=self.key_words,
            capacity=self.capacity,
            max_reads=scale(self.max_reads),
            max_writes=scale(self.max_writes),
            max_txns=t,
            max_point_reads=scale(self.rp),
            max_point_writes=scale(self.wp),
            fixpoint=self.fixpoint,
            history_search=self.history_search,
            heat_buckets=self.heat_buckets,
            history_structure=self.history_structure,
            history_runs=self.history_runs,
            # materialize the base config's derived run capacity: bucket
            # batch shapes scale down but the device state — run planes
            # included — must stay SHAPE-INVARIANT across the ladder
            history_run_rows=self.run_rows,
        )


def pick_history_search(cfg: "KernelConfig") -> str:
    """The `auto` rule: bsearch when the batch rows are small relative to
    the boundary table (T << H). The crossover is where the batch-only
    sort + O(T*K*log H) search beats re-sorting the capacity-H table with
    the batch: with the fused sort's ~(H+B)*K*log^2(H+B) comparator cost
    vs the search's B gathers per level, batch rows at <= a quarter of
    the capacity is comfortably on the search side on both TPU and CPU
    (tools/floor_bench.py sweeps the actual curve)."""
    return "bsearch" if cfg.batch_rows * 4 <= cfg.capacity else "fused_sort"


def resolved_history_search(cfg: "KernelConfig") -> str:
    """Concrete mode ("fused_sort" | "bsearch") a given config traces."""
    mode = cfg.history_search
    if mode not in HISTORY_SEARCH_MODES:
        raise ValueError(
            f"unknown history_search mode {mode!r}; expected one of "
            f"{HISTORY_SEARCH_MODES}")
    return pick_history_search(cfg) if mode == "auto" else mode


def resolved_history_structure(cfg: "KernelConfig") -> str:
    """Concrete structure ("monolithic" | "tiered") a config traces, with
    the tiered shape preconditions checked loudly at trace/build time."""
    structure = cfg.history_structure
    if structure not in HISTORY_STRUCTURES:
        raise ValueError(
            f"unknown history_structure {structure!r}; expected one of "
            f"{HISTORY_STRUCTURES}")
    if structure == "tiered":
        if cfg.history_runs < 2:
            raise ValueError(
                f"history_runs={cfg.history_runs} must be >= 2 for the "
                f"tiered structure (one slot would merge on every batch — "
                f"strictly worse than monolithic — and the heat-borne run "
                f"accounting could not distinguish append from merge)")
        if cfg.run_rows < 2 * cfg.w_all:
            raise ValueError(
                f"history_run_rows={cfg.run_rows} cannot hold one batch's "
                f"committed-write union (needs >= 2*w_all = {2 * cfg.w_all})")
    return structure


def _key_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a < b over trailing lane axis (uint32 words + length)."""
    neq = a != b
    idx = jnp.argmax(neq, axis=-1)
    any_neq = jnp.any(neq, axis=-1)
    av = jnp.take_along_axis(a, idx[..., None], axis=-1)[..., 0]
    bv = jnp.take_along_axis(b, idx[..., None], axis=-1)[..., 0]
    return any_neq & (av < bv)


def _key_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def _bump(q: jnp.ndarray) -> jnp.ndarray:
    """Successor of a packed key in packed order: (words, len) -> (words, len+1).

    No packable key sorts strictly between the two (lengths are integers), so
    lower_bound(_bump(q)) == upper_bound(q), and pack(k + '\\x00') ==
    _bump(pack(k)) whenever k fits the exact window (appending a NUL byte
    leaves the zero-padded words unchanged and adds one to the length lane).
    """
    return q.at[..., -1].add(1)


def _present(table: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """1 iff q occurs in the table, given s = lower_bound(q): one row gather.
    upper_bound(q) == s + _present(table, q, s)."""
    return _key_eq(table[s], q).astype(jnp.int32)


def _lower_bound(cfg: KernelConfig, hkeys: jnp.ndarray, n: jnp.ndarray,
                 q: jnp.ndarray) -> jnp.ndarray:
    """Branchless vectorized K-word binary search: lower_bound of every
    query row q[i] into the key-sorted valid prefix hkeys[0:n] — the
    16-way pipelined CheckMax of the reference skip list (SkipList.cpp)
    recast as `levels` rounds of [Q, K] row gathers, all Q queries probing
    in lockstep. Invariant per round: the answer lies in [lo, hi]; a
    converged lane (lo == hi) is frozen by the `active` mask, so
    cfg.levels (= ceil(log2 H) + 1) unrolled rounds pin every lane.
    Matches the fused sort's tie discipline exactly: table rows sort AFTER
    equal batch keys there, so its positional count equals this standard
    lower bound (first index with hkeys[i] >= q)."""
    return _lower_bound_n(hkeys, n, q, cfg.levels)


def _lower_bound_n(table: jnp.ndarray, n: jnp.ndarray, q: jnp.ndarray,
                   levels: int) -> jnp.ndarray:
    """The same branchless search against ANY key-sorted [*, K] table with
    valid prefix n — the tiered structure's run probes reuse it with the
    per-run row capacity's level count."""
    Q = q.shape[0]
    lo = jnp.zeros((Q,), jnp.int32)
    hi = jnp.broadcast_to(n.astype(jnp.int32), (Q,))
    for _ in range(levels):
        active = lo < hi
        mid = (lo + hi) >> 1
        go_right = _key_less(table[mid], q)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def _build_sparse_max(cfg: KernelConfig, vers: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Sparse table: out[k, i] = max(vers[i : i+2^k]) with invalid slots -> NEG.

    This is the skip-list maxVersion pyramid (SkipList.cpp:350-357) flattened
    into a dense, gather-friendly layout."""
    return _build_sparse_max_n(vers, n, cfg.capacity, cfg.levels)


def _build_sparse_max_n(vers: jnp.ndarray, n: jnp.ndarray, h: int,
                        n_levels: int) -> jnp.ndarray:
    base = jnp.where(jnp.arange(h) < n, vers, NEG_VERSION)
    levels = [base]
    for k in range(1, n_levels):
        half = 1 << (k - 1)
        prev = levels[-1]
        shifted = jnp.concatenate([prev[half:], jnp.full((half,), NEG_VERSION, prev.dtype)])
        levels.append(jnp.maximum(prev, shifted))
    return jnp.stack(levels)  # [n_levels, h]


def _range_max(cfg: KernelConfig, sparse: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """max(vers[lo:hi]) for hi > lo, via two overlapping power-of-two blocks."""
    return _range_max_n(sparse, lo, hi, cfg.capacity)


def _range_max_n(sparse: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
                 h: int) -> jnp.ndarray:
    s = (hi - lo).astype(jnp.uint32)
    k = (31 - lax.clz(s)).astype(jnp.int32)
    flat = sparse.reshape(-1)
    m1 = flat[k * h + lo]
    m2 = flat[k * h + hi - (1 << k).astype(jnp.int32)]
    return jnp.maximum(m1, m2)


def _i2u(x: jnp.ndarray) -> jnp.ndarray:
    return lax.bitcast_convert_type(x, jnp.uint32)


def _u2i(x: jnp.ndarray) -> jnp.ndarray:
    return lax.bitcast_convert_type(x, jnp.int32)


def _pack_bits(bits: jnp.ndarray, n_words: int) -> jnp.ndarray:
    """Pack a [..., W] bool array into [..., n_words] uint32 (W <= 32*n_words)."""
    w = bits.shape[-1]
    pad = 32 * n_words - w
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bits.dtype)], axis=-1
        )
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(
        bits.reshape(bits.shape[:-1] + (n_words, 32)).astype(jnp.uint32) * weights,
        axis=-1, dtype=jnp.uint32,
    )


def _tiered_read_probe(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],
    rpb: jnp.ndarray, rp_valid: jnp.ndarray,
    rb: jnp.ndarray, re: jnp.ndarray, r_valid: jnp.ndarray,
    empty_r: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tiered structure: per-run history contributions for both read
    classes — (point_max [Rp], range_max [Rr]), to be max-folded into the
    base table's phase-1 answers BEFORE any hit computation (so heat
    witnesses stay consistent with verdicts).

    Each run is a key-sorted mini interval table in the base table's own
    representation (value at k = vers[upper_bound(k) - 1]) whose rows
    alternate (union-begin, now) / (union-end, NEG gap): inside a
    committed-write union range the run answers `now`, outside it answers
    NEG so lower tiers and the base show through, and the effective map
    value is the max over base + runs (versions only grow with recency,
    so max == newest covering write — exactly the monolithic map).

    Unlike the base table, a run has no guaranteed minimal-key boundary
    row, so every probe carries an emptiness guard: upper_bound == 0
    means the query precedes the whole run (NEG), and an empty row
    window [lo, hi) with hi <= lo likewise answers NEG. Probe cost is
    O(NR * (Rp + 3*Rr) * K * run_levels) — batch-scaled, never
    capacity-scaled, in BOTH search modes (fused_sort keeps its fused
    base probe; runs are always searched)."""
    NR, RC = cfg.run_slots, cfg.run_rows
    Rp, Rr = cfg.rp, cfg.max_reads
    levels = cfg.run_levels
    rkeys, rvers = state["rkeys"], state["rvers"]
    rn = state["rn"]

    qvalid = jnp.concatenate([rp_valid, r_valid, r_valid, r_valid])
    qkeys = jnp.concatenate([rpb, rb, _bump(rb), re], axis=0)
    q_eff = jnp.where(qvalid[:, None], qkeys, jnp.uint32(0xFFFFFFFF))

    vp = jnp.full((Rp,), NEG_VERSION, jnp.int32)
    vr = jnp.full((Rr,), NEG_VERSION, jnp.int32)
    for j in range(NR):
        tk, tv, tn = rkeys[j], rvers[j], rn[j]
        lb = _lower_bound_n(tk, tn, q_eff, levels)
        lb_p = lb[:Rp]
        lb_b = lb[Rp:Rp + Rr]
        lb_bb = lb[Rp + Rr:Rp + 2 * Rr]     # lower(bump(rb)) == upper(rb)
        lb_e = lb[Rp + 2 * Rr:]
        # Point read: value at k = vers[upper(k) - 1], NEG before the run.
        # (Padding rows carry all-ones keys + NEG versions, so a gather
        # that lands past rn answers NEG and never forges a hit.)
        up_p = lb_p + _present(tk, rpb, lb_p)
        vp_j = jnp.where(up_p > 0, tv[jnp.maximum(up_p - 1, 0)], NEG_VERSION)
        vp = jnp.maximum(vp, vp_j)
        if Rr > 0:
            sparse = _build_sparse_max_n(tv, tn, RC, levels)
            # Empty reads ([q, q)) ask for the version strictly below q —
            # the value of the effective map's last boundary < q. The
            # oracle (and the base path, whose row 0 IS the minimal key)
            # clamp that predecessor scan to the minimal-key row, so for
            # q == b'' the answer degenerates to the value AT b'': a run
            # whose union begins exactly at b'' must contribute its row
            # AT q then. For q > b'' the base's b'' row anchors the
            # effective predecessor and a run with no row < q correctly
            # contributes NEG.
            is_min = jnp.all(rb == 0, axis=-1)       # q == b'' (packed zero)
            eq_b = _present(tk, rb, lb_b)
            s_qlo = jnp.where(empty_r,
                              lb_b + jnp.where(is_min, eq_b, 0), lb_bb)
            lo = jnp.maximum(s_qlo - 1, 0)
            hi = jnp.where(empty_r, s_qlo, lb_e)
            vr_j = jnp.where(
                hi > lo,
                _range_max_n(sparse, lo, jnp.maximum(hi, lo + 1), RC),
                NEG_VERSION)
            vr = jnp.maximum(vr, vr_j)
    return vp, vr


def local_phases(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]):
    """Phases 1-2, shard-local: reads vs. history + intra-batch overlap edges.

    Two interchangeable history-query strategies (cfg.history_search,
    bit-identical outputs — tests/test_kernel_parity.py cross-checks them):

      fused_sort: ONE fused lax.sort serves the entire step — the boundary
      table and every batch row sort together, so a single pass yields (a)
      every lower bound into the table (count of table rows preceding a
      row's sorted position), (b) endpoint order for range-row overlap
      tests, and (c) per-key group ids that decide point-vs-point overlap
      by integer equality — the dominant row class needs no synthesized
      end rows at all.

      bsearch: the table is ALREADY sorted (apply_writes_and_gc emits it
      key-sorted), so only the O(T) batch rows sort (for (b) and (c)) and
      (a) comes from a branchless vectorized binary search (_lower_bound)
      — the per-step fixed cost no longer scales with the capacity-H
      table, which is what flattens the small-batch device-time floor
      (docs/perf.md "History search modes").

    Tie codes at equal keys (end-read < end-write < begin-write <
    {begin-read, point} < point-write < table) make position compares
    exact half-open interval logic, the getCharacter trick
    (SkipList.cpp:147-177) extended with a point-write level so
    `range-begin <= point` resolves positionally.

    Returns (hist_hits int32 [T], edges, wpos) where edges holds the
    intra-batch overlap structure — "ovw" uint32 [r_all, wr_words] (reads
    vs RANGE writes, bit (r, w) = 1 iff read row r overlaps range-write
    row w AND w's txn is strictly earlier in the batch, the reference's
    earlier-in-batch-wins edge direction checkIntraBatchConflicts:1139-
    1152), "ovrp" uint32 [Rr, wp_words] (range reads vs point writes),
    and "gid_rp"/"gid_wp" per-key group ids through which the fixpoint
    resolves the dominant point-vs-point block without a matrix — and
    wpos carries the write-interval endpoint positions in the OLD
    boundary table that apply_writes_and_gc needs. Hits/overlaps are
    additive across key-range shards; the multi-shard engine psums
    hist_hits once and the fixpoint's per-iteration blocked-txn counts
    over the mesh axis — the "conflict bitmaps allreduced over ICI" of
    the north star. edges and wpos stay shard-local.

    batch fields (fixed shapes; see build_batch_arrays). Read/write rows are
    grouped by ascending owning txn within each group, valid rows first:
      rpb     uint32 [Rp, K]   POINT read keys (range is [k, k+'\\x00'))
      rp_snap int32  [Rp]      point-read snapshot, relative to base
      rp_txn  int32  [Rp]
      rp_valid bool  [Rp]
      rb, re  uint32 [Rr, K]   RANGE read begin/end (may be empty ranges)
      r_snap, r_txn, r_valid   as above, [Rr]
      wpb     uint32 [Wp, K]   POINT write keys
      wp_txn  int32  [Wp]
      wp_valid bool  [Wp]
      wb, we  uint32 [Wr, K]   RANGE write ranges (non-empty only)
      w_txn   int32  [Wr]
      w_valid bool   [Wr]
      t_ok     bool  [T]       valid txn, not too-old
      t_too_old bool [T]
      now     int32  []        commit version - base
      gc      int32  []        new_oldest - base (<=0: no GC/rebase)
    """
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    Rp, Rr = cfg.rp, cfg.max_reads
    Wp, Wr = cfg.wp, cfg.max_writes
    T = cfg.max_txns
    K = cfg.lanes

    rpb = batch["rpb"]
    rb, re = batch["rb"], batch["re"]
    wpb = batch["wpb"]
    wb, we = batch["wb"], batch["we"]
    rp_valid, r_valid = batch["rp_valid"], batch["r_valid"]
    wp_valid, w_valid = batch["wp_valid"], batch["w_valid"]
    H = cfg.capacity
    empty_r = ~_key_less(rb, re)

    mode = resolved_history_search(cfg)
    if mode == "fused_sort":
        # ---- THE fused sort: table ++ batch rows, one pass ----
        # Tie codes at equal keys (ascending): end-read 0, end-write 1,
        # begin-write 2, begin-read/point-read 3, point-write 4, table 5.
        # Table rows sort after every equal batch key, so
        #   lower_bound(row) = # valid table rows before row's sorted position
        # for every batch row at once. bump(rb) rows ride along only to provide
        # upper_bound(rb) for non-empty range reads' history query.
        #
        # Operand packing: invalid rows carry all-ones key words (no real key
        # reaches length 2^32-1, so they sort after everything), and the tie
        # code + original index share one word (code in the high bits; the
        # composite is unique per row, so the order is total and no separate
        # stability payload is needed). 6 sort operands instead of 8 — the
        # sort is the step's dominant cost and scales with operand width.
        groups = (
            (rpb, 3, rp_valid),       # point reads
            (rb, 3, r_valid),         # range-read begins
            (re, 0, r_valid),         # range-read ends
            (_bump(rb), 0, r_valid),  # upper-bound probes for range reads
            (wpb, 4, wp_valid),       # point writes
            (wb, 2, w_valid),         # range-write begins
            (we, 1, w_valid),         # range-write ends
        )
        bkeys = jnp.concatenate([g[0] for g in groups], axis=0)
        B = bkeys.shape[0]
        bcode = jnp.concatenate(
            [jnp.full((g[0].shape[0],), g[1], jnp.uint32) for g in groups])
        bvalid = jnp.concatenate([g[2] for g in groups])
        N = H + B
        idx_bits = max(1, (N - 1).bit_length())
        keys_all = jnp.concatenate([hkeys, bkeys], axis=0)
        code_all = jnp.concatenate([jnp.full((H,), 5, jnp.uint32), bcode])
        valid_all = jnp.concatenate([jnp.arange(H) < n, bvalid])
        keys_eff = jnp.where(valid_all[:, None], keys_all, jnp.uint32(0xFFFFFFFF))
        idx = jnp.arange(N, dtype=jnp.uint32)
        codeidx = (jnp.where(valid_all, code_all, jnp.uint32(7)) << idx_bits) | idx
        ops = tuple(keys_eff[:, c] for c in range(K)) + (codeidx,)
        s = lax.sort(ops, num_keys=K + 1)
        sidx = s[K] & jnp.uint32((1 << idx_bits) - 1)
        skeys = jnp.stack(s[:K], axis=1)
        pos = jnp.zeros((N,), jnp.int32).at[sidx].set(jnp.arange(N, dtype=jnp.int32))

        # Lower bounds: inclusive cumsum of valid-table rows in sorted order;
        # a batch row contributes 0, so gathering at its position counts exactly
        # the table rows before it.
        is_tab = (sidx < H) & (sidx.astype(jnp.int32) < n)
        cum_tab = jnp.cumsum(is_tab.astype(jnp.int32))
        # Per-key group ids: a new group starts where the sorted key differs
        # from its predecessor. Point-point overlap is gid equality — no end
        # rows, no position algebra, for the dominant row class.
        prev = jnp.concatenate([skeys[:1] + 1, skeys[:-1]], axis=0)
        gid_sorted = jnp.cumsum(jnp.any(skeys != prev, axis=-1).astype(jnp.int32))

        bpos = pos[H:]
        lb = cum_tab[bpos]
        gid = gid_sorted[bpos]
        o = 0
        pos_rpb, lb_rp, gid_rp = bpos[o:o + Rp], lb[o:o + Rp], gid[o:o + Rp]; o += Rp
        pos_rb, lb_rb = bpos[o:o + Rr], lb[o:o + Rr]; o += Rr
        pos_re, s_re = bpos[o:o + Rr], lb[o:o + Rr]; o += Rr
        lb_rbb = lb[o:o + Rr]; o += Rr                     # lower(bump(rb))
        pos_wpb, s_wpb, gid_wp = bpos[o:o + Wp], lb[o:o + Wp], gid[o:o + Wp]; o += Wp
        pos_wb, s_wb = bpos[o:o + Wr], lb[o:o + Wr]; o += Wr
        pos_we, s_we = bpos[o:o + Wr], lb[o:o + Wr]
        s_rp = lb_rp
    else:
        # ---- batch-only sort + vectorized binary search ----
        # apply_writes_and_gc emits the boundary table fully key-sorted, so
        # re-sorting it with every batch (the fused path) pays an
        # O((H+T)*K*log(H+T)) fixed floor per step regardless of batch
        # size. Search-in-sorted-structure instead: sort ONLY the O(T)
        # batch rows (same tie-code comparator, minus the table level and
        # the bump probes — those rows existed purely to read lower bounds
        # off the fused order) and recover every lower bound into
        # hkeys[0:n] with _lower_bound, O(T*K*log H).
        #
        # Bit-exactness: intra-batch positional compares and per-key group
        # ids only ever relate batch rows to batch rows, and removing the
        # interleaved table/bump rows preserves both the relative order of
        # the remaining rows (keys, then the same code ladder, then
        # original index — group order here matches the fused operand
        # order) and key-equality classes; the searched lower bounds equal
        # the fused path's positional counts because table rows sort AFTER
        # equal batch keys there (see _lower_bound). Everything downstream
        # — wpos, both phases, the fixpoint — is byte-for-byte shared.
        groups = (
            (rpb, 3, rp_valid),       # point reads
            (rb, 3, r_valid),         # range-read begins
            (re, 0, r_valid),         # range-read ends
            (wpb, 4, wp_valid),       # point writes
            (wb, 2, w_valid),         # range-write begins
            (we, 1, w_valid),         # range-write ends
        )
        bkeys = jnp.concatenate([g[0] for g in groups], axis=0)
        B = bkeys.shape[0]
        bcode = jnp.concatenate(
            [jnp.full((g[0].shape[0],), g[1], jnp.uint32) for g in groups])
        bvalid = jnp.concatenate([g[2] for g in groups])
        idx_bits = max(1, (B - 1).bit_length())
        keys_eff = jnp.where(bvalid[:, None], bkeys, jnp.uint32(0xFFFFFFFF))
        idx = jnp.arange(B, dtype=jnp.uint32)
        codeidx = (jnp.where(bvalid, bcode, jnp.uint32(7)) << idx_bits) | idx
        ops = tuple(keys_eff[:, c] for c in range(K)) + (codeidx,)
        s = lax.sort(ops, num_keys=K + 1)
        sidx = s[K] & jnp.uint32((1 << idx_bits) - 1)
        skeys = jnp.stack(s[:K], axis=1)
        pos = jnp.zeros((B,), jnp.int32).at[sidx].set(jnp.arange(B, dtype=jnp.int32))
        prev = jnp.concatenate([skeys[:1] + 1, skeys[:-1]], axis=0)
        gid_sorted = jnp.cumsum(jnp.any(skeys != prev, axis=-1).astype(jnp.int32))
        gid = gid_sorted[pos]

        # One packed search serves every query class (invalid rows keep the
        # all-ones override so their lower bound lands at n, exactly the
        # fused path's count). bump(rb) is searched directly — no probe
        # rows ride through the sort.
        qvalid = jnp.concatenate(
            [rp_valid, r_valid, r_valid, r_valid, wp_valid, w_valid, w_valid])
        qkeys = jnp.concatenate(
            [rpb, rb, _bump(rb), re, wpb, wb, we], axis=0)
        q_eff = jnp.where(qvalid[:, None], qkeys, jnp.uint32(0xFFFFFFFF))
        lb = _lower_bound(cfg, hkeys, n, q_eff)

        o = 0
        pos_rpb, gid_rp = pos[o:o + Rp], gid[o:o + Rp]; o += Rp
        pos_rb = pos[o:o + Rr]; o += Rr
        pos_re = pos[o:o + Rr]; o += Rr
        pos_wpb, gid_wp = pos[o:o + Wp], gid[o:o + Wp]; o += Wp
        pos_wb = pos[o:o + Wr]; o += Wr
        pos_we = pos[o:o + Wr]
        o = 0
        lb_rp = lb[o:o + Rp]; o += Rp
        lb_rb = lb[o:o + Rr]; o += Rr
        lb_rbb = lb[o:o + Rr]; o += Rr                     # lower(bump(rb))
        s_re = lb[o:o + Rr]; o += Rr
        s_wpb = lb[o:o + Wp]; o += Wp
        s_wb = lb[o:o + Wr]; o += Wr
        s_we = lb[o:o + Wr]
        s_rp = lb_rp

    # Equality gathers (one table row each) derive every upper bound:
    eq_rp = _present(hkeys, rpb, s_rp)
    eq_wpb = _present(hkeys, wpb, s_wpb)
    eq_we = _present(hkeys, we, s_we)
    eq_wpb2 = _present(hkeys, _bump(wpb), s_wpb + eq_wpb)

    # Write-interval endpoint positions for apply_writes_and_gc. Interval i
    # of the w_all = Wp ++ Wr layout has begin key (wpb | wb) and end key
    # (_bump(wpb) | we).
    wpos = {
        "lo_b": jnp.concatenate([s_wpb, s_wb]),                       # lower(begin)
        "lo_e": jnp.concatenate([s_wpb + eq_wpb, s_we]),              # lower(end)
        "up_e": jnp.concatenate([s_wpb + eq_wpb + eq_wpb2, s_we + eq_we]),  # upper(end)
    }

    # ---- Phase 1: reads vs. history (checkReadConflictRanges:1210) ----
    # Tiered structure: fold every active run's contribution into the
    # base table's answers BEFORE any hit computation, so verdicts AND
    # the heat witness context both see the effective (base + runs) map.
    tiered = resolved_history_structure(cfg) == "tiered"
    if tiered:
        run_vp, run_vr = _tiered_read_probe(
            cfg, state, rpb, rp_valid, rb, re, r_valid, empty_r)

    # Point read: its single covering interval starts at upper(rpb)-1, so the
    # range-max is one version gather — no sparse table involved.
    vmax_p = hvers[jnp.maximum(s_rp + eq_rp - 1, 0)]
    if tiered:
        vmax_p = jnp.maximum(vmax_p, run_vp)
    hit_p = batch["rp_valid"] & (vmax_p > batch["rp_snap"])
    hist_hits = jnp.zeros((T,), jnp.int32).at[batch["rp_txn"]].max(
        hit_p.astype(jnp.int32), mode="drop")

    if Rr > 0:
        sparse = _build_sparse_max(cfg, hvers, n)
        s_qlo = jnp.where(empty_r, lb_rb, lb_rbb)
        lo_e = jnp.maximum(s_qlo - 1, 0)
        lo = jnp.where(empty_r, lo_e, s_qlo - 1)
        hi = jnp.where(empty_r, lo_e + 1, s_re)
        rmax = _range_max(cfg, sparse, lo, hi)
        if tiered:
            rmax = jnp.maximum(rmax, run_vr)
        hit_rg = batch["r_valid"] & (rmax > batch["r_snap"])
        hist_hits = hist_hits.at[batch["r_txn"]].max(hit_rg.astype(jnp.int32), mode="drop")

    # ---- Phase 2: intra-batch (checkIntraBatchConflicts:1133) ----
    # Overlap edges, split by row class (all positions come from the fused
    # sort). The dominant point-vs-point block is NOT materialized as a
    # matrix: key equality == gid equality, so the fixpoint resolves it
    # with a per-gid min over committed point-write txn indices (a [Wp]
    # scatter-min + [Rp] gather per iteration) instead of an [Rp, Wp]
    # dense block (~67M lanes at the bench shape). Only the range-row
    # blocks — orders of magnitude smaller — are bit-packed:
    #   point-range:  [k,k+'\0') hits [wb,we) iff wb <= k < we; both compares
    #                 are positional under the code ladder (wb@2 < k@3 <=>
    #                 wb <= k; k@3 < we@1 <=> k < we)
    #   range-point:  [rb,re) hits [k,k+'\0') iff rb <= k < re (rb@3 < k@4
    #                 <=> rb <= k; k@4 < re@0 <=> k < re)
    #   range-range:  the classic endpoint-order compares
    ov_pr = (
        (pos_wb[None, :] < pos_rpb[:, None])          # wb <= k
        & (pos_rpb[:, None] < pos_we[None, :])        # k < we
        & (batch["w_txn"][None, :] < batch["rp_txn"][:, None])
        & rp_valid[:, None] & w_valid[None, :]
    )
    nonempty = ~empty_r
    ov_rp = (
        (pos_rb[:, None] < pos_wpb[None, :])          # rb <= k
        & (pos_wpb[None, :] < pos_re[:, None])        # k < re
        & (batch["wp_txn"][None, :] < batch["r_txn"][:, None])
        & (nonempty & r_valid)[:, None] & wp_valid[None, :]
    )
    ov_rr = (
        (pos_rb[:, None] < pos_we[None, :])
        & (pos_wb[None, :] < pos_re[:, None])
        & (batch["w_txn"][None, :] < batch["r_txn"][:, None])
        & (nonempty & r_valid)[:, None] & w_valid[None, :]
    )
    # Bit-pack edges (MiniConflictSet's word trick, SkipList.cpp:1028-1130,
    # transplanted to the VPU). The fixpoint touches only these packed
    # words plus the gid vectors per iteration.
    edges = {
        # all reads x RANGE writes: [r_all, wr_words]
        "ovw": _pack_bits(jnp.concatenate([ov_pr, ov_rr], axis=0), cfg.wr_words),
        # RANGE reads x point writes: [Rr, wp_words]
        "ovrp": _pack_bits(ov_rp, cfg.wp_words),
        # per-key group ids of point rows (equal gid == equal key)
        "gid_rp": gid_rp,
        "gid_wp": gid_wp,
    }
    if cfg.heat_buckets > 0:
        # Row-level history-witness context for the heat aggregate
        # (heat_of): which read rows hit history, at what stored version.
        # Rides inside `edges` so every (hist, edges, wpos) unpack site
        # stays untouched; absent when heat is off, so the heat-off
        # pytrees — and programs — are byte-for-byte unchanged. The
        # fixpoint engines read edges by key and ignore these.
        edges["heat_hhit_p"] = hit_p
        edges["heat_hver_p"] = vmax_p
        if Rr > 0:
            edges["heat_hhit_r"] = hit_rg
            edges["heat_hver_r"] = rmax
        else:
            edges["heat_hhit_r"] = jnp.zeros((0,), jnp.bool_)
            edges["heat_hver_r"] = jnp.zeros((0,), jnp.int32)
    return hist_hits, edges, wpos


def _group_bounds(txn: jnp.ndarray, valid: jnp.ndarray, T: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Row range [starts[t], ends[t]) of txn t's rows within one group
    (valid rows are a prefix, grouped by ascending txn)."""
    cnt = jnp.zeros((T,), jnp.int32).at[jnp.where(valid, txn, T)].add(1, mode="drop")
    ends = jnp.cumsum(cnt)
    return ends - cnt, ends


def _read_group_bounds(cfg: KernelConfig, batch: Dict[str, jnp.ndarray]):
    """Per-txn row windows of the two read groups — loop-invariant across
    fixpoint iterations, so callers compute them ONCE outside the
    while_loop (each iteration is launch-overhead-bound: ~20 small ops at
    ~15us each; two scatter+cumsum rounds per iteration are pure waste)."""
    T = cfg.max_txns
    ps, pe = _group_bounds(batch["rp_txn"], batch["rp_valid"], T)
    rs, re_ = _group_bounds(batch["r_txn"], batch["r_valid"], T)
    return ps, pe, rs, re_


def _blocked_rows(
    cfg: KernelConfig,
    edges: Dict[str, jnp.ndarray],
    batch: Dict[str, jnp.ndarray],
    c: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-read-row intra-batch blocked flags under committed mask c:
    (point rows [Rp], range rows [Rr]). The shared inner step of every
    fixpoint iteration — also reused by heat_of with the FINAL committed
    mask to attribute intra-batch aborts to their witness rows (same ops,
    so the heat pass can never diverge from the verdict path)."""
    T = cfg.max_txns
    Rp = cfg.rp
    G = cfg.gid_space
    cwp = c[batch["wp_txn"]] & batch["wp_valid"]                     # [Wp]
    cwr = c[batch["w_txn"]] & batch["w_valid"]                       # [Wr]
    maskw = _pack_bits(cwr, cfg.wr_words)
    hit_w = jnp.any(edges["ovw"] & maskw[None, :], axis=-1)          # [r_all]
    maskp = _pack_bits(cwp, cfg.wp_words)
    hit_rp = jnp.any(edges["ovrp"] & maskp[None, :], axis=-1)        # [Rr]
    # point-point per-gid min of committed writer txns (T = +inf).
    # gids are a 1-based cumsum over the N sorted rows, so G+1 (== N+1)
    # is a safe dustbin slot for uncommitted rows.
    mn = jnp.full((G + 2,), T, jnp.int32).at[
        jnp.where(cwp, edges["gid_wp"], G + 1)
    ].min(batch["wp_txn"], mode="drop")
    hit_pp = mn[edges["gid_rp"]] < batch["rp_txn"]                   # [Rp]
    return hit_w[:Rp] | hit_pp, hit_w[Rp:] | hit_rp


def _blocked_txns(
    cfg: KernelConfig,
    edges: Dict[str, jnp.ndarray],
    batch: Dict[str, jnp.ndarray],
    c: jnp.ndarray,
    bounds=None,
) -> jnp.ndarray:
    """One shard's per-txn blocked counts [T] given the current committed
    mask c [T] — the body of each fixpoint iteration. Additive across
    disjoint key shards (counts, not bools), so callers combine shards with
    psum (mesh) or a leading-axis sum (single-device sub-shards)."""
    ps, pe, rs, re_ = bounds if bounds is not None else _read_group_bounds(cfg, batch)

    def seg_count(hit, starts, ends):
        csum = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(hit.astype(jnp.int32))])
        return csum[ends] - csum[starts]

    hit_point, hit_range = _blocked_rows(cfg, edges, batch, c)
    return seg_count(hit_point, ps, pe) + seg_count(hit_range, rs, re_)


def commit_fixpoint(
    cfg: KernelConfig,
    t_ok: jnp.ndarray,
    hist_hits: jnp.ndarray,
    edges: Dict[str, jnp.ndarray],
    batch: Dict[str, jnp.ndarray],
    allreduce=lambda x: x,
) -> jnp.ndarray:
    """Earlier-in-batch-wins verdicts via bit-packed + segment-min fixpoint.

    Each iteration:
      1. point reads vs point writes (the dominant block): scatter the
         committed point-write txn indices to a per-gid min, gather per
         point read — a read is hit iff the min committed writer txn of
         its key group is strictly earlier in the batch. No [Rp, Wp]
         matrix exists anywhere.
      2. reads vs range writes / range reads vs point writes: AND the
         packed edge words against the iteration's committed masks,
      3. reduce reads -> txns with cumsums + [T] gathers per read group
         (rows are grouped by ascending owning txn within each group),
      4. `allreduce` the per-txn blocked counts ([T] int32; txn index space
         is the only space shared across shards — read rows are shard-local
         — and counts are additive across disjoint key shards; the sharded
         engine psums this 8KB vector over ICI).
    All inputs to the while condition are allreduced values, so every shard
    runs the identical number of iterations in lockstep. All arithmetic is
    integer, so >0 tests bit-match the oracle's set semantics.
    """
    T = cfg.max_txns
    base_commit = t_ok & ~(hist_hits > 0)
    bounds = _read_group_bounds(cfg, batch)

    def blocked_of(c):
        return allreduce(_blocked_txns(cfg, edges, batch, c, bounds)) > 0  # psum over shards

    # Earlier-in-batch-wins is a DAG over u < t edges; iterate to its unique
    # fixpoint (equivalent to the reference's in-order sweep).
    def fix_cond(carry):
        c, prev, it = carry
        return jnp.any(c != prev) & (it < T)

    def fix_body(carry):
        c, _, it = carry
        return base_commit & ~blocked_of(c), c, it + 1

    c0 = base_commit
    c1 = base_commit & ~blocked_of(c0)
    committed, _, _ = lax.while_loop(fix_cond, fix_body, (c1, c0, jnp.int32(0)))
    return committed


def _merge_runs(
    cfg: KernelConfig,
    hkeys: jnp.ndarray, hvers: jnp.ndarray, n: jnp.ndarray,
    rkeys: jnp.ndarray, rvers: jnp.ndarray, rn: jnp.ndarray,
    nruns: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The lazy device-side compaction: fold base + every active run into
    one key-sorted boundary table. Returns (mkeys, mvers, m_n, overflow,
    dropped) — dropped counts the physical rows the compaction retired
    (superseded same-key rows + value-redundant boundaries, which after a
    horizon rebase is exactly the GC reclamation the monolithic keep rule
    performs eagerly).

    Two stages, neither of which sorts the base (XLA has no k-way merge
    primitive, but a full H-row sort per merge priced the merge at ~6x
    the monolithic re-merge — the base is ALREADY key-sorted, and run
    rows are O(NR*RC) << H):

      1. Fold the NR runs alone: one small sort of the NR*RC run rows,
         a batched [NR, NR*RC] cummax forward fill (each run's map value
         at every sorted run key; the combined runs-map value is the max
         over runs — versions only grow with recency), one delta row per
         distinct run key, value-redundant delta rows dropped. Max is
         associative, so max(base, run_1..run_NR) == max(base, delta).
      2. Merge the delta boundary list into the base positionally — the
         same sort-free scatter+cumsum arithmetic as the monolithic
         phase 4: lower-bound every delta key into the base (the
         branchless bsearch), mark base rows inside covering delta
         segments (value != NEG: every run version outstrips every base
         version, so coverage == overwrite) plus equal-key base rows as
         dead, rewrite NEG delta rows to the preserved base tail
         hvers[upper-1] (NEG means "lower tiers show through"), scatter
         kept base + delta rows into merged order, then one global
         value-equal-predecessor pass (boundary redundancy; subsumes the
         monolithic GC compaction once versions have been rebased to the
         -1 floor). The pre-compaction image is H + NR*RC rows so an
         overflowing merge still counts m_n exactly before truncating."""
    NR, RC = cfg.run_slots, cfg.run_rows
    H, K = cfg.capacity, cfg.lanes
    Md = NR * RC

    # ---- Stage 1: fold the runs into one coalesced delta boundary list ----
    akeys = rkeys.reshape(Md, K)
    avers = rvers.reshape(Md)
    asrc = jnp.repeat(jnp.arange(NR, dtype=jnp.int32), RC)
    avalid = ((jnp.arange(RC)[None, :] < rn[:, None])
              & (jnp.arange(NR)[:, None] < nruns)).reshape(-1)

    idx_bits = max(1, (Md - 1).bit_length())
    keys_eff = jnp.where(avalid[:, None], akeys, jnp.uint32(0xFFFFFFFF))
    pidx = jnp.arange(Md, dtype=jnp.uint32)
    codeidx = (jnp.where(avalid, jnp.uint32(0), jnp.uint32(1)) << idx_bits) | pidx
    ops = tuple(keys_eff[:, c] for c in range(K)) + (codeidx,)
    s = lax.sort(ops, num_keys=K + 1)
    sidx = (s[K] & jnp.uint32((1 << idx_bits) - 1)).astype(jnp.int32)
    svalid = (s[K] >> idx_bits) == 0
    skeys = jnp.stack(s[:K], axis=1)
    ssrc = asrc[sidx]
    svers = avers[sidx]
    posn = jnp.arange(Md, dtype=jnp.int32)

    src_ids = jnp.arange(NR, dtype=jnp.int32)[:, None]
    tag2 = jnp.where(svalid[None, :] & (ssrc[None, :] == src_ids),
                     posn[None, :], -1)
    last2 = lax.cummax(tag2, axis=1)
    val2 = jnp.where(last2 >= 0, svers[jnp.maximum(last2, 0)], NEG_VERSION)
    dval = jnp.max(val2, axis=0)

    # One delta row per distinct run key: the last row of each equal-key
    # group (invalid all-ones rows cluster at the end, never equal real
    # keys). Runs-internal value-redundant boundaries drop here; a row
    # the global pass below would keep is never dropped early (a base
    # row between equal-valued run boundaries is itself covered or
    # carries the same fill, so the global verdict matches).
    diff_next = jnp.any(skeys != jnp.concatenate([skeys[1:], skeys[-1:]]), axis=-1)
    diff_next = diff_next.at[Md - 1].set(True)
    is_cand = svalid & diff_next
    ptag = jnp.where(is_cand, posn, -1)
    prevc = jnp.concatenate([jnp.full((1,), -1, jnp.int32), lax.cummax(ptag)[:-1]])
    prev_val = jnp.where(prevc >= 0, dval[jnp.maximum(prevc, 0)], jnp.int32(2**30))
    dkeep = is_cand & (dval != prev_val)

    dpos = jnp.cumsum(dkeep.astype(jnp.int32)) - 1
    d_n = jnp.sum(dkeep.astype(jnp.int32))
    dc = jnp.zeros((Md, K + 1), jnp.uint32).at[
        jnp.where(dkeep, dpos, Md)
    ].set(jnp.concatenate([skeys, _i2u(dval)[:, None]], axis=1), mode="drop")
    dkeys = dc[:, :K]
    dvers = _u2i(dc[:, K])

    # ---- Stage 2: positional merge of the delta into the sorted base ----
    valid_d = jnp.arange(Md, dtype=jnp.int32) < d_n
    lo = _lower_bound_n(hkeys, n, dkeys, cfg.levels)
    eq = valid_d & (lo < n) & _key_eq(hkeys[jnp.minimum(lo, H - 1)], dkeys)
    # Preserved tail for NEG delta rows: the base map value at the delta
    # key, hvers[upper_bound - 1] (upper == lo + eq: boundary keys are
    # distinct). No base row at or below the key -> stays NEG.
    ubm1 = lo + eq.astype(jnp.int32) - 1
    fill = jnp.where(ubm1 >= 0, hvers[jnp.maximum(ubm1, 0)], NEG_VERSION)
    dv2 = jnp.where(dvers == NEG_VERSION, fill, dvers)

    # Base rows inside a covering delta segment [key_i, key_{i+1}) with
    # value != NEG are overwritten (delta versions outstrip base); an
    # equal-key base row is superseded by its delta row either way.
    covering = valid_d & (dvers != NEG_VERSION)
    nxt_lo = jnp.concatenate([lo[1:], jnp.zeros((1,), lo.dtype)])
    stop = jnp.where(jnp.arange(Md) + 1 < d_n, nxt_lo, n.astype(lo.dtype))
    cov_delta = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(covering, lo, H + 1)].add(1, mode="drop")
        .at[jnp.where(covering, stop, H + 1)].add(-1, mode="drop")
    )
    covered = jnp.cumsum(cov_delta[:H]) > 0
    eq_kill = jnp.zeros((H,), bool).at[
        jnp.where(eq, lo, H)].set(True, mode="drop")
    jslot = jnp.arange(H, dtype=jnp.int32)
    old_keep = (jslot < n) & ~covered & ~eq_kill

    # Merged positions, monolithic phase-4 style: kept base rows shift by
    # the delta rows inserted before them; delta rows shift by the kept
    # base rows before them.
    cum_keep = jnp.cumsum(old_keep.astype(jnp.int32))
    new_cnt = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(valid_d, lo, H + 1)].add(1, mode="drop")
    )
    new_before_old = jnp.cumsum(new_cnt[:H])
    pos_old = cum_keep - 1 + new_before_old
    drop_before = jnp.cumsum((covered | eq_kill).astype(jnp.int32))
    db = jnp.where(lo > 0, drop_before[jnp.maximum(lo - 1, 0)], 0)
    pos_new = jnp.arange(Md, dtype=jnp.int32) + (lo - db)

    G = H + Md
    gc_img = jnp.concatenate(
        [jnp.zeros((G, K), jnp.uint32), jnp.full((G, 1), _i2u(NEG_VERSION))], axis=1
    ).at[jnp.where(old_keep, pos_old, G)].set(
        jnp.concatenate([hkeys, _i2u(hvers)[:, None]], axis=1), mode="drop"
    ).at[jnp.where(valid_d, pos_new, G)].set(
        jnp.concatenate([dkeys, _i2u(dv2)[:, None]], axis=1), mode="drop")
    gvers = _u2i(gc_img[:, K])
    mn_raw = cum_keep[H - 1] + d_n

    # Global boundary-redundancy pass over the merged image: drop rows
    # whose value equals the previous merged row's (pre-drop) value — the
    # first row's sentinel can never match a real version.
    pv = jnp.concatenate([jnp.full((1,), 2**30, jnp.int32), gvers[:-1]])
    keep = (jnp.arange(G, dtype=jnp.int32) < mn_raw) & (gvers != pv)

    cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    m_n = jnp.sum(keep.astype(jnp.int32))
    outc = jnp.concatenate(
        [jnp.zeros((H, K), jnp.uint32), jnp.full((H, 1), _i2u(NEG_VERSION))], axis=1
    ).at[jnp.where(keep, cpos, H)].set(gc_img, mode="drop")
    total = n + jnp.sum(jnp.where(jnp.arange(NR) < nruns, rn, 0))
    dropped = (total - m_n).astype(jnp.int32)
    return outc[:, :K], _u2i(outc[:, K]), m_n.astype(jnp.int32), m_n > H, dropped


def _tiered_apply(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],
    batch: Dict[str, jnp.ndarray],
    ub_keys: jnp.ndarray,
    ue_keys: jnp.ndarray,
    u_count: jnp.ndarray,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Tiered phases 4-5: append the batch's committed-write union as one
    sorted run (O(batch) — a stack/reshape plus one dynamic_update_slice;
    the capacity-H table is never rewritten), merge only when the run
    slots are full, and apply GC as a range deletion: an elementwise
    horizon rebase of base + runs with physical reclamation deferred to
    the next merge. `reclaimed` therefore moves at merge time (rows the
    compaction retired) instead of per-GC-batch."""
    NR, RC = cfg.run_slots, cfg.run_rows
    H, K = cfg.capacity, cfg.lanes
    Wa = cfg.w_all
    now, gc = batch["now"], batch["gc"]
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    rkeys, rvers, rn = state["rkeys"], state["rvers"], state["rn"]
    nruns = state["nruns"]

    # The new run image [RC, K]/[RC]: interleaved (union-begin, now) /
    # (union-end, NEG gap) rows — strictly increasing keys because the
    # union sweep merges touching ranges — padded with all-ones keys and
    # NEG versions so stray probes past rn answer NEG.
    valid_u = jnp.arange(Wa, dtype=jnp.int32) < u_count
    nrk = jnp.stack([ub_keys, ue_keys], axis=1).reshape(2 * Wa, K)
    nrv = jnp.stack(
        [jnp.full((Wa,), now, jnp.int32),
         jnp.full((Wa,), NEG_VERSION, jnp.int32)], axis=1).reshape(2 * Wa)
    row_valid = jnp.repeat(valid_u, 2)
    runk = jnp.where(row_valid[:, None], nrk, jnp.uint32(0xFFFFFFFF))
    runv = jnp.where(row_valid, nrv, NEG_VERSION)
    pad = RC - 2 * Wa
    if pad:
        runk = jnp.concatenate(
            [runk, jnp.full((pad, K), jnp.uint32(0xFFFFFFFF))], axis=0)
        runv = jnp.concatenate(
            [runv, jnp.full((pad,), NEG_VERSION, jnp.int32)], axis=0)
    has_rows = u_count > 0

    # Lazy merge: only when the incoming run needs a slot and none is
    # free. Empty unions (read-only batches) never claim a slot, so a
    # read-dominated steady state never pays a merge at all.
    do_merge = has_rows & (nruns >= NR)

    def merged(_):
        mk, mv, mn_, moverflow, dropped = _merge_runs(
            cfg, hkeys, hvers, n, rkeys, rvers, rn, nruns)
        return (mk, mv, mn_,
                jnp.full((NR, RC, K), jnp.uint32(0xFFFFFFFF)),
                jnp.full((NR, RC), NEG_VERSION, jnp.int32),
                jnp.zeros((NR,), jnp.int32), jnp.zeros((), jnp.int32),
                moverflow, dropped)

    def unmerged(_):
        return (hkeys, hvers, n, rkeys, rvers, rn, nruns,
                jnp.asarray(False), jnp.zeros((), jnp.int32))

    bk, bv, bn, rk1, rv1, rn1, nr1, overflow, reclaimed = lax.cond(
        do_merge, merged, unmerged, None)

    # Append at the first free slot (post-merge that is slot 0).
    def appended(_):
        slot = jnp.minimum(nr1, NR - 1)
        z = jnp.zeros((), slot.dtype)   # match index dtypes under x64
        return (lax.dynamic_update_slice(rk1, runk[None], (slot, z, z)),
                lax.dynamic_update_slice(rv1, runv[None], (slot, z)),
                rn1.at[slot].set((2 * u_count).astype(rn1.dtype)),
                nr1 + 1)

    def skipped(_):
        return rk1, rv1, rn1, nr1

    rk2, rv2, rn2, nr2 = lax.cond(has_rows, appended, skipped, None)

    # GC as a range deletion: one elementwise horizon rebase over base +
    # runs (the appended run included — its `now` rows rebase exactly as
    # the monolithic path rebases its freshly merged rows). NEG gap rows
    # must stay NEG: a plain subtract would underflow int32 AND turn gaps
    # into -1 "covered at floor" rows, silently extending coverage.
    jslot = jnp.arange(H, dtype=jnp.int32)
    bv = jnp.where(
        gc > 0,
        jnp.where(jslot < bn, jnp.maximum(bv - gc, -1), NEG_VERSION),
        bv)
    rv2 = jnp.where(
        gc > 0,
        jnp.where(rv2 == NEG_VERSION, NEG_VERSION, jnp.maximum(rv2 - gc, -1)),
        rv2)

    new_state = {
        "hkeys": bk, "hvers": bv, "n": bn.astype(jnp.int32),
        "rkeys": rk2, "rvers": rv2, "rn": rn2.astype(jnp.int32),
        "nruns": nr2.astype(jnp.int32),
    }
    return new_state, overflow, reclaimed


def apply_writes_and_gc(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],
    batch: Dict[str, jnp.ndarray],
    committed: jnp.ndarray,
    wpos: Dict[str, jnp.ndarray],
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Phases 3-5, shard-local: committed-write union, boundary-table merge,
    GC/rebase. Returns (new_state, overflow, reclaimed) — reclaimed is the
    int32 count of boundary rows the GC compaction dropped (0 on gc == 0
    batches), the occupancy-pressure signal the heat aggregate carries.
    `wpos` carries the OLD-table positions of every write-interval endpoint
    (precomputed by the step's fused search in local_phases), so this phase
    performs NO binary search — union rows recover their positions through
    the sort's pidx payload."""
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    Wa = cfg.w_all
    H = cfg.capacity
    K = cfg.lanes
    now = batch["now"]
    w_txn_all = jnp.concatenate([batch["wp_txn"], batch["w_txn"]])
    w_valid_all = jnp.concatenate([batch["wp_valid"], batch["w_valid"]])
    bkeys = jnp.concatenate([batch["wpb"], batch["wb"]], axis=0)          # [Wa, K]
    ekeys = jnp.concatenate([_bump(batch["wpb"]), batch["we"]], axis=0)   # [Wa, K]

    # ---- Phase 3: committed-write union (combineWriteConflictRanges:1320) ----
    # Same operand packing as the fused sort: all-ones keys push
    # uncommitted rows past every real key, and (code | original index)
    # share one word — 6 sort operands instead of 8.
    cw = w_valid_all & committed[w_txn_all]
    allk = jnp.concatenate([bkeys, ekeys], axis=0)                        # [2Wa, K]
    ecode = jnp.concatenate([jnp.zeros((Wa,), jnp.uint32), jnp.ones((Wa,), jnp.uint32)])
    evalid = jnp.concatenate([cw, cw])
    eidx_bits = max(1, (2 * Wa - 1).bit_length())
    ekeys_eff = jnp.where(evalid[:, None], allk, jnp.uint32(0xFFFFFFFF))
    epidx = jnp.arange(2 * Wa, dtype=jnp.uint32)
    ecodeidx = (jnp.where(evalid, ecode, jnp.uint32(3)) << eidx_bits) | epidx
    eops = tuple(ekeys_eff[:, c] for c in range(K)) + (ecodeidx,)
    es = lax.sort(eops, num_keys=K + 1)
    s_code = es[K] >> eidx_bits
    s_valid = s_code < 2
    s_delta = jnp.where(s_code == 0, 1, -1)
    s_keys = jnp.stack(es[:K], axis=1)                                    # [2Wa, K]
    s_pidx = (es[K] & jnp.uint32((1 << eidx_bits) - 1)).astype(jnp.int32)

    d = jnp.where(s_valid, s_delta, 0)
    cum = jnp.cumsum(d)
    is_ub = s_valid & (s_delta > 0) & ((cum - d) == 0)
    is_ue = s_valid & (s_delta < 0) & (cum == 0)
    ubi = jnp.cumsum(is_ub.astype(jnp.int32)) - 1
    uei = jnp.cumsum(is_ue.astype(jnp.int32)) - 1
    u_count = jnp.sum(is_ub.astype(jnp.int32))
    # Union rows: keys + the endpoint positions recovered via pidx (begin
    # rows index wpos lower(begin); end rows index lower/upper(end)).
    pe_lo = jnp.concatenate([wpos["lo_b"], wpos["lo_e"]])                 # [2Wa]
    pe_up = jnp.concatenate([wpos["lo_b"], wpos["up_e"]])                 # begins unused
    sc = jnp.concatenate(
        [s_keys, _i2u(pe_lo[s_pidx])[:, None], _i2u(pe_up[s_pidx])[:, None]], axis=1)
    ubc = jnp.zeros((Wa, K + 2), jnp.uint32).at[jnp.where(is_ub, ubi, Wa)].set(sc, mode="drop")
    uec = jnp.zeros((Wa, K + 2), jnp.uint32).at[jnp.where(is_ue, uei, Wa)].set(sc, mode="drop")
    ub_keys = ubc[:, :K]
    ue_keys = uec[:, :K]
    u_start = _u2i(ubc[:, K])                                             # lower(ub)
    u_stop = _u2i(uec[:, K])                                              # lower(ue)
    # Version at each union end = pre-batch map value there (preserved tail):
    # hvers[upper(ue) - 1].
    ue_ver = hvers[jnp.maximum(_u2i(uec[:, K + 1]) - 1, 0)]

    if resolved_history_structure(cfg) == "tiered":
        # Tiered structure: phase 3's union IS the new run — phases 4-5
        # (the capacity-H re-merge + GC compaction) are replaced by an
        # O(batch) append, an elementwise horizon rebase, and a lazy
        # slots-full merge (_tiered_apply). ue_ver/u_start/u_stop stay
        # unused here: a run's NEG gap rows mean "lower tiers show
        # through", so no preserved-tail version is ever read.
        return _tiered_apply(cfg, state, batch, ub_keys, ue_keys, u_count)

    # ---- Phase 4: merge union into the boundary table at version `now` ----
    # Positions of old rows relative to the union are recovered with
    # scatter+cumsum sweeps over the table instead of per-old-row searches.
    jslot = jnp.arange(H, dtype=jnp.int32)
    valid_u = jnp.arange(Wa, dtype=jnp.int32) < u_count
    # covered[h] iff some union range [ub_i, ue_i) contains hkeys[h]:
    # delta sweep over [start_i, stop_i) index windows.
    cov_delta = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(valid_u, u_start, H + 1)].add(1, mode="drop")
        .at[jnp.where(valid_u, u_stop, H + 1)].add(-1, mode="drop")
    )
    covered = jnp.cumsum(cov_delta[:H]) > 0
    old_keep = (jslot < n) & ~covered

    # New rows: interleave begins (version=now) and ends (version=ue_ver);
    # the interleaving [ub0, ue0, ub1, ue1, ...] is already key-sorted.
    nb_keys = jnp.stack([ub_keys, ue_keys], axis=1).reshape(2 * Wa, K)
    nb_vers = jnp.stack([jnp.full((Wa,), now, jnp.int32), ue_ver], axis=1).reshape(2 * Wa)
    nb_lb = jnp.stack([u_start, u_stop], axis=1).reshape(2 * Wa)          # lower bound in hkeys
    j_of = jnp.repeat(jnp.arange(Wa, dtype=jnp.int32), 2)
    is_end_row = jnp.tile(jnp.array([False, True]), Wa)
    nb_valid = j_of < u_count
    # Drop an end row when an equal, uncovered old boundary already exists
    # (same version by construction, so keeping the old row is exact).
    lbc = jnp.minimum(nb_lb, H - 1)
    eq_exists = (nb_lb < n) & _key_eq(hkeys[lbc], nb_keys) & ~covered[lbc]
    nb_keep = nb_valid & ~(is_end_row & eq_exists)

    # Single combined compaction scatter: (keys | version | lower-bound) per
    # row, instead of three scatters walking the same target indices.
    ncomp_pos = jnp.cumsum(nb_keep.astype(jnp.int32)) - 1
    nc = jnp.sum(nb_keep.astype(jnp.int32))
    nbc = jnp.concatenate(
        [nb_keys, _i2u(nb_vers)[:, None], _i2u(nb_lb)[:, None]], axis=1
    )                                                                     # [2Wa, K+2]
    ncc = jnp.zeros((2 * Wa, K + 2), jnp.uint32).at[
        jnp.where(nb_keep, ncomp_pos, 2 * Wa)
    ].set(nbc, mode="drop")
    nck = ncc[:, :K]
    ncv = _u2i(ncc[:, K])
    lb_old = _u2i(ncc[:, K + 1])

    cum_keep = jnp.cumsum(old_keep.astype(jnp.int32))
    # new_before_old[h] = # kept new rows whose insertion point <= h.
    new_cnt = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(jnp.arange(2 * Wa) < nc, lb_old, H + 1)].add(1, mode="drop")
    )
    new_before_old = jnp.cumsum(new_cnt[:H])
    pos_old = cum_keep - 1 + new_before_old
    cum_cov = jnp.cumsum(covered.astype(jnp.int32))
    cov_before = jnp.where(lb_old > 0, cum_cov[jnp.maximum(lb_old - 1, 0)], 0)
    pos_new = jnp.arange(2 * Wa, dtype=jnp.int32) + (lb_old - cov_before)

    # Merge via two combined (keys | version) row scatters — old rows and new
    # rows — instead of four key/version scatter pairs.
    outc = jnp.concatenate(
        [jnp.zeros((H, K), jnp.uint32), jnp.full((H, 1), _i2u(NEG_VERSION))], axis=1
    )
    outc = outc.at[jnp.where(old_keep, pos_old, H)].set(
        jnp.concatenate([hkeys, _i2u(hvers)[:, None]], axis=1), mode="drop"
    )
    nc_mask = jnp.arange(2 * Wa) < nc
    outc = outc.at[jnp.where(nc_mask, pos_new, H)].set(
        jnp.concatenate([nck, _i2u(ncv)[:, None]], axis=1), mode="drop"
    )
    out_v = _u2i(outc[:, K])
    n1 = cum_keep[-1] + nc
    overflow = n1 > H

    # ---- Phase 5: GC + rebase (removeBefore:665; keep rule :686-698) ----
    # Under lax.cond: most batches carry gc == 0 (the host amortizes the GC
    # cadence), and the compaction scatter + cumsums over H are the apply
    # phase's largest cost after the union sort — skipping them when no GC
    # runs is a straight win (one branch executes on TPU).
    gc = batch["gc"]

    def compact(_):
        prev_v = jnp.concatenate([jnp.array([2**30], jnp.int32), out_v[:-1]])
        keep = (jslot < n1) & ((jslot == 0) | (out_v >= gc) | (prev_v >= gc))
        cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        finc = jnp.concatenate(
            [jnp.zeros((H, K), jnp.uint32), jnp.full((H, 1), _i2u(NEG_VERSION))], axis=1
        ).at[jnp.where(keep, cpos, H)].set(outc, mode="drop")
        n2 = jnp.sum(keep.astype(jnp.int32))
        fin_v = _u2i(finc[:, K])
        fin_v = jnp.where(jslot < n2, jnp.maximum(fin_v - gc, -1), NEG_VERSION)
        return finc[:, :K], fin_v, n2

    def no_gc(_):
        fin_v = jnp.where(jslot < n1, jnp.maximum(out_v, -1), NEG_VERSION)
        return outc[:, :K], fin_v, n1

    hk, hv, n2 = lax.cond(gc > 0, compact, no_gc, None)
    # n stays int32 under any jax_enable_x64 default: a drifting state
    # dtype would silently retrace/recompile the serving program on the
    # SECOND batch (the bucket ladder's AOT executables reject it loudly).
    new_state = {"hkeys": hk, "hvers": hv, "n": n2.astype(jnp.int32)}
    reclaimed = (n1 - n2).astype(jnp.int32)   # rows the GC branch dropped
    return new_state, overflow, reclaimed


def detect_step(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]):
    """Phases 1-2 only (no fixpoint, no writes): for the host long-key tier,
    which must combine global verdicts across device + host tiers BEFORE any
    tier applies writes. Returns (hist_hits, edges, wpos) — device-resident."""
    return local_phases(cfg, state, batch)


def fix_step(cfg: KernelConfig, t_ok: jnp.ndarray, hist_hits: jnp.ndarray,
             edges: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Re-run the earlier-in-batch-wins fixpoint with an updated t_ok mask
    (host-tier aborts folded in); cheap relative to detect_step."""
    return _fixpoint(cfg, t_ok, hist_hits, edges, batch)


def apply_step(cfg: KernelConfig, state: Dict[str, jnp.ndarray],
               batch: Dict[str, jnp.ndarray], committed: jnp.ndarray,
               wpos: Dict[str, jnp.ndarray]):
    """Apply the globally-agreed committed writes (+GC). Returns
    (new_state, overflow)."""
    new_state, overflow, _ = apply_writes_and_gc(cfg, state, batch, committed, wpos)
    return new_state, overflow


#: lanes of the heat aggregate's per-bucket histogram (heat_of)
HEAT_HIST_LANES = 3          # 0: read rows, 1: write rows, 2: conflict rows
#: lanes of the heat aggregate's scalar counts vector
HEAT_COUNT_LANES = 4         # 0: committed, 1: conflicts, 2: too_old, 3: gc_reclaimed


def _heat_bounds(cfg: KernelConfig, hkeys: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """B boundary keys sampled at equally spaced POSITIONS of the sorted
    valid table prefix hkeys[0:n] — the bucket delimiters of the heat
    histogram. Position sampling (not value sampling) makes the bucket
    grid adapt to the actual served key distribution: each bucket spans
    ~n/B of the table's distinct boundary keys, so a dense key region
    gets proportionally fine buckets. Bucket i covers [bounds[i],
    bounds[i+1]) (the last bucket extends to +inf; keys below bounds[0]
    fold into bucket 0)."""
    B = cfg.heat_buckets
    pos = (jnp.arange(B, dtype=jnp.int32) * jnp.maximum(n, 1)) // B
    return hkeys[pos]                                        # [B, K]


def _heat_bucket_of(cfg: KernelConfig, bounds: jnp.ndarray,
                    q: jnp.ndarray) -> jnp.ndarray:
    """Bucket index of every query key row q[i] under `bounds`: the last
    boundary <= q (clamped to 0 below bounds[0]) — a branchless binary
    search in the style of _lower_bound, ceil(log2 B)+1 unrolled rounds."""
    B = cfg.heat_buckets
    Q = q.shape[0]
    lo = jnp.zeros((Q,), jnp.int32)
    hi = jnp.full((Q,), B, jnp.int32)
    for _ in range(max(1, B.bit_length())):
        active = lo < hi
        mid = (lo + hi) >> 1
        # go right iff bounds[mid] <= q  (upper_bound discipline)
        go_right = ~_key_less(q, bounds[jnp.minimum(mid, B - 1)])
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return jnp.maximum(lo - 1, 0)


def heat_of(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],      # POST-apply state (bounds source)
    batch: Dict[str, jnp.ndarray],
    committed: jnp.ndarray,             # final fixpoint verdicts [T]
    edges: Dict[str, jnp.ndarray],      # incl. the heat_* witness context
    reclaimed: jnp.ndarray,             # GC-dropped rows (apply_writes_and_gc)
) -> Dict[str, jnp.ndarray]:
    """The per-batch keyspace-heat aggregate (docs/observability.md
    "Keyspace heat & occupancy"), computed ON DEVICE so it rides the
    existing dispatch with zero extra host syncs:

      bounds     uint32 [B, K]  sampled bucket-boundary keys (begin of each)
      hist       int32  [B, 3]  read / write / conflict-attributed rows
      counts     int32  [4]     committed, conflicts, too_old, gc_reclaimed
      occupancy  int32  []      boundary-table rows after this batch
      wit_ver    int32  [T]     first-witness conflicting-write version
                                (history hits: the stored version that beat
                                the snapshot; intra-batch: `now`), relative
                                to the engine base; NEG_VERSION when the
                                txn did not conflict
      wit_bucket int32  [T]     the witness read row's bucket; -1 when none

    Purely observational: every input is a value the verdict path already
    produced (the final committed mask, the phase-1 hit context riding in
    `edges`, the intra-batch blocked rows recomputed with the SAME
    _blocked_rows the fixpoint iterates) — so abort sets with heat on are
    bit-identical to heat off (tests/test_heat.py pins this across both
    history-search modes, step and loop dispatch)."""
    B = cfg.heat_buckets
    T = cfg.max_txns
    Rp, Rr = cfg.rp, cfg.max_reads
    bounds = _heat_bounds(cfg, state["hkeys"], state["n"])
    conflicted = batch["t_ok"] & ~committed
    counts = jnp.stack([
        jnp.sum(committed.astype(jnp.int32)),
        jnp.sum(conflicted.astype(jnp.int32)),
        jnp.sum(batch["t_too_old"].astype(jnp.int32)),
        reclaimed.astype(jnp.int32),
    ])

    # One packed bucket search serves every row class (read begins + write
    # begins; range rows bin by their begin key).
    qkeys = jnp.concatenate(
        [batch["rpb"], batch["rb"], batch["wpb"], batch["wb"]], axis=0)
    bk = _heat_bucket_of(cfg, bounds, qkeys)
    rbk = bk[:Rp + Rr]                                       # read rows
    wbk = bk[Rp + Rr:]                                       # write rows
    rvalid = jnp.concatenate([batch["rp_valid"], batch["r_valid"]])
    wvalid = jnp.concatenate([batch["wp_valid"], batch["w_valid"]])
    r_txn_all = jnp.concatenate([batch["rp_txn"], batch["r_txn"]])
    crow = rvalid & conflicted[r_txn_all]                    # conflict rows
    hist = (
        jnp.zeros((B, HEAT_HIST_LANES), jnp.int32)
        .at[jnp.where(rvalid, rbk, B), 0].add(1, mode="drop")
        .at[jnp.where(wvalid, wbk, B), 1].add(1, mode="drop")
        .at[jnp.where(crow, rbk, B), 2].add(1, mode="drop")
    )

    # First-witness abort attribution: for each conflicted txn, its first
    # (lowest-index) read row that was hit — by history (witness = the
    # stored version that beat the snapshot) or by an earlier committed
    # write in this batch (witness = `now`, the batch's own version).
    ihit_p, ihit_r = _blocked_rows(cfg, edges, batch, committed)
    hhit_p, hver_p = edges["heat_hhit_p"], edges["heat_hver_p"]
    hhit_r, hver_r = edges["heat_hhit_r"], edges["heat_hver_r"]
    now = batch["now"]
    act = jnp.concatenate([
        batch["rp_valid"] & (hhit_p | ihit_p),
        batch["r_valid"] & (hhit_r | ihit_r)]) & conflicted[r_txn_all]
    wver = jnp.concatenate([
        jnp.where(hhit_p, hver_p, now),
        jnp.where(hhit_r, hver_r, now)])
    R = Rp + Rr
    ridx = jnp.arange(R, dtype=jnp.int32)
    first = jnp.full((T,), R, jnp.int32).at[
        jnp.where(act, r_txn_all, T)].min(ridx, mode="drop")
    has = first < R
    fc = jnp.minimum(first, R - 1)
    wit_ver = jnp.where(has, wver[fc], NEG_VERSION)
    wit_bucket = jnp.where(has, rbk[fc], -1)
    out = {"bounds": bounds, "hist": hist, "counts": counts,
           "occupancy": state["n"], "wit_ver": wit_ver,
           "wit_bucket": wit_bucket}
    if resolved_history_structure(cfg) == "tiered":
        # tiered-history gauges ride the heat aggregate so run/merge
        # accounting reaches the host with ZERO extra syncs on every
        # dispatch surface: `runs` is the live run-stack depth post-apply
        # (the aggregator derives appends/merges from its transitions —
        # a drop means a lazy merge compacted the stack), `run_rows` the
        # summed valid rows across live runs (tier occupancy)
        NR = cfg.run_slots
        live = jnp.arange(NR, dtype=jnp.int32) < state["nruns"]
        out["runs"] = state["nruns"]
        out["run_rows"] = jnp.sum(jnp.where(live, state["rn"], 0)).astype(
            jnp.int32)
    return out


def heat_struct(cfg: KernelConfig, stack: Tuple[int, ...] = ()) -> Dict[str, jax.ShapeDtypeStruct]:
    """Abstract shapes of one batch's heat aggregate (what the server loop
    zero-initializes its per-slot planes from)."""
    B, K, T = cfg.heat_buckets, cfg.lanes, cfg.max_txns
    s = jax.ShapeDtypeStruct
    out = {
        "bounds": s(stack + (B, K), jnp.uint32),
        "hist": s(stack + (B, HEAT_HIST_LANES), jnp.int32),
        "counts": s(stack + (HEAT_COUNT_LANES,), jnp.int32),
        "occupancy": s(stack + (), jnp.int32),
        "wit_ver": s(stack + (T,), jnp.int32),
        "wit_bucket": s(stack + (T,), jnp.int32),
    }
    if resolved_history_structure(cfg) == "tiered":
        out["runs"] = s(stack + (), jnp.int32)
        out["run_rows"] = s(stack + (), jnp.int32)
    return out


def status_of(t_too_old: jnp.ndarray, committed: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(
        t_too_old,
        jnp.int32(int(TransactionCommitResult.TOO_OLD)),
        jnp.where(committed, jnp.int32(int(TransactionCommitResult.COMMITTED)),
                  jnp.int32(int(TransactionCommitResult.CONFLICT))),
    )


def _fixpoint(cfg: KernelConfig, t_ok, hist_hits, edges, batch) -> jnp.ndarray:
    """Dispatch to the configured single-shard fixpoint engine. An explicit
    'pallas' request on an unsupported shape raises rather than silently
    measuring the XLA path under the wrong label."""
    if cfg.fixpoint in ("pallas", "pallas_interpret"):
        from . import fixpoint_pallas as fp

        if not fp.supported(cfg):
            raise ValueError(
                f"fixpoint='{cfg.fixpoint}' requested but the config is not "
                f"kernel-supported (need max_txns%32==0 and the gid/txn "
                f"encoding to fit int32); use fixpoint='xla'")
        return fp.commit_fixpoint_pallas(
            cfg, t_ok, hist_hits, edges, batch,
            interpret=(cfg.fixpoint == "pallas_interpret"))
    if cfg.fixpoint != "xla":
        raise ValueError(f"unknown fixpoint engine {cfg.fixpoint!r}")
    return commit_fixpoint(cfg, t_ok, hist_hits, edges, batch)


def resolve_step(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """One single-shard resolver batch: (state, batch) -> (state', outputs).
    Pure; jit me. See local_phases for the batch layout. With
    cfg.heat_buckets > 0 the outputs additionally carry the per-batch
    `heat` aggregate (heat_of) — observational only, abort sets are
    bit-identical either way."""
    hist_hits, edges, wpos = local_phases(cfg, state, batch)
    committed = _fixpoint(cfg, batch["t_ok"], hist_hits, edges, batch)
    new_state, overflow, reclaimed = apply_writes_and_gc(
        cfg, state, batch, committed, wpos)
    out = {
        "status": status_of(batch["t_too_old"], committed),
        "overflow": overflow,
        "n": new_state["n"],
    }
    if cfg.heat_buckets > 0:
        out["heat"] = heat_of(cfg, new_state, batch, committed, edges,
                              reclaimed)
    return new_state, out


def commit_fixpoint_stacked(
    cfg: KernelConfig,
    t_ok: jnp.ndarray,                 # [T] global
    hist_stacked: jnp.ndarray,         # [S, T] per-sub-shard history hits
    edges: Dict[str, jnp.ndarray],     # leaves [S, ...]
    batch: Dict[str, jnp.ndarray],     # leaves [S, ...]
) -> jnp.ndarray:
    """Earlier-in-batch-wins fixpoint across S single-device sub-shards:
    the psum of the mesh engine becomes a leading-axis sum. One while_loop
    drives all sub-shards; per-iteration work is vmapped."""
    T = cfg.max_txns
    base_commit = t_ok & ~(jnp.sum(hist_stacked, axis=0) > 0)
    bounds = jax.vmap(lambda b: _read_group_bounds(cfg, b))(batch)
    blocked_v = jax.vmap(
        lambda e, b, bd, c: _blocked_txns(cfg, e, b, c, bd),
        in_axes=(0, 0, 0, None))

    def blocked_of(c):
        return jnp.sum(blocked_v(edges, batch, bounds, c), axis=0) > 0

    def fix_cond(carry):
        c, prev, it = carry
        return jnp.any(c != prev) & (it < T)

    def fix_body(carry):
        c, _, it = carry
        return base_commit & ~blocked_of(c), c, it + 1

    c0 = base_commit
    c1 = base_commit & ~blocked_of(c0)
    committed, _, _ = lax.while_loop(fix_cond, fix_body, (c1, c0, jnp.int32(0)))
    return committed


def resolve_step_stacked(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],     # leaves [S, ...]
    batch: Dict[str, jnp.ndarray],     # leaves [S, ...]
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """One resolver batch over S key-range SUB-shards resident on ONE
    device (vmap over the leading axis) — the on-device analog of the
    reference's SkipList::partition/concatenate multi-core path
    (SkipList.cpp:561-585), reshaped for XLA: S pro-rata tables mean S
    small sorts (bitonic cost N·log2(N)^2 makes 8 sorts of N/8 cheaper
    than one of N) and 1/S-sized packed edge blocks. Verdict combination
    is a leading-axis sum — bit-identical to the mesh engine's psum and to
    the single-table kernel. t_ok/t_too_old/now/gc must be replicated
    across the leading axis."""
    hist, edges, wpos = jax.vmap(
        lambda st, b: local_phases(cfg, st, b))(state, batch)
    t_ok = batch["t_ok"][0]
    committed = commit_fixpoint_stacked(cfg, t_ok, hist, edges, batch)
    new_state, overflow, reclaimed = jax.vmap(
        lambda st, b, w: apply_writes_and_gc(cfg, st, b, committed, w)
    )(state, batch, wpos)
    out = {
        "status": status_of(batch["t_too_old"][0], committed),
        "overflow": jnp.any(overflow),
        "n": new_state["n"],
    }
    if cfg.heat_buckets > 0:
        # per-sub-shard aggregates (each shard's table delimits its own
        # buckets); the host merges them keyed by boundary key
        out["heat"] = jax.vmap(
            lambda st, b, e, r: heat_of(cfg, st, b, committed, e, r)
        )(new_state, batch, edges, reclaimed)
    return new_state, out


def detect_step_stacked(cfg: KernelConfig, state, batch):
    """Stacked phases 1-2 for the split-step (host long-key tier) path."""
    return jax.vmap(lambda st, b: local_phases(cfg, st, b))(state, batch)


def fix_step_stacked(cfg: KernelConfig, t_ok, hist_stacked, edges, batch):
    return commit_fixpoint_stacked(cfg, t_ok, hist_stacked, edges, batch)


def apply_step_stacked(cfg: KernelConfig, state, batch, committed, wpos):
    new_state, overflow, _ = jax.vmap(
        lambda st, b, w: apply_writes_and_gc(cfg, st, b, committed, w)
    )(state, batch, wpos)
    return new_state, jnp.any(overflow)


def resolve_step_scan(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],
    batches: Dict[str, jnp.ndarray],   # leaves [C, ...]
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """C same-shape resolver batches as ONE device program: a lax.scan of
    resolve_step threading the interval-table state across chunks, so a
    multi-chunk batch costs one dispatch instead of C. Scan order equals
    the per-chunk dispatch order on the single device queue, so the
    status/overflow stacks are bit-identical to C serial resolve_steps.
    With heat on, the per-chunk aggregates stack under the same leading
    [C] axis."""

    def body(st, b):
        st, out = resolve_step(cfg, st, b)
        return st, (out["status"], out["overflow"], out.get("heat", {}))

    state, (status, overflow, heat) = lax.scan(body, state, batches)
    out = {"status": status, "overflow": overflow}
    if cfg.heat_buckets > 0:
        out["heat"] = heat
    return state, out


def resolve_step_stacked_scan(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],     # leaves [S, ...]
    batches: Dict[str, jnp.ndarray],   # leaves [C, S, ...]
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Fused chunk scan over the S-sub-shard stacked step (one device)."""

    def body(st, b):
        st, out = resolve_step_stacked(cfg, st, b)
        return st, (out["status"], out["overflow"], out.get("heat", {}))

    state, (status, overflow, heat) = lax.scan(body, state, batches)
    out = {"status": status, "overflow": overflow}
    if cfg.heat_buckets > 0:
        out["heat"] = heat              # leaves [C, S, ...]
    return state, out


def status_words(cfg: KernelConfig) -> int:
    """uint32 words per packed verdict bitmap lane: the server loop emits
    committed/too-old BITMAPS ([Q, status_words] each) instead of [Q, T]
    int32 statuses — a 16x smaller readback for the result ring the host
    polls without forcing a sync (ops/device_loop.py decodes them into
    the exact status_of values)."""
    return (cfg.max_txns + 31) // 32


def resolve_server_loop(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],
    batches: Dict[str, jnp.ndarray],   # leaves [Q, ...] — one queue slot
    n_chunks: jnp.ndarray,             # int32 scalar: filled prefix of the slot
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """The device-resident resolver SERVER step (docs/perf.md
    "Device-resident loop"): one dispatch consumes the filled prefix of a
    Q-chunk packed batch queue slot under a lax.while_loop that owns the
    interval-table state, so the host's steady-state work per batch is
    enqueue (device_put of packed columns) plus a non-blocking poll of the
    emitted abort bitmaps — never a per-chunk launch, never a blocking
    sync.

    Differences from resolve_step_scan, both load-bearing for the loop
    engine:
      * the chunk count is a RUNTIME scalar — ONE compiled program per
        bucket serves any fill level 1..Q (the scan ladder needs one
        program per (bucket, scan size), and a partially filled slot
        would still pay Q chunks of device time under a scan);
      * verdicts come back as packed bitmaps (status_words) — committed
        and too-old bit planes whose host decode is the same pure
        function of (committed, t_too_old) as status_of, so abort sets
        are bit-identical to the step path (tests/test_device_loop.py).
    Loop order equals the slot fill order equals the dispatch order on
    the device queue, so state evolution matches C serial resolve_steps.
    Rows beyond n_chunks are never read (the while_loop exits first).
    With cfg.heat_buckets > 0 the per-chunk heat aggregates ride the same
    readback as [Q, ...] planes (zeros beyond the filled prefix)."""
    Q = batches["t_ok"].shape[0]
    TW = status_words(cfg)
    heat_on = cfg.heat_buckets > 0
    committed_code = jnp.int32(int(TransactionCommitResult.COMMITTED))
    too_old_code = jnp.int32(int(TransactionCommitResult.TOO_OLD))

    def cond(carry):
        return carry[0] < n_chunks

    def body(carry):
        i, st, cbits, tbits, ov, heat = carry
        b = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, i, axis=0, keepdims=False),
            batches)
        st, out = resolve_step(cfg, st, b)
        cbits = lax.dynamic_update_index_in_dim(
            cbits, _pack_bits(out["status"] == committed_code, TW), i, axis=0)
        tbits = lax.dynamic_update_index_in_dim(
            tbits, _pack_bits(out["status"] == too_old_code, TW), i, axis=0)
        if heat_on:
            heat = jax.tree.map(
                lambda acc, h: lax.dynamic_update_index_in_dim(
                    acc, h.astype(acc.dtype), i, axis=0),
                heat, out["heat"])
        return i + 1, st, cbits, tbits, ov | out["overflow"], heat

    heat0 = ({name: jnp.zeros(s.shape, s.dtype)
              for name, s in heat_struct(cfg, stack=(Q,)).items()}
             if heat_on else {})
    carry = (jnp.int32(0), state,
             jnp.zeros((Q, TW), jnp.uint32),
             jnp.zeros((Q, TW), jnp.uint32),
             jnp.asarray(False), heat0)
    _, state, cbits, tbits, overflow, heat = lax.while_loop(cond, body, carry)
    out = {"commit_bits": cbits, "too_old_bits": tbits, "overflow": overflow}
    if heat_on:
        out["heat"] = heat
    return state, out


def state_struct(cfg: KernelConfig, stack: Tuple[int, ...] = ()) -> Dict[str, jax.ShapeDtypeStruct]:
    """Abstract shapes of the device interval-table state (initial_state),
    optionally stacked under leading axes — what an AOT .lower() needs."""
    s = jax.ShapeDtypeStruct
    out = {
        "hkeys": s(stack + (cfg.capacity, cfg.lanes), jnp.uint32),
        "hvers": s(stack + (cfg.capacity,), jnp.int32),
        "n": s(stack + (), jnp.int32),
    }
    if resolved_history_structure(cfg) == "tiered":
        # run planes exist ONLY under the tiered structure, so monolithic
        # pytrees — and every already-compiled program — stay byte-for-
        # byte unchanged
        out["rkeys"] = s(stack + (cfg.run_slots, cfg.run_rows, cfg.lanes), jnp.uint32)
        out["rvers"] = s(stack + (cfg.run_slots, cfg.run_rows), jnp.int32)
        out["rn"] = s(stack + (cfg.run_slots,), jnp.int32)
        out["nruns"] = s(stack + (), jnp.int32)
    return out


def batch_struct(cfg: KernelConfig, stack: Tuple[int, ...] = ()) -> Dict[str, jax.ShapeDtypeStruct]:
    """Abstract shapes/dtypes of one packed batch (build_batch_arrays /
    host_engine.wire_chunk_arrays emit exactly these), optionally stacked
    under leading axes ((S,) sub-shards, (C,) scan chunks, or (C, S))."""
    K = cfg.lanes
    s = jax.ShapeDtypeStruct

    def f(shape, dt):
        return s(stack + shape, dt)

    return {
        "rpb": f((cfg.rp, K), jnp.uint32),
        "rp_snap": f((cfg.rp,), jnp.int32),
        "rp_txn": f((cfg.rp,), jnp.int32),
        "rp_valid": f((cfg.rp,), jnp.bool_),
        "rb": f((cfg.max_reads, K), jnp.uint32),
        "re": f((cfg.max_reads, K), jnp.uint32),
        "r_snap": f((cfg.max_reads,), jnp.int32),
        "r_txn": f((cfg.max_reads,), jnp.int32),
        "r_valid": f((cfg.max_reads,), jnp.bool_),
        "wpb": f((cfg.wp, K), jnp.uint32),
        "wp_txn": f((cfg.wp,), jnp.int32),
        "wp_valid": f((cfg.wp,), jnp.bool_),
        "wb": f((cfg.max_writes, K), jnp.uint32),
        "we": f((cfg.max_writes, K), jnp.uint32),
        "w_txn": f((cfg.max_writes,), jnp.int32),
        "w_valid": f((cfg.max_writes,), jnp.bool_),
        "t_ok": f((cfg.max_txns,), jnp.bool_),
        "t_too_old": f((cfg.max_txns,), jnp.bool_),
        "now": f((), jnp.int32),
        "gc": f((), jnp.int32),
    }


def initial_state(cfg: KernelConfig, version_rel: int = 0, first_key: bytes = b"") -> Dict[str, jnp.ndarray]:
    """Fresh boundary table whose single interval [first_key, +inf) carries
    version_rel. Key-range shards pass their span begin as first_key."""
    hkeys = np.zeros((cfg.capacity, cfg.lanes), np.uint32)
    hkeys[0] = keypack.pack_key(first_key, cfg.key_words)
    hvers = np.full((cfg.capacity,), int(NEG_VERSION), np.int32)
    hvers[0] = version_rel
    out = {
        "hkeys": jnp.asarray(hkeys),
        "hvers": jnp.asarray(hvers),
        "n": jnp.asarray(1, jnp.int32),
    }
    if resolved_history_structure(cfg) == "tiered":
        out["rkeys"] = jnp.full(
            (cfg.run_slots, cfg.run_rows, cfg.lanes), 0xFFFFFFFF, jnp.uint32)
        out["rvers"] = jnp.full(
            (cfg.run_slots, cfg.run_rows), int(NEG_VERSION), jnp.int32)
        out["rn"] = jnp.zeros((cfg.run_slots,), jnp.int32)
        out["nruns"] = jnp.asarray(0, jnp.int32)
    return out


def history_run_snapshot(
    cfg: KernelConfig,
    state: Dict[str, jnp.ndarray],
    since_runs: int = 0,
) -> Dict[str, object]:
    """Host copy of the ACTIVE run planes only — the O(delta) incremental
    surface behind the ResilientEngine shadow rebuild and the reshard
    pre-copy handoff (fault/handoff.py run_slice): the un-merged runs ARE
    the history delta since the last compaction, so a receiver that
    already holds the merged base needs `sum(rn)` rows, never a
    capacity-H replay.

    `since_runs` is a caller-held watermark (the nruns value of its last
    snapshot): only runs appended after it are materialized. A merge
    resets nruns to 0-or-1, so `nruns < since_runs` in the returned dict
    tells the caller its watermark died with a compaction and a full
    resync (or base copy) is needed — exactly the LSM manifest contract.

    Returns {"structure", "nruns", "runs": [(keys [rn_j, K] uint32,
    vers [rn_j] int32), ...]} with numpy rows sliced to each run's valid
    prefix; rows alternate (interval-begin, version) / (interval-end,
    NEG gap) — see run_intervals for the decoded form."""
    structure = resolved_history_structure(cfg)
    if structure != "tiered":
        return {"structure": structure, "nruns": 0, "runs": []}
    nruns = int(state["nruns"])
    rn = np.asarray(state["rn"])
    lo = min(max(int(since_runs), 0), nruns)
    runs = []
    for j in range(lo, nruns):
        rows = int(rn[j])
        runs.append((np.asarray(state["rkeys"][j, :rows]),
                     np.asarray(state["rvers"][j, :rows])))
    return {"structure": structure, "nruns": nruns, "runs": runs}


def run_intervals(snapshot: Dict[str, object]):
    """Decode a history_run_snapshot into (begin_row, end_row, version)
    packed-key interval triples, oldest run first — the shape the host
    VersionIntervalMap coalescer consumes. Run rows alternate strictly:
    even rows open a committed-write union range at their version, odd
    rows close it with the NEG gap sentinel."""
    for keys, vers in snapshot["runs"]:
        for i in range(0, keys.shape[0] - 1, 2):
            yield keys[i], keys[i + 1], int(vers[i])


def build_batch_arrays(
    cfg: KernelConfig,
    rp_keys: List[bytes], rp_snap: List[int], rp_txn: List[int],
    r_keys_b: List[bytes], r_keys_e: List[bytes], r_snap: List[int], r_txn: List[int],
    wp_keys: List[bytes], wp_txn: List[int],
    w_keys_b: List[bytes], w_keys_e: List[bytes], w_txn: List[int],
    t_ok: np.ndarray, t_too_old: np.ndarray,
    now_rel: int, gc_rel: int,
) -> Dict[str, np.ndarray]:
    """Pad host-side range lists to the kernel's fixed shapes (numpy).

    Point rows carry only their begin key (the end is the on-device
    successor). Layout invariant relied on by commit_fixpoint's segment
    reduce: within each group, valid rows are a contiguous prefix grouped by
    ascending owning transaction index."""
    for lst in (rp_txn, r_txn):
        assert all(a <= b for a, b in zip(lst, lst[1:])), "read rows must be grouped by ascending txn"
    Rp, Rr, Wp, Wr, K = cfg.rp, cfg.max_reads, cfg.wp, cfg.max_writes, cfg.lanes

    def padk(keys: List[bytes], cap: int, endpoint: bool = False) -> np.ndarray:
        arr = np.zeros((cap, K), np.uint32)
        if keys:
            pack = keypack.pack_endpoint_keys if endpoint else keypack.pack_keys
            arr[: len(keys)] = pack(keys, cfg.key_words)
        return arr

    def padi(vals: List[int], cap: int) -> np.ndarray:
        return np.pad(np.asarray(vals, np.int32), (0, cap - len(vals)))

    return {
        "rpb": padk(rp_keys, Rp),
        "rp_snap": padi(rp_snap, Rp),
        "rp_txn": padi(rp_txn, Rp),
        "rp_valid": np.arange(Rp) < len(rp_txn),
        "rb": padk(r_keys_b, Rr, endpoint=True),
        "re": padk(r_keys_e, Rr, endpoint=True),
        "r_snap": padi(r_snap, Rr),
        "r_txn": padi(r_txn, Rr),
        "r_valid": np.arange(Rr) < len(r_txn),
        "wpb": padk(wp_keys, Wp),
        "wp_txn": padi(wp_txn, Wp),
        "wp_valid": np.arange(Wp) < len(wp_txn),
        "wb": padk(w_keys_b, Wr, endpoint=True),
        "we": padk(w_keys_e, Wr, endpoint=True),
        "w_txn": padi(w_txn, Wr),
        "w_valid": np.arange(Wr) < len(w_txn),
        "t_ok": np.asarray(t_ok, bool),
        "t_too_old": np.asarray(t_too_old, bool),
        "now": np.asarray(now_rel, np.int32),
        "gc": np.asarray(gc_rel, np.int32),
    }


def __getattr__(name):  # PEP 562: JaxConflictEngine lives in host_engine
    # (which imports this module); re-export lazily to avoid an import cycle.
    if name == "JaxConflictEngine":
        from .host_engine import JaxConflictEngine

        return JaxConflictEngine
    raise AttributeError(name)
