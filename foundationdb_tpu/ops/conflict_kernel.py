"""TPU-native batched conflict detection — the north-star kernel.

Re-design of the reference resolver's versioned skip list
(fdbserver/SkipList.cpp) as a data-parallel, fixed-shape XLA program:

  reference                      this kernel
  ---------                      -----------
  skip-list nodes                sorted boundary table hkeys[H, K] in HBM
  per-level maxVersion pyramid   sparse table (block-max) over hvers[H]
  16-way pipelined CheckMax      vectorized binary search + range-max gather
  radix sortPoints (:227)        one lax.sort of all endpoints w/ tie codes
  MiniConflictSet sweep (:1133)  overlap matrix + DAG fixpoint (while_loop)
  skip-list insert/remove        sort-free merge: searchsorted + scatter
  removeBefore GC (:665)         vectorized keep rule + compaction

Exactness: verdicts are a pure function of the logical version-interval map
(see ops/oracle.py); every op here (max, OR, integer compares) is
order-insensitive, so results are bit-identical to the oracle and hence to
the reference CPU resolver, for keys within the configured exact width.

Versions on device are int32 offsets from a host-tracked base (the 5-second
MVCC window MAX_WRITE_TRANSACTION_LIFE_VERSIONS = 5e6 << 2^31); versions at
or below the base are clamped to -1, which is semantics-preserving because
any read that passes the too-old gate has snapshot >= base.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core.types import TransactionCommitResult
from . import keypack

NEG_VERSION = jnp.int32(-(2**30))


@dataclass(frozen=True)
class KernelConfig:
    key_words: int = 4          # exact-compare width = 4*key_words bytes
    capacity: int = 1 << 16     # H: max boundaries in the interval table
    max_reads: int = 1 << 12    # R: read conflict ranges per device batch
    max_writes: int = 1 << 12   # W: write conflict ranges per device batch
    max_txns: int = 1 << 12     # T: transactions per device batch

    @property
    def lanes(self) -> int:     # K: words per packed key incl. length
        return self.key_words + 1

    @property
    def search_steps(self) -> int:
        return int(math.ceil(math.log2(self.capacity))) + 1

    @property
    def levels(self) -> int:    # sparse-table levels
        return int(math.ceil(math.log2(self.capacity))) + 1


def _key_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a < b over trailing lane axis (uint32 words + length)."""
    neq = a != b
    idx = jnp.argmax(neq, axis=-1)
    any_neq = jnp.any(neq, axis=-1)
    av = jnp.take_along_axis(a, idx[..., None], axis=-1)[..., 0]
    bv = jnp.take_along_axis(b, idx[..., None], axis=-1)[..., 0]
    return any_neq & (av < bv)


def _key_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def _search(cfg: KernelConfig, table: jnp.ndarray, count: jnp.ndarray, q: jnp.ndarray, lower: bool) -> jnp.ndarray:
    """Vectorized binary search over table[0:count] (sorted, [N,K]).

    lower=True  -> first i with table[i] >= q   (lower_bound)
    lower=False -> first i with table[i] >  q   (upper_bound)
    """
    nq = q.shape[0]
    lo = jnp.zeros((nq,), jnp.int32)
    hi = jnp.full((nq,), count, jnp.int32)
    for _ in range(cfg.search_steps):
        m = lo < hi
        mid = (lo + hi) >> 1
        row = table[mid]
        go_right = _key_less(row, q) if lower else ~_key_less(q, row)
        lo = jnp.where(m & go_right, mid + 1, lo)
        hi = jnp.where(m & ~go_right, mid, hi)
    return lo


def _build_sparse_max(cfg: KernelConfig, vers: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Sparse table: out[k, i] = max(vers[i : i+2^k]) with invalid slots -> NEG.

    This is the skip-list maxVersion pyramid (SkipList.cpp:350-357) flattened
    into a dense, gather-friendly layout."""
    h = cfg.capacity
    base = jnp.where(jnp.arange(h) < n, vers, NEG_VERSION)
    levels = [base]
    for k in range(1, cfg.levels):
        half = 1 << (k - 1)
        prev = levels[-1]
        shifted = jnp.concatenate([prev[half:], jnp.full((half,), NEG_VERSION, prev.dtype)])
        levels.append(jnp.maximum(prev, shifted))
    return jnp.stack(levels)  # [levels, H]


def _range_max(cfg: KernelConfig, sparse: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """max(vers[lo:hi]) for hi > lo, via two overlapping power-of-two blocks."""
    s = (hi - lo).astype(jnp.uint32)
    k = (31 - lax.clz(s)).astype(jnp.int32)
    flat = sparse.reshape(-1)
    h = cfg.capacity
    m1 = flat[k * h + lo]
    m2 = flat[k * h + hi - (1 << k).astype(jnp.int32)]
    return jnp.maximum(m1, m2)


def _compact_rows(keys: jnp.ndarray, vals: jnp.ndarray, keep: jnp.ndarray, out_rows: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scatter kept rows to the front of a fresh [out_rows] table (stable)."""
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, pos, out_rows)  # dropped rows go out of bounds
    ok = jnp.zeros((out_rows, keys.shape[1]), keys.dtype).at[tgt].set(keys, mode="drop")
    ov = jnp.full((out_rows,), NEG_VERSION, vals.dtype).at[tgt].set(vals, mode="drop")
    return ok, ov, jnp.sum(keep.astype(jnp.int32))


def local_phases(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phases 1-2, shard-local: reads vs. history + intra-batch overlap graph.

    Returns (hist_hits int32 [T], o_cnt float32 [T, T]). Both are additive
    across key-range shards (a hit/overlap occurs in >= 1 shard iff it occurs
    globally), so the multi-shard engine psums them over the mesh axis — the
    "conflict bitmaps allreduced over ICI" of the north star — before running
    the order-dependent fixpoint identically on every shard.

    batch fields (fixed shapes; see build_batch_arrays):
      rb, re   uint32 [R, K]   read range begin/end (packed keys)
      r_snap   int32  [R]      read snapshot, relative to base (>= 0)
      r_txn    int32  [R]      owning transaction index
      r_valid  bool   [R]
      wb, we   uint32 [W, K]   write ranges (non-empty only)
      w_txn    int32  [W]
      w_valid  bool   [W]
      t_ok     bool   [T]      valid txn, not too-old
      t_too_old bool  [T]
      now      int32  []       commit version - base
      gc       int32  []       new_oldest - base (<=0: no GC/rebase)
    """
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    R = cfg.max_reads
    W = cfg.max_writes
    T = cfg.max_txns
    K = cfg.lanes

    rb, re = batch["rb"], batch["re"]
    wb, we = batch["wb"], batch["we"]
    r_txn, w_txn = batch["r_txn"], batch["w_txn"]
    r_valid, w_valid = batch["r_valid"], batch["w_valid"]

    # ---- Phase 1: reads vs. history (checkReadConflictRanges:1210) ----
    sparse = _build_sparse_max(cfg, hvers, n)
    empty_r = ~_key_less(rb, re)
    lo_ne = _search(cfg, hkeys, n, rb, lower=False) - 1      # interval containing rb
    hi_ne = _search(cfg, hkeys, n, re, lower=True)           # first boundary >= re
    lo_e = jnp.maximum(_search(cfg, hkeys, n, rb, lower=True) - 1, 0)
    lo = jnp.where(empty_r, lo_e, lo_ne)
    hi = jnp.where(empty_r, lo_e + 1, hi_ne)
    rmax = _range_max(cfg, sparse, lo, hi)
    r_hit = r_valid & (rmax > batch["r_snap"])
    hist_hits = jnp.zeros((T,), jnp.int32).at[r_txn].max(r_hit.astype(jnp.int32), mode="drop")

    # ---- Phase 2: intra-batch (checkIntraBatchConflicts:1133) ----
    # Endpoint order with the reference's tie codes (getCharacter,
    # SkipList.cpp:147-177): at equal keys  end-read < end-write < begin-write
    # < begin-read, which makes integer position compare == exact half-open
    # overlap. Invalid rows sort last via a leading flag.
    P = 2 * R + 2 * W
    pkeys = jnp.concatenate([rb, re, wb, we], axis=0)                    # [P, K]
    pcode = jnp.concatenate([
        jnp.full((R,), 3, jnp.uint32),   # begin-read
        jnp.full((R,), 0, jnp.uint32),   # end-read
        jnp.full((W,), 2, jnp.uint32),   # begin-write
        jnp.full((W,), 1, jnp.uint32),   # end-write
    ])
    pvalid = jnp.concatenate([r_valid, r_valid, w_valid, w_valid])
    pinv = (~pvalid).astype(jnp.uint32)
    pidx = jnp.arange(P, dtype=jnp.uint32)
    ops = (pinv,) + tuple(pkeys[:, c] for c in range(K)) + (pcode, pidx)
    sorted_ops = lax.sort(ops, num_keys=K + 2, is_stable=True)
    sorted_idx = sorted_ops[-1]
    pos = jnp.zeros((P,), jnp.int32).at[sorted_idx].set(jnp.arange(P, dtype=jnp.int32))
    pos_rb, pos_re = pos[:R], pos[R : 2 * R]
    pos_wb, pos_we = pos[2 * R : 2 * R + W], pos[2 * R + W :]

    ov = (
        (pos_rb[:, None] < pos_re[:, None])      # non-empty read
        & (pos_rb[:, None] < pos_we[None, :])    # rb < we
        & (pos_wb[None, :] < pos_re[:, None])    # wb < re
        & r_valid[:, None]
        & w_valid[None, :]
    )
    # Reduce [R, W] -> per-transaction graph O[t, u] via one-hot matmuls (MXU).
    tids = jnp.arange(T, dtype=jnp.int32)
    a = (r_txn[:, None] == tids[None, :]) & r_valid[:, None]             # [R, T]
    b = (w_txn[:, None] == tids[None, :]) & w_valid[:, None]             # [W, T]
    ovb = jnp.dot(ov.astype(jnp.float32), b.astype(jnp.float32),
                  precision=lax.Precision.HIGHEST)                        # [R, T]
    o_cnt = jnp.dot(a.astype(jnp.float32).T, ovb,
                    precision=lax.Precision.HIGHEST)                      # [T, T]
    return hist_hits, o_cnt


def commit_fixpoint(cfg: KernelConfig, t_ok: jnp.ndarray, hist_hits: jnp.ndarray, o_cnt: jnp.ndarray) -> jnp.ndarray:
    """Earlier-in-batch-wins verdicts from the (globally combined) conflict
    inputs. Pure function of allreduced values, so every shard computes the
    identical committed vector with no further communication."""
    T = cfg.max_txns
    tids = jnp.arange(T, dtype=jnp.int32)
    o_strict = (o_cnt > 0) & (tids[None, :] < tids[:, None])             # u < t
    o_f32 = o_strict.astype(jnp.float32)

    base_commit = t_ok & ~(hist_hits > 0)
    # Earlier-in-batch-wins is a DAG over u < t edges; iterate to its unique
    # fixpoint (equivalent to the reference's in-order sweep).
    def fix_cond(carry):
        c, prev, it = carry
        return jnp.any(c != prev) & (it < T)

    def fix_body(carry):
        c, _, it = carry
        blocked = jnp.dot(o_f32, c.astype(jnp.float32),
                          precision=lax.Precision.HIGHEST) > 0
        return base_commit & ~blocked, c, it + 1

    c0 = base_commit
    c1 = base_commit & ~(jnp.dot(o_f32, c0.astype(jnp.float32), precision=lax.Precision.HIGHEST) > 0)
    committed, _, _ = lax.while_loop(fix_cond, fix_body, (c1, c0, jnp.int32(0)))
    return committed


def apply_writes_and_gc(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray], committed: jnp.ndarray) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Phases 3-5, shard-local: committed-write union, boundary-table merge,
    GC/rebase. Returns (new_state, overflow)."""
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    W = cfg.max_writes
    H = cfg.capacity
    K = cfg.lanes
    wb, we = batch["wb"], batch["we"]
    w_txn = batch["w_txn"]
    w_valid = batch["w_valid"]
    now = batch["now"]

    # ---- Phase 3: committed-write union (combineWriteConflictRanges:1320) ----
    cw = w_valid & committed[w_txn]
    ekeys = jnp.concatenate([wb, we], axis=0)                             # [2W, K]
    edelta = jnp.concatenate([jnp.ones((W,), jnp.int32), jnp.full((W,), -1, jnp.int32)])
    ecode = jnp.concatenate([jnp.zeros((W,), jnp.uint32), jnp.ones((W,), jnp.uint32)])
    evalid = jnp.concatenate([cw, cw])
    einv = (~evalid).astype(jnp.uint32)
    eops = (einv,) + tuple(ekeys[:, c] for c in range(K)) + (ecode, edelta.astype(jnp.uint32),) + tuple(
        ekeys[:, c] for c in range(K)
    )
    es = lax.sort(eops, num_keys=K + 2, is_stable=True)
    s_valid = es[0] == 0
    s_delta = jnp.where(es[K + 2].astype(jnp.int32) == 1, 1, -1)
    s_keys = jnp.stack(es[K + 3 :], axis=1)                               # [2W, K]
    d = jnp.where(s_valid, s_delta, 0)
    cum = jnp.cumsum(d)
    is_ub = s_valid & (s_delta > 0) & ((cum - d) == 0)
    is_ue = s_valid & (s_delta < 0) & (cum == 0)
    ubi = jnp.cumsum(is_ub.astype(jnp.int32)) - 1
    uei = jnp.cumsum(is_ue.astype(jnp.int32)) - 1
    u_count = jnp.sum(is_ub.astype(jnp.int32))
    ub_keys = jnp.zeros((W, K), jnp.uint32).at[jnp.where(is_ub, ubi, W)].set(s_keys, mode="drop")
    ue_keys = jnp.zeros((W, K), jnp.uint32).at[jnp.where(is_ue, uei, W)].set(s_keys, mode="drop")
    # Version at each union end = pre-batch map value there (preserved tail).
    ue_ver = hvers[_search(cfg, hkeys, n, ue_keys, lower=False) - 1]

    # ---- Phase 4: merge union into the boundary table at version `now` ----
    # All searches below are W/2W-query (never H-query): positions of old
    # rows relative to the union are recovered with scatter+cumsum sweeps
    # over the table instead of per-old-row binary searches (H >> W made
    # those the dominant cost on TPU).
    jslot = jnp.arange(H, dtype=jnp.int32)
    valid_u = jnp.arange(W, dtype=jnp.int32) < u_count
    # covered[h] iff some union range [ub_i, ue_i) contains hkeys[h]:
    # delta sweep over [start_i, stop_i) index windows.
    u_start = _search(cfg, hkeys, n, ub_keys, lower=True)                # [W]
    u_stop = _search(cfg, hkeys, n, ue_keys, lower=True)                 # [W]
    cov_delta = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(valid_u, u_start, H + 1)].add(1, mode="drop")
        .at[jnp.where(valid_u, u_stop, H + 1)].add(-1, mode="drop")
    )
    covered = jnp.cumsum(cov_delta[:H]) > 0
    old_keep = (jslot < n) & ~covered

    # New rows: interleave begins (version=now) and ends (version=ue_ver);
    # the interleaving [ub0, ue0, ub1, ue1, ...] is already key-sorted.
    nb_keys = jnp.stack([ub_keys, ue_keys], axis=1).reshape(2 * W, K)
    nb_vers = jnp.stack([jnp.full((W,), now, jnp.int32), ue_ver], axis=1).reshape(2 * W)
    nb_lb = jnp.stack([u_start, u_stop], axis=1).reshape(2 * W)          # lower bound in hkeys
    j_of = jnp.repeat(jnp.arange(W, dtype=jnp.int32), 2)
    is_end_row = jnp.tile(jnp.array([False, True]), W)
    nb_valid = j_of < u_count
    # Drop an end row when an equal, uncovered old boundary already exists
    # (same version by construction, so keeping the old row is exact).
    lbc = jnp.minimum(nb_lb, H - 1)
    eq_exists = (nb_lb < n) & _key_eq(hkeys[lbc], nb_keys) & ~covered[lbc]
    nb_keep = nb_valid & ~(is_end_row & eq_exists)

    ncomp_pos = jnp.cumsum(nb_keep.astype(jnp.int32)) - 1
    nc = jnp.sum(nb_keep.astype(jnp.int32))
    nck = jnp.zeros((2 * W, K), jnp.uint32).at[jnp.where(nb_keep, ncomp_pos, 2 * W)].set(nb_keys, mode="drop")
    ncv = jnp.zeros((2 * W,), jnp.int32).at[jnp.where(nb_keep, ncomp_pos, 2 * W)].set(nb_vers, mode="drop")
    lb_old = jnp.zeros((2 * W,), jnp.int32).at[jnp.where(nb_keep, ncomp_pos, 2 * W)].set(nb_lb, mode="drop")

    cum_keep = jnp.cumsum(old_keep.astype(jnp.int32))
    # new_before_old[h] = # kept new rows whose insertion point <= h.
    new_cnt = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(jnp.arange(2 * W) < nc, lb_old, H + 1)].add(1, mode="drop")
    )
    new_before_old = jnp.cumsum(new_cnt[:H])
    pos_old = cum_keep - 1 + new_before_old
    cum_cov = jnp.cumsum(covered.astype(jnp.int32))
    cov_before = jnp.where(lb_old > 0, cum_cov[jnp.maximum(lb_old - 1, 0)], 0)
    pos_new = jnp.arange(2 * W, dtype=jnp.int32) + (lb_old - cov_before)

    out_k = jnp.zeros((H, K), jnp.uint32)
    out_v = jnp.full((H,), NEG_VERSION, jnp.int32)
    out_k = out_k.at[jnp.where(old_keep, pos_old, H)].set(hkeys, mode="drop")
    out_v = out_v.at[jnp.where(old_keep, pos_old, H)].set(hvers, mode="drop")
    nc_mask = jnp.arange(2 * W) < nc
    out_k = out_k.at[jnp.where(nc_mask, pos_new, H)].set(nck, mode="drop")
    out_v = out_v.at[jnp.where(nc_mask, pos_new, H)].set(ncv, mode="drop")
    n1 = cum_keep[-1] + nc
    overflow = n1 > H

    # ---- Phase 5: GC + rebase (removeBefore:665; keep rule :686-698) ----
    gc = batch["gc"]
    do_gc = gc > 0
    prev_v = jnp.concatenate([jnp.array([2**30], jnp.int32), out_v[:-1]])
    keep = (jslot < n1) & (~do_gc | (jslot == 0) | (out_v >= gc) | (prev_v >= gc))
    fin_k, fin_v, n2 = _compact_rows(out_k, out_v, keep, H)
    delta = jnp.maximum(gc, 0)
    fin_v = jnp.where(jslot < n2, jnp.maximum(fin_v - delta, -1), NEG_VERSION)

    new_state = {"hkeys": fin_k, "hvers": fin_v, "n": n2}
    return new_state, overflow


def status_of(t_too_old: jnp.ndarray, committed: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(
        t_too_old,
        jnp.int32(int(TransactionCommitResult.TOO_OLD)),
        jnp.where(committed, jnp.int32(int(TransactionCommitResult.COMMITTED)),
                  jnp.int32(int(TransactionCommitResult.CONFLICT))),
    )


def resolve_step(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """One single-shard resolver batch: (state, batch) -> (state', outputs).
    Pure; jit me. See local_phases for the batch layout."""
    hist_hits, o_cnt = local_phases(cfg, state, batch)
    committed = commit_fixpoint(cfg, batch["t_ok"], hist_hits, o_cnt)
    new_state, overflow = apply_writes_and_gc(cfg, state, batch, committed)
    out = {
        "status": status_of(batch["t_too_old"], committed),
        "overflow": overflow,
        "n": new_state["n"],
    }
    return new_state, out


def initial_state(cfg: KernelConfig, version_rel: int = 0, first_key: bytes = b"") -> Dict[str, jnp.ndarray]:
    """Fresh boundary table whose single interval [first_key, +inf) carries
    version_rel. Key-range shards pass their span begin as first_key."""
    hkeys = np.zeros((cfg.capacity, cfg.lanes), np.uint32)
    hkeys[0] = keypack.pack_key(first_key, cfg.key_words)
    hvers = np.full((cfg.capacity,), int(NEG_VERSION), np.int32)
    hvers[0] = version_rel
    return {
        "hkeys": jnp.asarray(hkeys),
        "hvers": jnp.asarray(hvers),
        "n": jnp.asarray(1, jnp.int32),
    }


def build_batch_arrays(
    cfg: KernelConfig,
    r_keys_b: List[bytes], r_keys_e: List[bytes], r_snap: List[int], r_txn: List[int],
    w_keys_b: List[bytes], w_keys_e: List[bytes], w_txn: List[int],
    t_ok: np.ndarray, t_too_old: np.ndarray,
    now_rel: int, gc_rel: int,
) -> Dict[str, np.ndarray]:
    """Pad host-side range lists to the kernel's fixed shapes (numpy)."""
    R, W, K = cfg.max_reads, cfg.max_writes, cfg.lanes
    nr, nw = len(r_txn), len(w_txn)

    def padk(keys: List[bytes], cap: int) -> np.ndarray:
        arr = np.zeros((cap, K), np.uint32)
        if keys:
            arr[: len(keys)] = keypack.pack_keys(keys, cfg.key_words)
        return arr

    return {
        "rb": padk(r_keys_b, R),
        "re": padk(r_keys_e, R),
        "r_snap": np.pad(np.asarray(r_snap, np.int32), (0, R - nr)),
        "r_txn": np.pad(np.asarray(r_txn, np.int32), (0, R - nr)),
        "r_valid": np.arange(R) < nr,
        "wb": padk(w_keys_b, W),
        "we": padk(w_keys_e, W),
        "w_txn": np.pad(np.asarray(w_txn, np.int32), (0, W - nw)),
        "w_valid": np.arange(W) < nw,
        "t_ok": np.asarray(t_ok, bool),
        "t_too_old": np.asarray(t_too_old, bool),
        "now": np.asarray(now_rel, np.int32),
        "gc": np.asarray(gc_rel, np.int32),
    }


def __getattr__(name):  # PEP 562: JaxConflictEngine lives in host_engine
    # (which imports this module); re-export lazily to avoid an import cycle.
    if name == "JaxConflictEngine":
        from .host_engine import JaxConflictEngine

        return JaxConflictEngine
    raise AttributeError(name)
