"""TPU-native batched conflict detection — the north-star kernel.

Re-design of the reference resolver's versioned skip list
(fdbserver/SkipList.cpp) as a data-parallel, fixed-shape XLA program:

  reference                      this kernel
  ---------                      -----------
  skip-list nodes                sorted boundary table hkeys[H, K] in HBM
  per-level maxVersion pyramid   sparse table (block-max) over hvers[H]
  16-way pipelined CheckMax      vectorized binary search + range-max gather
  radix sortPoints (:227)        one lax.sort of all endpoints w/ tie codes
  MiniConflictSet sweep (:1133)  overlap matrix + DAG fixpoint (while_loop)
  skip-list insert/remove        sort-free merge: searchsorted + scatter
  removeBefore GC (:665)         vectorized keep rule + compaction

Exactness: verdicts are a pure function of the logical version-interval map
(see ops/oracle.py); every op here (max, OR, integer compares) is
order-insensitive, so results are bit-identical to the oracle and hence to
the reference CPU resolver, for keys within the configured exact width.

Versions on device are int32 offsets from a host-tracked base (the 5-second
MVCC window MAX_WRITE_TRANSACTION_LIFE_VERSIONS = 5e6 << 2^31); versions at
or below the base are clamped to -1, which is semantics-preserving because
any read that passes the too-old gate has snapshot >= base.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core.types import TransactionCommitResult
from . import keypack

NEG_VERSION = jnp.int32(-(2**30))


@dataclass(frozen=True)
class KernelConfig:
    key_words: int = 4          # exact-compare width = 4*key_words bytes
    capacity: int = 1 << 16     # H: max boundaries in the interval table
    max_reads: int = 1 << 12    # R: read conflict ranges per device batch
    max_writes: int = 1 << 12   # W: write conflict ranges per device batch
    max_txns: int = 1 << 12     # T: transactions per device batch

    @property
    def lanes(self) -> int:     # K: words per packed key incl. length
        return self.key_words + 1

    @property
    def write_words(self) -> int:  # W rounded up to whole uint32 bit-words
        return (self.max_writes + 31) // 32

    @property
    def search_steps(self) -> int:
        return int(math.ceil(math.log2(self.capacity))) + 1

    @property
    def levels(self) -> int:    # sparse-table levels
        return int(math.ceil(math.log2(self.capacity))) + 1


def _key_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a < b over trailing lane axis (uint32 words + length)."""
    neq = a != b
    idx = jnp.argmax(neq, axis=-1)
    any_neq = jnp.any(neq, axis=-1)
    av = jnp.take_along_axis(a, idx[..., None], axis=-1)[..., 0]
    bv = jnp.take_along_axis(b, idx[..., None], axis=-1)[..., 0]
    return any_neq & (av < bv)


def _key_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def _bump(q: jnp.ndarray) -> jnp.ndarray:
    """Successor of a packed key in packed order: (words, len) -> (words, len+1).

    No packable key sorts strictly between the two (lengths are integers), so
    lower_bound(_bump(q)) == upper_bound(q). This keeps every search call
    single-direction (a mixed-bound search would evaluate both lexicographic
    compare directions per step — measured slower than three separate calls).
    """
    return q.at[..., -1].add(1)


def _search(cfg: KernelConfig, table: jnp.ndarray, count: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Vectorized lower_bound over table[0:count] (sorted, [N,K]): first i
    with table[i] >= q. For upper_bound, pass _bump(q). Call sites batch all
    their queries into ONE call so the serialized 16-step gather loop runs
    once per phase instead of once per query set."""
    nq = q.shape[0]
    lo = jnp.zeros((nq,), jnp.int32)
    hi = jnp.full((nq,), count, jnp.int32)
    for _ in range(cfg.search_steps):
        m = lo < hi
        mid = (lo + hi) >> 1
        row = table[mid]
        go_right = _key_less(row, q)
        lo = jnp.where(m & go_right, mid + 1, lo)
        hi = jnp.where(m & ~go_right, mid, hi)
    return lo


def _build_sparse_max(cfg: KernelConfig, vers: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Sparse table: out[k, i] = max(vers[i : i+2^k]) with invalid slots -> NEG.

    This is the skip-list maxVersion pyramid (SkipList.cpp:350-357) flattened
    into a dense, gather-friendly layout."""
    h = cfg.capacity
    base = jnp.where(jnp.arange(h) < n, vers, NEG_VERSION)
    levels = [base]
    for k in range(1, cfg.levels):
        half = 1 << (k - 1)
        prev = levels[-1]
        shifted = jnp.concatenate([prev[half:], jnp.full((half,), NEG_VERSION, prev.dtype)])
        levels.append(jnp.maximum(prev, shifted))
    return jnp.stack(levels)  # [levels, H]


def _range_max(cfg: KernelConfig, sparse: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """max(vers[lo:hi]) for hi > lo, via two overlapping power-of-two blocks."""
    s = (hi - lo).astype(jnp.uint32)
    k = (31 - lax.clz(s)).astype(jnp.int32)
    flat = sparse.reshape(-1)
    h = cfg.capacity
    m1 = flat[k * h + lo]
    m2 = flat[k * h + hi - (1 << k).astype(jnp.int32)]
    return jnp.maximum(m1, m2)


def _i2u(x: jnp.ndarray) -> jnp.ndarray:
    return lax.bitcast_convert_type(x, jnp.uint32)


def _u2i(x: jnp.ndarray) -> jnp.ndarray:
    return lax.bitcast_convert_type(x, jnp.int32)


def local_phases(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phases 1-2, shard-local: reads vs. history + intra-batch overlap edges.

    Returns (hist_hits int32 [T], ovp uint32 [R, cfg.write_words]) where ovp
    bit (r, w) = 1 iff read row r overlaps write row w AND w's txn is
    strictly earlier in the batch than r's (the reference's
    earlier-in-batch-wins edge direction, checkIntraBatchConflicts:1139-1152).
    Hits/overlaps are additive across key-range shards (a hit/overlap occurs
    in >= 1 shard iff it occurs globally); the multi-shard engine psums
    hist_hits once and the fixpoint's per-iteration blocked-txn counts over
    the mesh axis — the "conflict bitmaps allreduced over ICI" of the north
    star. ovp itself never crosses the ICI: it stays shard-local and is
    consumed only through bitwise-AND sweeps in commit_fixpoint.

    batch fields (fixed shapes; see build_batch_arrays):
      rb, re   uint32 [R, K]   read range begin/end (packed keys)
      r_snap   int32  [R]      read snapshot, relative to base (>= 0)
      r_txn    int32  [R]      owning transaction index
      r_valid  bool   [R]
      wb, we   uint32 [W, K]   write ranges (non-empty only)
      w_txn    int32  [W]
      w_valid  bool   [W]
      t_ok     bool   [T]      valid txn, not too-old
      t_too_old bool  [T]
      now      int32  []       commit version - base
      gc       int32  []       new_oldest - base (<=0: no GC/rebase)
    """
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    R = cfg.max_reads
    W = cfg.max_writes
    T = cfg.max_txns
    K = cfg.lanes

    rb, re = batch["rb"], batch["re"]
    wb, we = batch["wb"], batch["we"]
    r_txn, w_txn = batch["r_txn"], batch["w_txn"]
    r_valid, w_valid = batch["r_valid"], batch["w_valid"]

    # ---- Phase 1: reads vs. history (checkReadConflictRanges:1210) ----
    # One fused 2R-query lower-bound search: non-empty reads need
    # upper(rb) == lower(_bump(rb)); empty reads need lower(rb) — selected
    # per row. The serialized 16-step gather loop runs once, not three times.
    sparse = _build_sparse_max(cfg, hvers, n)
    empty_r = ~_key_less(rb, re)
    q_lo = jnp.where(empty_r[:, None], rb, _bump(rb))
    s2 = _search(cfg, hkeys, n, jnp.concatenate([q_lo, re], axis=0))
    lo_ne = s2[:R] - 1                                       # interval containing rb
    hi_ne = s2[R:]                                           # first boundary >= re
    lo_e = jnp.maximum(s2[:R] - 1, 0)
    lo = jnp.where(empty_r, lo_e, lo_ne)
    hi = jnp.where(empty_r, lo_e + 1, hi_ne)
    rmax = _range_max(cfg, sparse, lo, hi)
    r_hit = r_valid & (rmax > batch["r_snap"])
    hist_hits = jnp.zeros((T,), jnp.int32).at[r_txn].max(r_hit.astype(jnp.int32), mode="drop")

    # ---- Phase 2: intra-batch (checkIntraBatchConflicts:1133) ----
    # Endpoint order with the reference's tie codes (getCharacter,
    # SkipList.cpp:147-177): at equal keys  end-read < end-write < begin-write
    # < begin-read, which makes integer position compare == exact half-open
    # overlap. Invalid rows sort last via a leading flag.
    P = 2 * R + 2 * W
    pkeys = jnp.concatenate([rb, re, wb, we], axis=0)                    # [P, K]
    pcode = jnp.concatenate([
        jnp.full((R,), 3, jnp.uint32),   # begin-read
        jnp.full((R,), 0, jnp.uint32),   # end-read
        jnp.full((W,), 2, jnp.uint32),   # begin-write
        jnp.full((W,), 1, jnp.uint32),   # end-write
    ])
    pvalid = jnp.concatenate([r_valid, r_valid, w_valid, w_valid])
    pinv = (~pvalid).astype(jnp.uint32)
    pidx = jnp.arange(P, dtype=jnp.uint32)
    ops = (pinv,) + tuple(pkeys[:, c] for c in range(K)) + (pcode, pidx)
    sorted_ops = lax.sort(ops, num_keys=K + 2, is_stable=True)
    sorted_idx = sorted_ops[-1]
    pos = jnp.zeros((P,), jnp.int32).at[sorted_idx].set(jnp.arange(P, dtype=jnp.int32))
    pos_rb, pos_re = pos[:R], pos[R : 2 * R]
    pos_wb, pos_we = pos[2 * R : 2 * R + W], pos[2 * R + W :]

    ov = (
        (pos_rb[:, None] < pos_re[:, None])      # non-empty read
        & (pos_rb[:, None] < pos_we[None, :])    # rb < we
        & (pos_wb[None, :] < pos_re[:, None])    # wb < re
        & (w_txn[None, :] < r_txn[:, None])      # strictly earlier writer txn
        & r_valid[:, None]
        & w_valid[None, :]
    )
    # Bit-pack edges to [R, W/32] uint32 (MiniConflictSet's word trick,
    # SkipList.cpp:1028-1130, transplanted to the VPU). The old path
    # projected ov to a [T, T] txn graph via two one-hot matmuls
    # (2*R*W*T + 2*R*T*T FLOPs ~ 1e11 per batch — the round-1 perf whale);
    # the fixpoint now touches only these 2MB of packed words per iteration.
    ovp = _pack_bits(ov, cfg.write_words)
    return hist_hits, ovp


def _pack_bits(bits: jnp.ndarray, n_words: int) -> jnp.ndarray:
    """Pack a [..., W] bool array into [..., n_words] uint32 (W <= 32*n_words)."""
    w = bits.shape[-1]
    pad = 32 * n_words - w
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bits.dtype)], axis=-1
        )
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(
        bits.reshape(bits.shape[:-1] + (n_words, 32)).astype(jnp.uint32) * weights,
        axis=-1, dtype=jnp.uint32,
    )


def commit_fixpoint(
    cfg: KernelConfig,
    t_ok: jnp.ndarray,
    hist_hits: jnp.ndarray,
    ovp: jnp.ndarray,
    r_txn: jnp.ndarray,
    r_valid: jnp.ndarray,
    w_txn: jnp.ndarray,
    allreduce=lambda x: x,
) -> jnp.ndarray:
    """Earlier-in-batch-wins verdicts via bit-packed fixpoint.

    Each iteration over the packed edge words ovp [R, W/32]:
      1. pack the committed mask to [W/32] words,
      2. hit_r = any(ovp & mask) per read row — 2MB of uint32 traffic,
      3. reduce reads -> txns with a cumsum over rows + two [T] gathers
         (read rows are grouped by ascending owning txn — the layout
         build_batch_arrays/_resolve_chunk produce),
      4. `allreduce` the per-txn blocked counts ([T] int32; txn index space
         is the only space shared across shards — read rows are shard-local
         — and counts are additive across disjoint key shards; the sharded
         engine psums this 8KB vector over ICI).
    All inputs to the while condition are allreduced values, so every shard
    runs the identical number of iterations in lockstep. All arithmetic is
    integer, so >0 tests bit-match the oracle's set semantics.
    """
    T = cfg.max_txns

    # Row range [starts[t], ends[t]) of txn t's reads (valid rows are a
    # prefix, grouped by ascending txn).
    cnt_t = jnp.zeros((T,), jnp.int32).at[
        jnp.where(r_valid, r_txn, T)
    ].add(1, mode="drop")
    ends = jnp.cumsum(cnt_t)
    starts = ends - cnt_t

    base_commit = t_ok & ~(hist_hits > 0)

    def blocked_of(c):
        maskp = _pack_bits(c[w_txn], cfg.write_words)                    # [W/32]
        hit_r = jnp.any(ovp & maskp[None, :], axis=-1)                   # [R]
        csum = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(hit_r.astype(jnp.int32))])    # [R+1]
        blocked_t = csum[ends] - csum[starts]                            # [T]
        return allreduce(blocked_t) > 0                                  # psum over shards

    # Earlier-in-batch-wins is a DAG over u < t edges; iterate to its unique
    # fixpoint (equivalent to the reference's in-order sweep).
    def fix_cond(carry):
        c, prev, it = carry
        return jnp.any(c != prev) & (it < T)

    def fix_body(carry):
        c, _, it = carry
        return base_commit & ~blocked_of(c), c, it + 1

    c0 = base_commit
    c1 = base_commit & ~blocked_of(c0)
    committed, _, _ = lax.while_loop(fix_cond, fix_body, (c1, c0, jnp.int32(0)))
    return committed


def apply_writes_and_gc(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray], committed: jnp.ndarray) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Phases 3-5, shard-local: committed-write union, boundary-table merge,
    GC/rebase. Returns (new_state, overflow)."""
    hkeys, hvers, n = state["hkeys"], state["hvers"], state["n"]
    W = cfg.max_writes
    H = cfg.capacity
    K = cfg.lanes
    wb, we = batch["wb"], batch["we"]
    w_txn = batch["w_txn"]
    w_valid = batch["w_valid"]
    now = batch["now"]

    # ---- Phase 3: committed-write union (combineWriteConflictRanges:1320) ----
    cw = w_valid & committed[w_txn]
    ekeys = jnp.concatenate([wb, we], axis=0)                             # [2W, K]
    ecode = jnp.concatenate([jnp.zeros((W,), jnp.uint32), jnp.ones((W,), jnp.uint32)])
    evalid = jnp.concatenate([cw, cw])
    einv = (~evalid).astype(jnp.uint32)
    # All payload is derivable from the sort keys themselves (delta = +1 for
    # code 0 / -1 for code 1; the key words are sort operands), so the sort
    # carries no extra payload lanes.
    eops = (einv,) + tuple(ekeys[:, c] for c in range(K)) + (ecode,)
    es = lax.sort(eops, num_keys=K + 2, is_stable=True)
    s_valid = es[0] == 0
    s_delta = jnp.where(es[K + 1] == 0, 1, -1)
    s_keys = jnp.stack(es[1 : K + 1], axis=1)                             # [2W, K]
    d = jnp.where(s_valid, s_delta, 0)
    cum = jnp.cumsum(d)
    is_ub = s_valid & (s_delta > 0) & ((cum - d) == 0)
    is_ue = s_valid & (s_delta < 0) & (cum == 0)
    ubi = jnp.cumsum(is_ub.astype(jnp.int32)) - 1
    uei = jnp.cumsum(is_ue.astype(jnp.int32)) - 1
    u_count = jnp.sum(is_ub.astype(jnp.int32))
    ub_keys = jnp.zeros((W, K), jnp.uint32).at[jnp.where(is_ub, ubi, W)].set(s_keys, mode="drop")
    ue_keys = jnp.zeros((W, K), jnp.uint32).at[jnp.where(is_ue, uei, W)].set(s_keys, mode="drop")
    # One fused 3W-query lower-bound search: upper(ue) == lower(_bump(ue))
    # for the preserved-tail version, lower(ub)/lower(ue) for the
    # covered-window sweep below.
    q3 = jnp.concatenate([_bump(ue_keys), ub_keys, ue_keys], axis=0)
    s3 = _search(cfg, hkeys, n, q3)
    # Version at each union end = pre-batch map value there (preserved tail).
    ue_ver = hvers[s3[:W] - 1]

    # ---- Phase 4: merge union into the boundary table at version `now` ----
    # All searches below are W/2W-query (never H-query): positions of old
    # rows relative to the union are recovered with scatter+cumsum sweeps
    # over the table instead of per-old-row binary searches (H >> W made
    # those the dominant cost on TPU).
    jslot = jnp.arange(H, dtype=jnp.int32)
    valid_u = jnp.arange(W, dtype=jnp.int32) < u_count
    # covered[h] iff some union range [ub_i, ue_i) contains hkeys[h]:
    # delta sweep over [start_i, stop_i) index windows.
    u_start = s3[W : 2 * W]                                              # [W]
    u_stop = s3[2 * W :]                                                 # [W]
    cov_delta = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(valid_u, u_start, H + 1)].add(1, mode="drop")
        .at[jnp.where(valid_u, u_stop, H + 1)].add(-1, mode="drop")
    )
    covered = jnp.cumsum(cov_delta[:H]) > 0
    old_keep = (jslot < n) & ~covered

    # New rows: interleave begins (version=now) and ends (version=ue_ver);
    # the interleaving [ub0, ue0, ub1, ue1, ...] is already key-sorted.
    nb_keys = jnp.stack([ub_keys, ue_keys], axis=1).reshape(2 * W, K)
    nb_vers = jnp.stack([jnp.full((W,), now, jnp.int32), ue_ver], axis=1).reshape(2 * W)
    nb_lb = jnp.stack([u_start, u_stop], axis=1).reshape(2 * W)          # lower bound in hkeys
    j_of = jnp.repeat(jnp.arange(W, dtype=jnp.int32), 2)
    is_end_row = jnp.tile(jnp.array([False, True]), W)
    nb_valid = j_of < u_count
    # Drop an end row when an equal, uncovered old boundary already exists
    # (same version by construction, so keeping the old row is exact).
    lbc = jnp.minimum(nb_lb, H - 1)
    eq_exists = (nb_lb < n) & _key_eq(hkeys[lbc], nb_keys) & ~covered[lbc]
    nb_keep = nb_valid & ~(is_end_row & eq_exists)

    # Single combined compaction scatter: (keys | version | lower-bound) per
    # row, instead of three scatters walking the same target indices.
    ncomp_pos = jnp.cumsum(nb_keep.astype(jnp.int32)) - 1
    nc = jnp.sum(nb_keep.astype(jnp.int32))
    nbc = jnp.concatenate(
        [nb_keys, _i2u(nb_vers)[:, None], _i2u(nb_lb)[:, None]], axis=1
    )                                                                     # [2W, K+2]
    ncc = jnp.zeros((2 * W, K + 2), jnp.uint32).at[
        jnp.where(nb_keep, ncomp_pos, 2 * W)
    ].set(nbc, mode="drop")
    nck = ncc[:, :K]
    ncv = _u2i(ncc[:, K])
    lb_old = _u2i(ncc[:, K + 1])

    cum_keep = jnp.cumsum(old_keep.astype(jnp.int32))
    # new_before_old[h] = # kept new rows whose insertion point <= h.
    new_cnt = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(jnp.arange(2 * W) < nc, lb_old, H + 1)].add(1, mode="drop")
    )
    new_before_old = jnp.cumsum(new_cnt[:H])
    pos_old = cum_keep - 1 + new_before_old
    cum_cov = jnp.cumsum(covered.astype(jnp.int32))
    cov_before = jnp.where(lb_old > 0, cum_cov[jnp.maximum(lb_old - 1, 0)], 0)
    pos_new = jnp.arange(2 * W, dtype=jnp.int32) + (lb_old - cov_before)

    # Merge via two combined (keys | version) row scatters — old rows and new
    # rows — instead of four key/version scatter pairs.
    outc = jnp.concatenate(
        [jnp.zeros((H, K), jnp.uint32), jnp.full((H, 1), _i2u(NEG_VERSION))], axis=1
    )
    outc = outc.at[jnp.where(old_keep, pos_old, H)].set(
        jnp.concatenate([hkeys, _i2u(hvers)[:, None]], axis=1), mode="drop"
    )
    nc_mask = jnp.arange(2 * W) < nc
    outc = outc.at[jnp.where(nc_mask, pos_new, H)].set(
        jnp.concatenate([nck, _i2u(ncv)[:, None]], axis=1), mode="drop"
    )
    out_v = _u2i(outc[:, K])
    n1 = cum_keep[-1] + nc
    overflow = n1 > H

    # ---- Phase 5: GC + rebase (removeBefore:665; keep rule :686-698) ----
    gc = batch["gc"]
    do_gc = gc > 0
    prev_v = jnp.concatenate([jnp.array([2**30], jnp.int32), out_v[:-1]])
    keep = (jslot < n1) & (~do_gc | (jslot == 0) | (out_v >= gc) | (prev_v >= gc))
    cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    finc = jnp.concatenate(
        [jnp.zeros((H, K), jnp.uint32), jnp.full((H, 1), _i2u(NEG_VERSION))], axis=1
    ).at[jnp.where(keep, cpos, H)].set(outc, mode="drop")
    n2 = jnp.sum(keep.astype(jnp.int32))
    fin_v = _u2i(finc[:, K])
    delta = jnp.maximum(gc, 0)
    fin_v = jnp.where(jslot < n2, jnp.maximum(fin_v - delta, -1), NEG_VERSION)

    new_state = {"hkeys": finc[:, :K], "hvers": fin_v, "n": n2}
    return new_state, overflow


def status_of(t_too_old: jnp.ndarray, committed: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(
        t_too_old,
        jnp.int32(int(TransactionCommitResult.TOO_OLD)),
        jnp.where(committed, jnp.int32(int(TransactionCommitResult.COMMITTED)),
                  jnp.int32(int(TransactionCommitResult.CONFLICT))),
    )


def resolve_step(cfg: KernelConfig, state: Dict[str, jnp.ndarray], batch: Dict[str, jnp.ndarray]) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """One single-shard resolver batch: (state, batch) -> (state', outputs).
    Pure; jit me. See local_phases for the batch layout."""
    hist_hits, ov = local_phases(cfg, state, batch)
    committed = commit_fixpoint(
        cfg, batch["t_ok"], hist_hits, ov,
        batch["r_txn"], batch["r_valid"], batch["w_txn"],
    )
    new_state, overflow = apply_writes_and_gc(cfg, state, batch, committed)
    out = {
        "status": status_of(batch["t_too_old"], committed),
        "overflow": overflow,
        "n": new_state["n"],
    }
    return new_state, out


def initial_state(cfg: KernelConfig, version_rel: int = 0, first_key: bytes = b"") -> Dict[str, jnp.ndarray]:
    """Fresh boundary table whose single interval [first_key, +inf) carries
    version_rel. Key-range shards pass their span begin as first_key."""
    hkeys = np.zeros((cfg.capacity, cfg.lanes), np.uint32)
    hkeys[0] = keypack.pack_key(first_key, cfg.key_words)
    hvers = np.full((cfg.capacity,), int(NEG_VERSION), np.int32)
    hvers[0] = version_rel
    return {
        "hkeys": jnp.asarray(hkeys),
        "hvers": jnp.asarray(hvers),
        "n": jnp.asarray(1, jnp.int32),
    }


def build_batch_arrays(
    cfg: KernelConfig,
    r_keys_b: List[bytes], r_keys_e: List[bytes], r_snap: List[int], r_txn: List[int],
    w_keys_b: List[bytes], w_keys_e: List[bytes], w_txn: List[int],
    t_ok: np.ndarray, t_too_old: np.ndarray,
    now_rel: int, gc_rel: int,
) -> Dict[str, np.ndarray]:
    """Pad host-side range lists to the kernel's fixed shapes (numpy).

    Layout invariant relied on by commit_fixpoint's segment reduce: valid
    read/write rows are a contiguous prefix, grouped by ascending owning
    transaction index (r_txn/w_txn non-decreasing over the valid prefix)."""
    assert all(a <= b for a, b in zip(r_txn, r_txn[1:])), "read rows must be grouped by ascending txn"
    R, W, K = cfg.max_reads, cfg.max_writes, cfg.lanes
    nr, nw = len(r_txn), len(w_txn)

    def padk(keys: List[bytes], cap: int) -> np.ndarray:
        arr = np.zeros((cap, K), np.uint32)
        if keys:
            arr[: len(keys)] = keypack.pack_keys(keys, cfg.key_words)
        return arr

    return {
        "rb": padk(r_keys_b, R),
        "re": padk(r_keys_e, R),
        "r_snap": np.pad(np.asarray(r_snap, np.int32), (0, R - nr)),
        "r_txn": np.pad(np.asarray(r_txn, np.int32), (0, R - nr)),
        "r_valid": np.arange(R) < nr,
        "wb": padk(w_keys_b, W),
        "we": padk(w_keys_e, W),
        "w_txn": np.pad(np.asarray(w_txn, np.int32), (0, W - nw)),
        "w_valid": np.arange(W) < nw,
        "t_ok": np.asarray(t_ok, bool),
        "t_too_old": np.asarray(t_too_old, bool),
        "now": np.asarray(now_rel, np.int32),
        "gc": np.asarray(gc_rel, np.int32),
    }


def __getattr__(name):  # PEP 562: JaxConflictEngine lives in host_engine
    # (which imports this module); re-export lazily to avoid an import cycle.
    if name == "JaxConflictEngine":
        from .host_engine import JaxConflictEngine

        return JaxConflictEngine
    raise AttributeError(name)
