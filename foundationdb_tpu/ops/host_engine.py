"""Shared host-side machinery for device-backed ConflictSet engines.

Everything that is NOT the device program lives here exactly once: the int32
version window (device versions are offsets from a host-tracked base), the
key-range shard map + routing/clipping (the analog of the proxy's
`keyResolvers` range map, MasterProxyServer.actor.cpp:263-316), the greedy
transaction chunking against per-shard device caps, and fixed-shape batch
packing. Engines (single-chip jit, multi-chip shard_map) subclass and supply
only `_run_step`.

Batch splitting on transaction boundaries is exact: sub-batch writes land at
version `now` and every later read in the same batch has snapshot < now, so
history-vs-intra-batch classification cannot change any verdict.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core import error
from ..core.keyshard import KeyShardMap
from ..core.types import CommitTransaction, Key, TransactionCommitResult, Version
from . import conflict_kernel as ck
from . import keypack
from .conflict_kernel import KernelConfig, build_batch_arrays
from .oracle import VersionIntervalMap


from ..core.types import is_point_range as _is_point


def donate_state_kwargs() -> dict:
    """jit kwargs donating the engine-state argument — only off-CPU.

    On the CPU backend the donation is unusable anyway (XLA warns the
    buffers cannot be aliased), and executing a DESERIALIZED persistently
    cached program with donated inputs corrupts the glibc heap (double
    free, jaxlib 0.4.36) — a fresh engine whose jit hits the compilation
    cache aborts the process a few batches in. The real accelerator path
    keeps the in-place state aliasing."""
    if jax.default_backend() == "cpu":
        return {}
    return {"donate_argnums": (0,)}


@dataclass
class _RoutedTxn:
    """One transaction's conflict ranges, clipped per shard (computed once).
    Point rows ([k, k+'\\x00')) are classified here, carrying only the key.

    Rows involving keys beyond the device's exact-compare window go to the
    host long-key tier (tier_*): long points exclusively; range rows
    additionally (membership of long keys in any range is tier-owned, while
    the device answers the same range for in-window keys via truncated
    endpoints — an exact disjoint decomposition of the keyspace)."""

    preads: List[Tuple[int, Key]]       # (shard, key)
    rreads: List[Tuple[int, Key, Key]]  # (shard, begin, end) — may be empty ranges
    pwrites: List[Tuple[int, Key]]
    rwrites: List[Tuple[int, Key, Key]] # non-empty only
    n_preads: List[int]                 # per-shard counts
    n_rreads: List[int]
    n_pwrites: List[int]
    n_rwrites: List[int]
    snapshot: Version
    #: host-tier rows (byte keys, unclipped)
    tier_preads: List[Key]              # long point reads
    tier_ereads: List[Key]              # long empty reads [k, k)
    tier_rreads: List[Tuple[Key, Key]]  # non-empty range reads (all)
    tier_pwrites: List[Key]             # long point writes
    tier_rwrites: List[Tuple[Key, Key]] # non-empty range writes (all)
    has_long: bool = False              # any long-key row in this txn

    def has_reads(self) -> bool:
        return bool(self.preads or self.rreads or self.tier_preads
                    or self.tier_ereads or self.tier_rreads)


def wire_pass1(window: int, blocks: List[bytes]):
    """Native pass 1 over concatenated conflict-wire blocks: per-txn POINT
    row counts. Returns (blob, offs, rp_cnt, wp_cnt) or None when the batch
    has any range/empty/long-key row (general router handles it) or no
    native library is available."""
    lib = keypack._fastpack()
    if lib is None or not blocks:
        return None
    import ctypes

    n = len(blocks)
    blob = b"".join(blocks)
    offs = np.zeros((n + 1,), np.int64)
    np.cumsum(np.fromiter((len(b) for b in blocks), np.int64, count=n), out=offs[1:])
    rp_cnt = np.zeros((n,), np.int32)
    wp_cnt = np.zeros((n,), np.int32)
    rc = lib.conflict_counts(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, window,
        rp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return blob, offs, rp_cnt, wp_cnt


def wire_pass1_sharded(window: int, blocks: List[bytes],
                       splits_blob: bytes, splits_offs: np.ndarray, S: int):
    """Native pass 1 with per-shard routing: per-(txn, shard) POINT row
    counts. Returns (blob, offs, rp_cnt[n,S], wp_cnt[n,S]) or None when the
    batch has any range/empty/long-key row or no native library."""
    lib = keypack._fastpack()
    if lib is None or not blocks or not hasattr(lib, "conflict_counts_sharded"):
        return None
    import ctypes

    n = len(blocks)
    blob = b"".join(blocks)
    offs = np.zeros((n + 1,), np.int64)
    np.cumsum(np.fromiter((len(b) for b in blocks), np.int64, count=n), out=offs[1:])
    rp_cnt = np.zeros((n, S), np.int32)
    wp_cnt = np.zeros((n, S), np.int32)
    rc = lib.conflict_counts_sharded(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, window,
        splits_blob,
        splits_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        S - 1,
        rp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return blob, offs, rp_cnt, wp_cnt


def wire_chunk_arrays_sharded(
    cfg: KernelConfig,
    blob: bytes,
    offs: np.ndarray,
    t0: int,
    t1: int,
    skip: np.ndarray,
    snap_rel: np.ndarray,
    eff_r: np.ndarray,         # int32 [ntx, S] read counts, skipped txns zeroed
    now_rel: int,
    gc_rel: int,
    splits_blob: bytes,
    splits_offs: np.ndarray,
    S: int,
) -> List[Dict[str, np.ndarray]]:
    """Native pass 2, sharded: per-shard kernel batch dicts for txns
    [t0, t1) straight from wire bytes. One C call routes + packs every
    point row into its shard's padded region; the int lanes are vectorized
    numpy. Point keys route whole (a point range never straddles a shard
    split), so no clipping happens here."""
    import ctypes

    lib = keypack._fastpack()
    K = cfg.lanes
    n = t1 - t0
    rpb = np.zeros((S, cfg.rp, K), np.uint32)
    rp_txn = np.zeros((S, cfg.rp), np.int32)
    wpb = np.zeros((S, cfg.wp, K), np.uint32)
    wp_txn = np.zeros((S, cfg.wp), np.int32)
    out_n = np.zeros((2 * S,), np.int64)
    lib.build_point_rows_sharded(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        t0, t1, bytes(skip),
        cfg.key_words,
        splits_blob,
        splits_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        S - 1,
        cfg.rp, cfg.wp,
        rpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        rp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        wp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_n.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    t_ok = np.zeros((cfg.max_txns,), bool)
    t_too_old = np.zeros((cfg.max_txns,), bool)
    t_too_old[:n] = skip[t0:t1] != 0
    t_ok[:n] = ~t_too_old[:n]
    Rr, Wr = cfg.max_reads, cfg.max_writes
    now_a = np.asarray(now_rel, np.int32)
    gc_a = np.asarray(gc_rel, np.int32)
    per = []
    for s in range(S):
        n_rp, n_wp = int(out_n[2 * s]), int(out_n[2 * s + 1])
        rp_snap = np.zeros((cfg.rp,), np.int32)
        rp_snap[:n_rp] = np.repeat(snap_rel[t0:t1], eff_r[t0:t1, s])
        per.append({
            "rpb": rpb[s],
            "rp_snap": rp_snap,
            "rp_txn": rp_txn[s],
            "rp_valid": np.arange(cfg.rp) < n_rp,
            "rb": np.zeros((Rr, K), np.uint32),
            "re": np.zeros((Rr, K), np.uint32),
            "r_snap": np.zeros((Rr,), np.int32),
            "r_txn": np.zeros((Rr,), np.int32),
            "r_valid": np.zeros((Rr,), bool),
            "wpb": wpb[s],
            "wp_txn": wp_txn[s],
            "wp_valid": np.arange(cfg.wp) < n_wp,
            "wb": np.zeros((Wr, K), np.uint32),
            "we": np.zeros((Wr, K), np.uint32),
            "w_txn": np.zeros((Wr,), np.int32),
            "w_valid": np.zeros((Wr,), bool),
            "t_ok": t_ok,
            "t_too_old": t_too_old,
            "now": now_a,
            "gc": gc_a,
        })
    return per


def wire_chunk_arrays(
    cfg: KernelConfig,
    blob: bytes,
    offs: np.ndarray,
    t0: int,
    t1: int,
    skip: np.ndarray,          # uint8 [ntx], 1 = contribute no rows (too old)
    snap_rel: np.ndarray,      # int32 [ntx]
    eff_r: np.ndarray,         # int32 [ntx] read counts with skipped txns zeroed
    now_rel: int,
    gc_rel: int,
) -> Dict[str, np.ndarray]:
    """Native pass 2: kernel batch dict for txns [t0, t1) straight from wire
    bytes — the row groups are written into their padded arrays by C, the
    int lanes by vectorized numpy. The per-range Python of build_batch_arrays
    never runs on this path."""
    import ctypes

    lib = keypack._fastpack()
    K = cfg.lanes
    n = t1 - t0
    rpb = np.zeros((cfg.rp, K), np.uint32)
    rp_txn = np.zeros((cfg.rp,), np.int32)
    wpb = np.zeros((cfg.wp, K), np.uint32)
    wp_txn = np.zeros((cfg.wp,), np.int32)
    out_n = np.zeros((2,), np.int64)
    lib.build_point_rows(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        t0, t1, bytes(skip),
        cfg.key_words,
        rpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        rp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        wp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_n.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    n_rp, n_wp = int(out_n[0]), int(out_n[1])
    rp_snap = np.zeros((cfg.rp,), np.int32)
    rp_snap[:n_rp] = np.repeat(snap_rel[t0:t1], eff_r[t0:t1])
    t_ok = np.zeros((cfg.max_txns,), bool)
    t_too_old = np.zeros((cfg.max_txns,), bool)
    t_too_old[:n] = skip[t0:t1] != 0
    t_ok[:n] = ~t_too_old[:n]
    Rr, Wr = cfg.max_reads, cfg.max_writes
    return {
        "rpb": rpb,
        "rp_snap": rp_snap,
        "rp_txn": rp_txn,
        "rp_valid": np.arange(cfg.rp) < n_rp,
        "rb": np.zeros((Rr, K), np.uint32),
        "re": np.zeros((Rr, K), np.uint32),
        "r_snap": np.zeros((Rr,), np.int32),
        "r_txn": np.zeros((Rr,), np.int32),
        "r_valid": np.zeros((Rr,), bool),
        "wpb": wpb,
        "wp_txn": wp_txn,
        "wp_valid": np.arange(cfg.wp) < n_wp,
        "wb": np.zeros((Wr, K), np.uint32),
        "we": np.zeros((Wr, K), np.uint32),
        "w_txn": np.zeros((Wr,), np.int32),
        "w_valid": np.zeros((Wr,), bool),
        "t_ok": t_ok,
        "t_too_old": t_too_old,
        "now": np.asarray(now_rel, np.int32),
        "gc": np.asarray(gc_rel, np.int32),
    }


class RoutedConflictEngineBase:
    """Host side of a device-backed ConflictSet engine. Subclasses implement
    `_run_step(per_shard_batches) -> (status[T] np.ndarray, overflow bool)`
    and `_reset_device_state(version_rel)`."""

    name = "routed"

    def __init__(self, cfg: KernelConfig, shards: KeyShardMap):
        # Subclasses seed their device state (incl. any initial version, as a
        # base-relative offset) via _reset_device_state.
        self.cfg = cfg
        self.shards = shards
        self.n_shards = shards.n_shards
        self.base: Version = 0
        self.oldest_version: Version = 0
        self._window = keypack.max_key_bytes(cfg.key_words)
        #: exact host tier for out-of-window keys (absolute versions);
        #: short-key-only workloads never touch it
        self.tier_map = VersionIntervalMap(0)
        self._tier_has_writes = False
        # Shard split keys in the wire form the native router consumes.
        splits = self.shards.begins[1:]
        self._splits_blob = b"".join(splits)
        self._splits_offs = np.zeros((len(splits) + 1,), np.int64)
        np.cumsum(
            np.fromiter((len(s) for s in splits), np.int64, count=len(splits)),
            out=self._splits_offs[1:],
        )

    # -- subclass interface -------------------------------------------------
    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        """Fused detect+fix+apply (the fast path; no host tier involved)."""
        raise NotImplementedError

    def _run_step_async(self, per_shard: List[Dict[str, np.ndarray]]):
        """Fused step, dispatch-only: returns (status, overflow, keepalive)
        WITHOUT forcing device values to the host. The default runs the
        synchronous step (already-forced numpy arrays force trivially);
        device engines override to return unmaterialized device arrays so
        the host is free to pack the next batch while this one runs.

        `keepalive` is whatever host memory the dispatched program may
        still be reading — CPU-backend jax aliases well-aligned numpy
        inputs ZERO-COPY, so the batch arrays handed to the jit must stay
        referenced until the program's outputs are forced, or the async
        program races a freed buffer (flaky verdicts / segfaults)."""
        status, overflow = self._run_step(per_shard)
        return status, np.asarray(overflow), None

    def _run_detect(self, per_shard: List[Dict[str, np.ndarray]]):
        """Phases 1-2; returns an opaque device context for _run_fix/_run_apply."""
        raise NotImplementedError

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        """Earlier-in-batch-wins fixpoint under an updated t_ok; committed[T]."""
        raise NotImplementedError

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        """Apply globally-agreed writes; returns (status[T], overflow)."""
        raise NotImplementedError

    def _reset_device_state(self, version_rel: int) -> None:
        raise NotImplementedError

    # -- shared implementation ---------------------------------------------
    def clear(self, version: Version) -> None:
        """reference: clearConflictSet (SkipList.cpp:957-959)."""
        self._reset_device_state(self._rel(version))
        self.tier_map = VersionIntervalMap(version)
        self._tier_has_writes = False

    def _rel(self, v: Version) -> int:
        r = v - self.base
        if r >= 2**30:
            raise error.client_invalid_operation(
                f"version {v} too far beyond base {self.base} for int32 device window"
            )
        return max(r, -1)

    def _packed_empty(self, begin: Key, end: Key) -> bool:
        """True iff a truly non-empty [begin, end) becomes empty under
        endpoint truncation (both endpoints share the window prefix): the
        device would mis-evaluate it as an empty read, so it is tier-only."""
        w = self._window
        a = (begin[:w], min(len(begin), w + 1))
        b = (end[:w], min(len(end), w + 1))
        return a >= b

    def _route_txn(self, tr: CommitTransaction) -> _RoutedTxn:
        S = self.n_shards
        rt = _RoutedTxn([], [], [], [], [0] * S, [0] * S, [0] * S, [0] * S,
                        tr.read_snapshot, [], [], [], [], [])
        w_cap = self._window
        for r in tr.read_conflict_ranges:
            if r.begin >= r.end:
                k = r.begin
                if len(k) > w_cap and not (len(k) == w_cap + 1 and k[-1] == 0):
                    # Long empty read [k, k): the interval strictly below k
                    # borders long keys, whose values only tier-visible
                    # writes (range writes, long points) can set — the tier
                    # answer is exact. The ONE exception is k = s+'\x00'
                    # with a window-sized s: there the below-interval is
                    # {s}, owned by device-side point writes, and packing k
                    # (length window+1) is exact — so that shape routes to
                    # the device below.
                    rt.tier_ereads.append(k)
                    rt.has_long = True
                    continue
                s = self.shards.shard_of_point_below(k)
                rt.rreads.append((s, k, r.end))
                rt.n_rreads[s] += 1
            elif _is_point(r.begin, r.end) and len(r.begin) > w_cap:
                rt.tier_preads.append(r.begin)
                rt.has_long = True
            elif self._packed_empty(r.begin, r.end):
                rt.tier_rreads.append((r.begin, r.end))
                rt.has_long = True
            else:
                # Every non-point range may contain out-of-window keys: the
                # tier answers for those, the device for the in-window rest.
                if not _is_point(r.begin, r.end):
                    rt.tier_rreads.append((r.begin, r.end))
                    if len(r.begin) > w_cap or len(r.end) > w_cap:
                        rt.has_long = True
                # A point range never straddles a shard split (a split key
                # strictly inside [k, k+'\x00') would have to equal k).
                for s, cb, ce in self.shards.shards_of_range(r.begin, r.end):
                    if _is_point(cb, ce):
                        if len(cb) > w_cap:
                            # long split key carved a long point zone:
                            # tier-owned (the full range is in tier_rreads)
                            rt.has_long = True
                            continue
                        rt.preads.append((s, cb))
                        rt.n_preads[s] += 1
                    else:
                        if self._packed_empty(cb, ce):
                            rt.has_long = True
                            continue
                        rt.rreads.append((s, cb, ce))
                        rt.n_rreads[s] += 1
        for w in tr.write_conflict_ranges:
            if w.begin < w.end:
                if _is_point(w.begin, w.end) and len(w.begin) > w_cap:
                    rt.tier_pwrites.append(w.begin)
                    rt.has_long = True
                    continue
                if not _is_point(w.begin, w.end):
                    rt.tier_rwrites.append((w.begin, w.end))
                    if len(w.begin) > w_cap or len(w.end) > w_cap:
                        rt.has_long = True
                for s, cb, ce in self.shards.shards_of_range(w.begin, w.end):
                    if _is_point(cb, ce):
                        if len(cb) > w_cap:
                            rt.has_long = True
                            continue
                        rt.pwrites.append((s, cb))
                        rt.n_pwrites[s] += 1
                    else:
                        if self._packed_empty(cb, ce):
                            # collapses to nothing on device; tier-owned
                            rt.has_long = True
                            continue
                        rt.rwrites.append((s, cb, ce))
                        rt.n_rwrites[s] += 1
        cfg = self.cfg
        if (
            max(rt.n_preads) > cfg.rp
            or max(rt.n_rreads) > cfg.max_reads
            or max(rt.n_pwrites) > cfg.wp
            or max(rt.n_rwrites) > cfg.max_writes
        ):
            raise error.client_invalid_operation(
                "single transaction exceeds device conflict-range capacity"
            )
        return rt

    def resolve(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> List[TransactionCommitResult]:
        if transactions:
            res = self._resolve_columnar(transactions, now, new_oldest)
            if res is not None:
                return res
        cfg = self.cfg
        S = self.n_shards
        routed = [self._route_txn(tr) for tr in transactions]
        results: List[TransactionCommitResult] = []
        i = 0
        ntx = len(transactions)
        caps = (
            ("n_preads", cfg.rp),
            ("n_rreads", cfg.max_reads),
            ("n_pwrites", cfg.wp),
            ("n_rwrites", cfg.max_writes),
        )
        while True:
            # Greedy prefix respecting every shard's device caps.
            j = i
            used = {f: [0] * S for f, _ in caps}
            while j < ntx and (j - i) < cfg.max_txns:
                rt = routed[j]
                if any(
                    used[f][s] + getattr(rt, f)[s] > cap
                    for f, cap in caps
                    for s in range(S)
                ):
                    break
                for f, _ in caps:
                    for s in range(S):
                        used[f][s] += getattr(rt, f)[s]
                j += 1
            last = j >= ntx
            results.extend(self._resolve_chunk(routed[i:j], now, new_oldest if last else 0))
            if last:
                break
            i = j
        if new_oldest > self.oldest_version:
            self.oldest_version = new_oldest
            self.base += max(0, new_oldest - self.base)
        return results

    def _resolve_columnar(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> Optional[List[TransactionCommitResult]]:
        """Columnar fast path = pack + dispatch + force, in one call."""
        plan = self.columnar_pack(transactions, now, new_oldest)
        if plan is None:
            return None
        return self.columnar_dispatch(plan)()

    def columnar_pack(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> Optional[dict]:
        """Host half of the columnar fast path over conflict-wire blocks
        (any shard count): when every range is a short-key POINT row, batch
        assembly is two native passes + numpy (no per-range Python); for
        S > 1 the C pass routes each point row to its owning shard (a point
        range never straddles a split key, so no clipping is needed). Point
        reads of in-window keys never couple with the host long-key tier
        (keypack.py: short-key membership is device-exact), so the fused
        device step is always safe here.

        Returns an opaque plan for columnar_dispatch, or None when
        preconditions fail (the general router must handle the batch).
        Mutates NO engine state, but the packed arrays embed base-relative
        versions: the matching columnar_dispatch must run before any LATER
        batch packs (the ResolverPipeline keeps this ordering)."""
        cfg = self.cfg
        S = self.n_shards
        ntx = len(transactions)
        if ntx == 0:
            return None
        blocks = []
        for tr in transactions:
            blk, all_point, max_len = tr.conflict_wire_info()
            if not all_point or max_len > self._window:
                return None  # early out: later txns are not even encoded
            blocks.append(blk)
        if S == 1:
            p1 = wire_pass1(self._window, blocks)
        else:
            p1 = wire_pass1_sharded(
                self._window, blocks, self._splits_blob, self._splits_offs, S)
        if p1 is None:
            return None
        blob, offs, rp_cnt, wp_cnt = p1
        # caps bind per shard (S>1: rp_cnt/wp_cnt are [ntx, S] columns)
        if int(rp_cnt.max()) > cfg.rp or int(wp_cnt.max()) > cfg.wp:
            raise error.client_invalid_operation(
                "single transaction exceeds device conflict-range capacity"
            )
        has_reads = rp_cnt.sum(axis=1) > 0 if S > 1 else rp_cnt > 0
        snaps = np.fromiter(
            (tr.read_snapshot for tr in transactions), np.int64, count=ntx)
        rel = snaps - self.base
        if int(rel.max()) >= 2**30 or now - self.base >= 2**30:
            raise error.client_invalid_operation(
                f"version too far beyond base {self.base} for int32 device window"
            )
        snap_rel = np.maximum(rel, -1).astype(np.int32)
        too_old = (snaps < self.oldest_version) & has_reads
        skip = too_old.astype(np.uint8)
        if S > 1:
            eff_r = np.where(too_old[:, None], 0, rp_cnt).astype(np.int32)
            eff_w = np.where(too_old[:, None], 0, wp_cnt).astype(np.int32)
        else:
            eff_r = np.where(too_old, 0, rp_cnt).astype(np.int32)
            eff_w = np.where(too_old, 0, wp_cnt).astype(np.int32)
        cr = np.cumsum(eff_r, axis=0)
        cw = np.cumsum(eff_w, axis=0)

        now_rel = self._rel(now)
        chunks: List[Tuple[List[Dict[str, np.ndarray]], int]] = []
        i = 0
        while i < ntx:
            r0 = cr[i - 1] if i else np.zeros_like(cr[0])
            w0 = cw[i - 1] if i else np.zeros_like(cw[0])
            j = min(i + cfg.max_txns, ntx)
            if S > 1:
                for s in range(S):
                    j = min(
                        j,
                        int(np.searchsorted(cr[:, s], r0[s] + cfg.rp, side="right")),
                        int(np.searchsorted(cw[:, s], w0[s] + cfg.wp, side="right")),
                    )
            else:
                j = min(
                    j,
                    int(np.searchsorted(cr, int(r0) + cfg.rp, side="right")),
                    int(np.searchsorted(cw, int(w0) + cfg.wp, side="right")),
                )
            j = max(j, i + 1)  # a single txn always fits (checked above)
            last = j >= ntx
            gc_rel = (
                self._rel(new_oldest)
                if last and new_oldest > self.oldest_version
                else 0
            )
            if S == 1:
                per = [wire_chunk_arrays(
                    cfg, blob, offs, i, j, skip, snap_rel, eff_r, now_rel, gc_rel,
                )]
            else:
                per = wire_chunk_arrays_sharded(
                    cfg, blob, offs, i, j, skip, snap_rel, eff_r, now_rel,
                    gc_rel, self._splits_blob, self._splits_offs, S,
                )
            chunks.append((per, j - i))
            i = j
        return {"chunks": chunks, "new_oldest": new_oldest}

    def columnar_dispatch(self, plan: dict):
        """Device half of the columnar fast path: dispatch every chunk's
        program via JAX ASYNC dispatch (nothing is forced to the host) and
        advance the host version bookkeeping. Returns force() ->
        List[TransactionCommitResult], which blocks on the device values.

        The ResolverPipeline keeps several dispatched batches in flight —
        the host packs batch i+1 while the device still runs batch i — and
        forces them in commit-version order, so abort sets are bit-identical
        to the serial resolve() path (the device programs run in dispatch
        order on one device queue either way). One observable difference:
        a boundary-table overflow raises at force() time, after any later
        chunks of the SAME batch were already dispatched (the serial path
        stops at the overflowing chunk); overflow is a fatal capacity error
        in both cases."""
        outs = []
        for per, n in plan["chunks"]:
            status_dev, overflow_dev, keepalive = self._run_step_async(per)
            # keepalive pins the host arrays the async program may be
            # reading zero-copy; it rides in `outs` until force() has
            # blocked on the program's outputs (see _run_step_async).
            outs.append((status_dev, overflow_dev, n, keepalive))
        new_oldest = plan["new_oldest"]
        if new_oldest > self.oldest_version:
            self.tier_map.gc(new_oldest)
            self.oldest_version = new_oldest
            self.base += max(0, new_oldest - self.base)
        capacity = self.cfg.capacity

        def force() -> List[TransactionCommitResult]:
            results: List[TransactionCommitResult] = []
            for status_dev, overflow_dev, n, _keepalive in outs:
                status = np.asarray(status_dev)
                if bool(np.asarray(overflow_dev)):
                    raise error.conflict_capacity_exceeded(
                        f"a shard's boundary table needs > {capacity} rows"
                    )
                results.extend(TransactionCommitResult(int(v)) for v in status[:n])
            return results

        return force

    def _resolve_chunk(
        self, routed: Sequence[_RoutedTxn], now: Version, new_oldest: Version
    ) -> List[TransactionCommitResult]:
        cfg = self.cfg
        S = self.n_shards
        n = len(routed)
        assert n <= cfg.max_txns

        too_old = np.zeros((cfg.max_txns,), bool)
        t_ok = np.zeros((cfg.max_txns,), bool)
        rpk: List[List[bytes]] = [[] for _ in range(S)]
        rps: List[List[int]] = [[] for _ in range(S)]
        rpt: List[List[int]] = [[] for _ in range(S)]
        rb: List[List[bytes]] = [[] for _ in range(S)]
        re_: List[List[bytes]] = [[] for _ in range(S)]
        rs: List[List[int]] = [[] for _ in range(S)]
        rt_: List[List[int]] = [[] for _ in range(S)]
        wpk: List[List[bytes]] = [[] for _ in range(S)]
        wpt: List[List[int]] = [[] for _ in range(S)]
        wb: List[List[bytes]] = [[] for _ in range(S)]
        we: List[List[bytes]] = [[] for _ in range(S)]
        wt: List[List[int]] = [[] for _ in range(S)]
        for t, rt in enumerate(routed):
            is_old = rt.snapshot < self.oldest_version and rt.has_reads()
            too_old[t] = is_old
            t_ok[t] = not is_old
            if is_old:
                continue
            snap = self._rel(rt.snapshot)
            for s, k in rt.preads:
                rpk[s].append(k)
                rps[s].append(snap)
                rpt[s].append(t)
            for s, cb, ce in rt.rreads:
                rb[s].append(cb)
                re_[s].append(ce)
                rs[s].append(snap)
                rt_[s].append(t)
            for s, k in rt.pwrites:
                wpk[s].append(k)
                wpt[s].append(t)
            for s, cb, ce in rt.rwrites:
                wb[s].append(cb)
                we[s].append(ce)
                wt[s].append(t)

        now_rel = self._rel(now)
        gc_rel = self._rel(new_oldest) if new_oldest > self.oldest_version else 0
        per = [
            build_batch_arrays(
                cfg,
                rpk[s], rps[s], rpt[s],
                rb[s], re_[s], rs[s], rt_[s],
                wpk[s], wpt[s],
                wb[s], we[s], wt[s],
                t_ok, too_old, now_rel, gc_rel,
            )
            for s in range(S)
        ]

        chunk_has_long = any(rt.has_long for rt in routed)
        chunk_has_rreads = any(rt.tier_rreads for rt in routed)
        chunk_has_rwrites = any(rt.tier_rwrites for rt in routed)
        # Slow (split-step) path only when verdicts can couple across tiers:
        # long rows present, or range reads that tier-held write history
        # could hit. Range-write-only chunks stay fused and just record.
        slow = chunk_has_long or (self._tier_has_writes and chunk_has_rreads)

        if not slow:
            status, overflow = self._run_step(per)
            if overflow:
                raise error.conflict_capacity_exceeded(
                    f"a shard's boundary table needs > {cfg.capacity} rows"
                )
            results = [TransactionCommitResult(int(v)) for v in status[:n]]
            if chunk_has_rwrites:
                self._tier_record(routed, results, now, new_oldest)
            elif new_oldest > self.oldest_version:
                self.tier_map.gc(new_oldest)
            return results

        # ---- split-step path: global verdicts BEFORE any writes ----------
        # Tier history hits are t_ok-level aborts; tier intra-batch edges
        # join the device fixpoint through an outer iteration that converges
        # to the oracle's sequential-sweep verdicts (all edges point earlier
        # txn -> later txn, so each round finalizes a growing prefix).
        tier_hist = np.zeros((cfg.max_txns,), bool)
        for t, rt in enumerate(routed):
            if not t_ok[t]:
                continue
            snap = rt.snapshot
            hit = False
            for k in rt.tier_preads:
                if self.tier_map.range_max(k, k + b"\x00") > snap:
                    hit = True
                    break
            if not hit:
                for k in rt.tier_ereads:
                    if self.tier_map.version_strictly_below(k) > snap:
                        hit = True
                        break
            if not hit:
                for b, e in rt.tier_rreads:
                    if self.tier_map.range_max(b, e) > snap:
                        hit = True
                        break
            tier_hist[t] = hit

        # Unconditional tier intra-batch edges (u writes, t reads, u < t);
        # whether an edge blocks depends on u's GLOBAL verdict each round.
        edges: List[Tuple[int, int]] = []
        writes_by_txn: List[List[Tuple[Key, Key]]] = []
        for u, ru in enumerate(routed):
            ws = [(k, k + b"\x00") for k in ru.tier_pwrites] + list(ru.tier_rwrites)
            writes_by_txn.append(ws)
        for t, rt in enumerate(routed):
            if not t_ok[t]:
                continue
            reads = [(k, k + b"\x00") for k in rt.tier_preads] + list(rt.tier_rreads)
            if not reads:
                continue
            for u in range(t):
                if any(rb_ < we_ and wb_ < re__
                       for (rb_, re__) in reads
                       for (wb_, we_) in writes_by_txn[u]):
                    edges.append((u, t))

        ctx = self._run_detect(per)
        cur_abort = tier_hist.copy()
        committed = self._run_fix(ctx, per, t_ok & ~cur_abort)
        for _ in range(n + 1):
            blocked = np.zeros((cfg.max_txns,), bool)
            for u, t in edges:
                if committed[u]:
                    blocked[t] = True
            new_abort = tier_hist | blocked
            if np.array_equal(new_abort, cur_abort):
                break
            cur_abort = new_abort
            committed = self._run_fix(ctx, per, t_ok & ~cur_abort)

        status, overflow = self._run_apply(ctx, per, committed)
        if overflow:
            raise error.conflict_capacity_exceeded(
                f"a shard's boundary table needs > {cfg.capacity} rows"
            )
        results = [TransactionCommitResult(int(v)) for v in status[:n]]
        self._tier_record(routed, results, now, new_oldest)
        return results

    def _write_lossy_on_device(self, b: Key, e: Key) -> bool:
        """True iff the device's truncated image of write [b, e) loses
        coverage somewhere — only such writes force later range reads onto
        the split-step path (a short-endpoint range write is fully visible
        on device, so device range-maxes already include it)."""
        w = self._window
        if len(b) > w or len(e) > w or self._packed_empty(b, e):
            return True
        for s, cb, ce in self.shards.shards_of_range(b, e):
            if _is_point(cb, ce):
                if len(cb) > w:
                    return True
            elif self._packed_empty(cb, ce):
                return True
        return False

    def _tier_record(self, routed, results, now: Version, new_oldest: Version) -> None:
        """Record COMMITTED tier writes into the host tier map + GC."""
        for t, rt in enumerate(routed):
            if results[t] != TransactionCommitResult.COMMITTED:
                continue
            for k in rt.tier_pwrites:
                self.tier_map.write(k, k + b"\x00", now)
                self._tier_has_writes = True
            for b, e in rt.tier_rwrites:
                self.tier_map.write(b, e, now)
                if not self._tier_has_writes and self._write_lossy_on_device(b, e):
                    self._tier_has_writes = True
        if new_oldest > self.oldest_version:
            self.tier_map.gc(new_oldest)


class SubshardedConflictEngine(RoutedConflictEngineBase):
    """S key-range sub-shards resident on ONE device (vmap over a leading
    axis): the single-chip throughput configuration. Each sub-shard holds a
    pro-rata boundary table, so the step runs S small sorts instead of one
    big one (conflict_kernel.resolve_step_stacked) while the host routes
    rows with the same native sharded passes the mesh engine uses. Verdicts
    are bit-identical to JaxConflictEngine/the oracle."""

    name = "subsharded"

    def __init__(self, cfg: KernelConfig, shards: KeyShardMap,
                 initial_version: Version = 0):
        super().__init__(cfg, shards)
        self._reset_device_state(initial_version)
        self.tier_map = VersionIntervalMap(initial_version)
        self._step = jax.jit(
            functools.partial(ck.resolve_step_stacked, cfg),
            **donate_state_kwargs(),
        )
        self._detect = jax.jit(functools.partial(ck.detect_step_stacked, cfg))
        self._fix = jax.jit(functools.partial(ck.fix_step_stacked, cfg))
        self._apply = jax.jit(
            functools.partial(ck.apply_step_stacked, cfg), **donate_state_kwargs())

    def _reset_device_state(self, version_rel: int) -> None:
        per = [
            ck.initial_state(self.cfg, version_rel=version_rel,
                             first_key=self.shards.begins[s])
            for s in range(self.n_shards)
        ]
        self.state = jax.tree.map(lambda *xs: jnp.stack(xs), *per)

    def _stack(self, per_shard: List[Dict[str, np.ndarray]]):
        return jax.tree.map(
            lambda *xs: jnp.asarray(np.stack([np.asarray(x) for x in xs])),
            *per_shard)

    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        batch = self._stack(per_shard)
        self.state, out = self._step(self.state, batch)
        return np.asarray(out["status"]), bool(out["overflow"])

    def _run_step_async(self, per_shard: List[Dict[str, np.ndarray]]):
        batch = self._stack(per_shard)
        self.state, out = self._step(self.state, batch)
        return out["status"], out["overflow"], batch

    def _run_detect(self, per_shard):
        batch = self._stack(per_shard)
        hist, edges, wpos = self._detect(self.state, batch)
        return {"batch": batch, "hist": hist, "ovp": edges, "wpos": wpos}

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        committed = self._fix(
            jnp.asarray(t_ok), ctx["hist"], ctx["ovp"], ctx["batch"])
        return np.asarray(committed)

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        cm = jnp.asarray(committed)
        self.state, overflow = self._apply(
            self.state, ctx["batch"], cm, ctx["wpos"])
        status = ck.status_of(np.asarray(ctx["batch"]["t_too_old"])[0], committed)
        return np.asarray(status), bool(overflow)


class JaxConflictEngine(RoutedConflictEngineBase):
    """Single-chip ConflictSet engine backed by the XLA/TPU kernel
    (one shard, plain jit). Same resolve() contract as OracleConflictEngine."""

    name = "jax"

    def __init__(self, cfg: KernelConfig = KernelConfig(), initial_version: Version = 0):
        super().__init__(cfg, KeyShardMap([]))
        self.state = ck.initial_state(cfg, version_rel=initial_version)
        self.tier_map = VersionIntervalMap(initial_version)
        self._step = jax.jit(
            functools.partial(ck.resolve_step, cfg),
            **donate_state_kwargs(),
        )
        # Split-step programs for the long-key tier path, compiled lazily
        # (short-key-only workloads never pay for them).
        self._detect = jax.jit(functools.partial(ck.detect_step, cfg))
        self._fix = jax.jit(functools.partial(ck.fix_step, cfg))
        self._apply = jax.jit(functools.partial(ck.apply_step, cfg), **donate_state_kwargs())

    def _reset_device_state(self, version_rel: int) -> None:
        self.state = ck.initial_state(self.cfg, version_rel=version_rel)

    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        (arrays,) = per_shard
        batch = {k: jnp.asarray(v) for k, v in arrays.items()}
        self.state, out = self._step(self.state, batch)
        return np.asarray(out["status"]), bool(out["overflow"])

    def _run_step_async(self, per_shard: List[Dict[str, np.ndarray]]):
        (arrays,) = per_shard
        batch = {k: jnp.asarray(v) for k, v in arrays.items()}
        self.state, out = self._step(self.state, batch)
        return out["status"], out["overflow"], (arrays, batch)

    def _run_detect(self, per_shard):
        (arrays,) = per_shard
        batch = {k: jnp.asarray(v) for k, v in arrays.items()}
        hist, ovp, wpos = self._detect(self.state, batch)
        return {"batch": batch, "hist": hist, "ovp": ovp, "wpos": wpos}

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        committed = self._fix(jnp.asarray(t_ok), ctx["hist"], ctx["ovp"], ctx["batch"])
        return np.asarray(committed)

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        batch = ctx["batch"]
        cm = jnp.asarray(committed)
        self.state, overflow = self._apply(self.state, batch, cm, ctx["wpos"])
        status = ck.status_of(np.asarray(batch["t_too_old"]), committed)
        return np.asarray(status), bool(overflow)
