"""Shared host-side machinery for device-backed ConflictSet engines.

Everything that is NOT the device program lives here exactly once: the int32
version window (device versions are offsets from a host-tracked base), the
key-range shard map + routing/clipping (the analog of the proxy's
`keyResolvers` range map, MasterProxyServer.actor.cpp:263-316), the greedy
transaction chunking against per-shard device caps, and fixed-shape batch
packing. Engines (single-chip jit, multi-chip shard_map) subclass and supply
only `_run_step`.

Batch splitting on transaction boundaries is exact: sub-batch writes land at
version `now` and every later read in the same batch has snapshot < now, so
history-vs-intra-batch classification cannot change any verdict.
"""
from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core import error
from ..core.types import CommitTransaction, Key, TransactionCommitResult, Version
from . import conflict_kernel as ck
from .conflict_kernel import KernelConfig, build_batch_arrays


class KeyShardMap:
    """Static partition of the keyspace into S contiguous spans.

    Span s = [begins[s], begins[s+1]) with begins[0] = b'' and a virtual
    +inf end for the last span (the analog of the keyResolvers range map,
    ProxyCommitData:169)."""

    def __init__(self, split_keys: Sequence[Key]):
        assert list(split_keys) == sorted(split_keys), "split keys must be sorted"
        assert all(k for k in split_keys), "split keys must be non-empty"
        self.begins: List[Key] = [b""] + list(split_keys)
        self.n_shards = len(self.begins)

    @staticmethod
    def uniform(n_shards: int) -> "KeyShardMap":
        """Evenly split on the first key byte."""
        if n_shards == 1:
            return KeyShardMap([])
        assert n_shards <= 256, "one-byte granularity cannot split past 256 shards"
        splits = [bytes([(256 * i) // n_shards]) for i in range(1, n_shards)]
        return KeyShardMap(splits)

    def span_end(self, s: int) -> Optional[Key]:
        return self.begins[s + 1] if s + 1 < self.n_shards else None

    def shard_of_key(self, key: Key) -> int:
        """Shard owning `key` (span containing it)."""
        return max(bisect.bisect_right(self.begins, key) - 1, 0)

    def shard_of_point_below(self, key: Key) -> int:
        """Shard owning the interval strictly below `key` (for empty reads:
        mirrors VersionIntervalMap.version_strictly_below's max(i,0))."""
        return max(bisect.bisect_left(self.begins, key) - 1, 0)

    def shards_of_range(self, begin: Key, end: Key) -> List[Tuple[int, Key, Key]]:
        """(shard, clipped_begin, clipped_end) for every span intersecting
        the non-empty range [begin, end)."""
        out = []
        lo = max(bisect.bisect_right(self.begins, begin) - 1, 0)
        for s in range(lo, self.n_shards):
            sb = self.begins[s]
            if sb >= end:
                break
            se = self.span_end(s)
            cb = max(begin, sb)
            ce = end if se is None else min(end, se)
            if cb < ce:
                out.append((s, cb, ce))
        return out


def _is_point(begin: Key, end: Key) -> bool:
    """True iff the half-open range is exactly [k, k+'\\x00') — the kernel's
    cheap POINT row shape (its end key is synthesized on device)."""
    return len(end) == len(begin) + 1 and end[-1] == 0 and end[:-1] == begin


@dataclass
class _RoutedTxn:
    """One transaction's conflict ranges, clipped per shard (computed once).
    Point rows ([k, k+'\\x00')) are classified here, carrying only the key."""

    preads: List[Tuple[int, Key]]       # (shard, key)
    rreads: List[Tuple[int, Key, Key]]  # (shard, begin, end) — may be empty ranges
    pwrites: List[Tuple[int, Key]]
    rwrites: List[Tuple[int, Key, Key]] # non-empty only
    n_preads: List[int]                 # per-shard counts
    n_rreads: List[int]
    n_pwrites: List[int]
    n_rwrites: List[int]
    snapshot: Version

    def has_reads(self) -> bool:
        return bool(self.preads or self.rreads)


class RoutedConflictEngineBase:
    """Host side of a device-backed ConflictSet engine. Subclasses implement
    `_run_step(per_shard_batches) -> (status[T] np.ndarray, overflow bool)`
    and `_reset_device_state(version_rel)`."""

    name = "routed"

    def __init__(self, cfg: KernelConfig, shards: KeyShardMap):
        # Subclasses seed their device state (incl. any initial version, as a
        # base-relative offset) via _reset_device_state.
        self.cfg = cfg
        self.shards = shards
        self.n_shards = shards.n_shards
        self.base: Version = 0
        self.oldest_version: Version = 0

    # -- subclass interface -------------------------------------------------
    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        raise NotImplementedError

    def _reset_device_state(self, version_rel: int) -> None:
        raise NotImplementedError

    # -- shared implementation ---------------------------------------------
    def clear(self, version: Version) -> None:
        """reference: clearConflictSet (SkipList.cpp:957-959)."""
        self._reset_device_state(self._rel(version))

    def _rel(self, v: Version) -> int:
        r = v - self.base
        if r >= 2**30:
            raise error.client_invalid_operation(
                f"version {v} too far beyond base {self.base} for int32 device window"
            )
        return max(r, -1)

    def _route_txn(self, tr: CommitTransaction) -> _RoutedTxn:
        S = self.n_shards
        rt = _RoutedTxn([], [], [], [], [0] * S, [0] * S, [0] * S, [0] * S, tr.read_snapshot)
        for r in tr.read_conflict_ranges:
            if r.begin >= r.end:
                s = self.shards.shard_of_point_below(r.begin)
                rt.rreads.append((s, r.begin, r.end))
                rt.n_rreads[s] += 1
            else:
                # A point range never straddles a shard split (a split key
                # strictly inside [k, k+'\x00') would have to equal k).
                for s, cb, ce in self.shards.shards_of_range(r.begin, r.end):
                    if _is_point(cb, ce):
                        rt.preads.append((s, cb))
                        rt.n_preads[s] += 1
                    else:
                        rt.rreads.append((s, cb, ce))
                        rt.n_rreads[s] += 1
        for w in tr.write_conflict_ranges:
            if w.begin < w.end:
                for s, cb, ce in self.shards.shards_of_range(w.begin, w.end):
                    if _is_point(cb, ce):
                        rt.pwrites.append((s, cb))
                        rt.n_pwrites[s] += 1
                    else:
                        rt.rwrites.append((s, cb, ce))
                        rt.n_rwrites[s] += 1
        cfg = self.cfg
        if (
            max(rt.n_preads) > cfg.rp
            or max(rt.n_rreads) > cfg.max_reads
            or max(rt.n_pwrites) > cfg.wp
            or max(rt.n_rwrites) > cfg.max_writes
        ):
            raise error.client_invalid_operation(
                "single transaction exceeds device conflict-range capacity"
            )
        return rt

    def resolve(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> List[TransactionCommitResult]:
        cfg = self.cfg
        S = self.n_shards
        routed = [self._route_txn(tr) for tr in transactions]
        results: List[TransactionCommitResult] = []
        i = 0
        ntx = len(transactions)
        caps = (
            ("n_preads", cfg.rp),
            ("n_rreads", cfg.max_reads),
            ("n_pwrites", cfg.wp),
            ("n_rwrites", cfg.max_writes),
        )
        while True:
            # Greedy prefix respecting every shard's device caps.
            j = i
            used = {f: [0] * S for f, _ in caps}
            while j < ntx and (j - i) < cfg.max_txns:
                rt = routed[j]
                if any(
                    used[f][s] + getattr(rt, f)[s] > cap
                    for f, cap in caps
                    for s in range(S)
                ):
                    break
                for f, _ in caps:
                    for s in range(S):
                        used[f][s] += getattr(rt, f)[s]
                j += 1
            last = j >= ntx
            results.extend(self._resolve_chunk(routed[i:j], now, new_oldest if last else 0))
            if last:
                break
            i = j
        if new_oldest > self.oldest_version:
            self.oldest_version = new_oldest
            self.base += max(0, new_oldest - self.base)
        return results

    def _resolve_chunk(
        self, routed: Sequence[_RoutedTxn], now: Version, new_oldest: Version
    ) -> List[TransactionCommitResult]:
        cfg = self.cfg
        S = self.n_shards
        n = len(routed)
        assert n <= cfg.max_txns

        too_old = np.zeros((cfg.max_txns,), bool)
        t_ok = np.zeros((cfg.max_txns,), bool)
        rpk: List[List[bytes]] = [[] for _ in range(S)]
        rps: List[List[int]] = [[] for _ in range(S)]
        rpt: List[List[int]] = [[] for _ in range(S)]
        rb: List[List[bytes]] = [[] for _ in range(S)]
        re_: List[List[bytes]] = [[] for _ in range(S)]
        rs: List[List[int]] = [[] for _ in range(S)]
        rt_: List[List[int]] = [[] for _ in range(S)]
        wpk: List[List[bytes]] = [[] for _ in range(S)]
        wpt: List[List[int]] = [[] for _ in range(S)]
        wb: List[List[bytes]] = [[] for _ in range(S)]
        we: List[List[bytes]] = [[] for _ in range(S)]
        wt: List[List[int]] = [[] for _ in range(S)]
        for t, rt in enumerate(routed):
            is_old = rt.snapshot < self.oldest_version and rt.has_reads()
            too_old[t] = is_old
            t_ok[t] = not is_old
            if is_old:
                continue
            snap = self._rel(rt.snapshot)
            for s, k in rt.preads:
                rpk[s].append(k)
                rps[s].append(snap)
                rpt[s].append(t)
            for s, cb, ce in rt.rreads:
                rb[s].append(cb)
                re_[s].append(ce)
                rs[s].append(snap)
                rt_[s].append(t)
            for s, k in rt.pwrites:
                wpk[s].append(k)
                wpt[s].append(t)
            for s, cb, ce in rt.rwrites:
                wb[s].append(cb)
                we[s].append(ce)
                wt[s].append(t)

        now_rel = self._rel(now)
        gc_rel = self._rel(new_oldest) if new_oldest > self.oldest_version else 0
        per = [
            build_batch_arrays(
                cfg,
                rpk[s], rps[s], rpt[s],
                rb[s], re_[s], rs[s], rt_[s],
                wpk[s], wpt[s],
                wb[s], we[s], wt[s],
                t_ok, too_old, now_rel, gc_rel,
            )
            for s in range(S)
        ]
        status, overflow = self._run_step(per)
        if overflow:
            raise error.conflict_capacity_exceeded(
                f"a shard's boundary table needs > {cfg.capacity} rows"
            )
        return [TransactionCommitResult(int(v)) for v in status[:n]]


class JaxConflictEngine(RoutedConflictEngineBase):
    """Single-chip ConflictSet engine backed by the XLA/TPU kernel
    (one shard, plain jit). Same resolve() contract as OracleConflictEngine."""

    name = "jax"

    def __init__(self, cfg: KernelConfig = KernelConfig(), initial_version: Version = 0):
        super().__init__(cfg, KeyShardMap([]))
        self.state = ck.initial_state(cfg, version_rel=initial_version)
        self._step = jax.jit(
            functools.partial(ck.resolve_step, cfg),
            donate_argnums=(0,),
        )

    def _reset_device_state(self, version_rel: int) -> None:
        self.state = ck.initial_state(self.cfg, version_rel=version_rel)

    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        (arrays,) = per_shard
        batch = {k: jnp.asarray(v) for k, v in arrays.items()}
        self.state, out = self._step(self.state, batch)
        return np.asarray(out["status"]), bool(out["overflow"])
