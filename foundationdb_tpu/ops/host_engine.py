"""Shared host-side machinery for device-backed ConflictSet engines.

Everything that is NOT the device program lives here exactly once: the int32
version window (device versions are offsets from a host-tracked base), the
key-range shard map + routing/clipping (the analog of the proxy's
`keyResolvers` range map, MasterProxyServer.actor.cpp:263-316), the greedy
transaction chunking against per-shard device caps, and fixed-shape batch
packing. Engines (single-chip jit, multi-chip shard_map) subclass and supply
only `_run_step`.

Batch splitting on transaction boundaries is exact: sub-batch writes land at
version `now` and every later read in the same batch has snapshot < now, so
history-vs-intra-batch classification cannot change any verdict.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core import error, progcache
from ..core.keyshard import KeyShardMap
from ..core.types import CommitTransaction, Key, TransactionCommitResult, Version
from . import conflict_kernel as ck
from . import keypack
from .conflict_kernel import KernelConfig, build_batch_arrays
from .oracle import VersionIntervalMap


from ..core.types import is_point_range as _is_point


def donate_state_kwargs() -> dict:
    """jit kwargs donating the engine-state argument — only off-CPU.

    On the CPU backend the donation is unusable anyway (XLA warns the
    buffers cannot be aliased), and executing a DESERIALIZED persistently
    cached program with donated inputs corrupts the glibc heap (double
    free, jaxlib 0.4.36) — a fresh engine whose jit hits the compilation
    cache aborts the process a few batches in. The real accelerator path
    keeps the in-place state aliasing."""
    if jax.default_backend() == "cpu":
        return {}
    return {"donate_argnums": (0,)}


@dataclass
class _RoutedTxn:
    """One transaction's conflict ranges, clipped per shard (computed once).
    Point rows ([k, k+'\\x00')) are classified here, carrying only the key.

    Rows involving keys beyond the device's exact-compare window go to the
    host long-key tier (tier_*): long points exclusively; range rows
    additionally (membership of long keys in any range is tier-owned, while
    the device answers the same range for in-window keys via truncated
    endpoints — an exact disjoint decomposition of the keyspace)."""

    preads: List[Tuple[int, Key]]       # (shard, key)
    rreads: List[Tuple[int, Key, Key]]  # (shard, begin, end) — may be empty ranges
    pwrites: List[Tuple[int, Key]]
    rwrites: List[Tuple[int, Key, Key]] # non-empty only
    n_preads: List[int]                 # per-shard counts
    n_rreads: List[int]
    n_pwrites: List[int]
    n_rwrites: List[int]
    snapshot: Version
    #: host-tier rows (byte keys, unclipped)
    tier_preads: List[Key]              # long point reads
    tier_ereads: List[Key]              # long empty reads [k, k)
    tier_rreads: List[Tuple[Key, Key]]  # non-empty range reads (all)
    tier_pwrites: List[Key]             # long point writes
    tier_rwrites: List[Tuple[Key, Key]] # non-empty range writes (all)
    has_long: bool = False              # any long-key row in this txn

    def has_reads(self) -> bool:
        return bool(self.preads or self.rreads or self.tier_preads
                    or self.tier_ereads or self.tier_rreads)


def wire_pass1(window: int, blocks: List[bytes]):
    """Native pass 1 over concatenated conflict-wire blocks: per-txn POINT
    row counts. Returns (blob, offs, rp_cnt, wp_cnt) or None when the batch
    has any range/empty/long-key row (general router handles it) or no
    native library is available."""
    lib = keypack._fastpack()
    if lib is None or not blocks:
        return None
    import ctypes

    n = len(blocks)
    blob = b"".join(blocks)
    offs = np.zeros((n + 1,), np.int64)
    np.cumsum(np.fromiter((len(b) for b in blocks), np.int64, count=n), out=offs[1:])
    rp_cnt = np.zeros((n,), np.int32)
    wp_cnt = np.zeros((n,), np.int32)
    rc = lib.conflict_counts(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, window,
        rp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return blob, offs, rp_cnt, wp_cnt


def wire_pass1_sharded(window: int, blocks: List[bytes],
                       splits_blob: bytes, splits_offs: np.ndarray, S: int):
    """Native pass 1 with per-shard routing: per-(txn, shard) POINT row
    counts. Returns (blob, offs, rp_cnt[n,S], wp_cnt[n,S]) or None when the
    batch has any range/empty/long-key row or no native library."""
    lib = keypack._fastpack()
    if lib is None or not blocks or not hasattr(lib, "conflict_counts_sharded"):
        return None
    import ctypes

    n = len(blocks)
    blob = b"".join(blocks)
    offs = np.zeros((n + 1,), np.int64)
    np.cumsum(np.fromiter((len(b) for b in blocks), np.int64, count=n), out=offs[1:])
    rp_cnt = np.zeros((n, S), np.int32)
    wp_cnt = np.zeros((n, S), np.int32)
    rc = lib.conflict_counts_sharded(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, window,
        splits_blob,
        splits_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        S - 1,
        rp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wp_cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return blob, offs, rp_cnt, wp_cnt


class ArenaLease:
    """Checkout handle for one chunk's mutable pack buffers. The arrays may
    be read ZERO-COPY by an async-dispatched device program (see
    _dispatch_unit's keepalive contract), so the buffers return to the
    pool only when release() is called — columnar_dispatch's force() does
    it after blocking on the program's outputs. An unreleased lease is
    merely unpooled: the buffers fall to the GC, never to reuse-while-read."""

    __slots__ = ("_arena", "_key", "_bufs")

    def __init__(self, arena: "HostPackArena", key, bufs: Dict[str, np.ndarray]):
        self._arena = arena
        self._key = key
        self._bufs = bufs

    def release(self) -> None:
        if self._bufs is not None:
            self._arena._give_back(self._key, self._bufs)
            self._bufs = None


class HostPackArena:
    """Reusable host-pack buffers: wire_chunk_arrays[_sharded] used to
    allocate ~10 fresh padded numpy arrays per chunk (the rp/wp key planes
    dominate — MBs per chunk at production shapes); the arena hands out
    pooled buffer sets keyed by the bucket shape instead.

    Reuse is bit-safe WITHOUT zeroing the big planes: every kernel input
    row beyond a group's valid prefix is dead — invalid rows sort under an
    all-ones key override, their hits are masked by the *_valid lanes, and
    the segment reduces only cover valid prefixes — so stale content from
    a previous chunk can never reach a verdict. Only the [T] t_ok /
    t_too_old lanes (whole-array semantics) are cleared per checkout.

    The range-row group is all-zero forever on the columnar path (points
    only), so one immutable zero set per shape is SHARED by every chunk in
    flight. Thread-safe: the pipeline packs on an executor thread while
    the main thread dispatches."""

    #: pooled buffer sets kept per shape (in-flight count is bounded by the
    #: pipeline depth + chunks per plan; beyond this, release just drops)
    MAX_POOLED = 8

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pools: Dict[tuple, List[Dict[str, np.ndarray]]] = {}
        self._shared: Dict[tuple, Dict[str, np.ndarray]] = {}
        #: buffer sets created fresh because the pool was empty (the bench
        #: reports steady-state == 0 alongside host_pack_ms)
        self.misses = 0

    @staticmethod
    def _key(cfg: KernelConfig, S: int) -> tuple:
        return (S, cfg.max_txns, cfg.rp, cfg.wp, cfg.max_reads,
                cfg.max_writes, cfg.lanes)

    def lease(self, cfg: KernelConfig, S: int = 1) -> Tuple[Dict[str, np.ndarray], ArenaLease]:
        """Buffers for one chunk at `cfg`'s shapes ((S, ...) when sharded).
        Returns (bufs, lease); bufs also exposes the shared zero range-row
        arrays and cached aranges under the same dict."""
        key = self._key(cfg, S)
        with self._lock:
            pool = self._pools.get(key)
            bufs = pool.pop() if pool else None
            shared = self._shared.get(key)
            if shared is None:
                shared = self._make_shared(cfg, S)
                self._shared[key] = shared
        if bufs is None:
            self.misses += 1
            bufs = self._make_bufs(cfg, S)
        out = dict(shared)
        out.update(bufs)
        return out, ArenaLease(self, key, bufs)

    def _give_back(self, key, bufs: Dict[str, np.ndarray]) -> None:
        with self._lock:
            pool = self._pools.setdefault(key, [])
            if len(pool) < self.MAX_POOLED:
                pool.append(bufs)

    @staticmethod
    def _make_bufs(cfg: KernelConfig, S: int) -> Dict[str, np.ndarray]:
        K = cfg.lanes
        sh = (lambda *s: s) if S == 1 else (lambda *s: (S,) + s)
        return {
            "rpb": np.zeros(sh(cfg.rp, K), np.uint32),
            "rp_txn": np.zeros(sh(cfg.rp), np.int32),
            "rp_snap": np.zeros(sh(cfg.rp), np.int32),
            "rp_valid": np.zeros(sh(cfg.rp), bool),
            "wpb": np.zeros(sh(cfg.wp, K), np.uint32),
            "wp_txn": np.zeros(sh(cfg.wp), np.int32),
            "wp_valid": np.zeros(sh(cfg.wp), bool),
            "t_ok": np.zeros((cfg.max_txns,), bool),
            "t_too_old": np.zeros((cfg.max_txns,), bool),
        }

    @staticmethod
    def _make_shared(cfg: KernelConfig, S: int) -> Dict[str, np.ndarray]:
        K = cfg.lanes
        Rr, Wr = cfg.max_reads, cfg.max_writes
        sh = (lambda *s: s) if S == 1 else (lambda *s: (S,) + s)
        return {
            "rb": np.zeros(sh(Rr, K), np.uint32),
            "re": np.zeros(sh(Rr, K), np.uint32),
            "r_snap": np.zeros(sh(Rr), np.int32),
            "r_txn": np.zeros(sh(Rr), np.int32),
            "r_valid": np.zeros(sh(Rr), bool),
            "wb": np.zeros(sh(Wr, K), np.uint32),
            "we": np.zeros(sh(Wr, K), np.uint32),
            "w_txn": np.zeros(sh(Wr), np.int32),
            "w_valid": np.zeros(sh(Wr), bool),
            "_arange_rp": np.arange(cfg.rp),
            "_arange_wp": np.arange(cfg.wp),
        }


def wire_chunk_arrays_sharded(
    cfg: KernelConfig,
    blob: bytes,
    offs: np.ndarray,
    t0: int,
    t1: int,
    skip: np.ndarray,
    snap_rel: np.ndarray,
    eff_r: np.ndarray,         # int32 [ntx, S] read counts, skipped txns zeroed
    now_rel: int,
    gc_rel: int,
    splits_blob: bytes,
    splits_offs: np.ndarray,
    S: int,
    bufs: Optional[Dict[str, np.ndarray]] = None,
) -> List[Dict[str, np.ndarray]]:
    """Native pass 2, sharded: per-shard kernel batch dicts for txns
    [t0, t1) straight from wire bytes. One C call routes + packs every
    point row into its shard's padded region; the int lanes are vectorized
    numpy. Point keys route whole (a point range never straddles a shard
    split), so no clipping happens here. `bufs` (HostPackArena.lease)
    supplies reusable buffers; rows beyond each valid prefix stay stale —
    masked by the *_valid lanes (see HostPackArena)."""
    import ctypes

    lib = keypack._fastpack()
    K = cfg.lanes
    n = t1 - t0
    if bufs is None:
        bufs = dict(HostPackArena._make_shared(cfg, S))
        bufs.update(HostPackArena._make_bufs(cfg, S))
    rpb = bufs["rpb"]
    rp_txn = bufs["rp_txn"]
    wpb = bufs["wpb"]
    wp_txn = bufs["wp_txn"]
    out_n = np.zeros((2 * S,), np.int64)
    lib.build_point_rows_sharded(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        t0, t1, bytes(skip),
        cfg.key_words,
        splits_blob,
        splits_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        S - 1,
        cfg.rp, cfg.wp,
        rpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        rp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        wp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_n.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    t_ok = bufs["t_ok"]
    t_too_old = bufs["t_too_old"]
    t_ok.fill(False)
    t_too_old.fill(False)
    t_too_old[:n] = skip[t0:t1] != 0
    t_ok[:n] = ~t_too_old[:n]
    rp_valid = bufs["rp_valid"]
    wp_valid = bufs["wp_valid"]
    rp_snap = bufs["rp_snap"]
    arange_rp = bufs["_arange_rp"]
    arange_wp = bufs["_arange_wp"]
    now_a = np.asarray(now_rel, np.int32)
    gc_a = np.asarray(gc_rel, np.int32)
    per = []
    for s in range(S):
        n_rp, n_wp = int(out_n[2 * s]), int(out_n[2 * s + 1])
        rp_snap[s, :n_rp] = np.repeat(snap_rel[t0:t1], eff_r[t0:t1, s])
        np.less(arange_rp, n_rp, out=rp_valid[s])
        np.less(arange_wp, n_wp, out=wp_valid[s])
        per.append({
            "rpb": rpb[s],
            "rp_snap": rp_snap[s],
            "rp_txn": rp_txn[s],
            "rp_valid": rp_valid[s],
            "rb": bufs["rb"][s],
            "re": bufs["re"][s],
            "r_snap": bufs["r_snap"][s],
            "r_txn": bufs["r_txn"][s],
            "r_valid": bufs["r_valid"][s],
            "wpb": wpb[s],
            "wp_txn": wp_txn[s],
            "wp_valid": wp_valid[s],
            "wb": bufs["wb"][s],
            "we": bufs["we"][s],
            "w_txn": bufs["w_txn"][s],
            "w_valid": bufs["w_valid"][s],
            "t_ok": t_ok,
            "t_too_old": t_too_old,
            "now": now_a,
            "gc": gc_a,
        })
    return per


def wire_chunk_arrays(
    cfg: KernelConfig,
    blob: bytes,
    offs: np.ndarray,
    t0: int,
    t1: int,
    skip: np.ndarray,          # uint8 [ntx], 1 = contribute no rows (too old)
    snap_rel: np.ndarray,      # int32 [ntx]
    eff_r: np.ndarray,         # int32 [ntx] read counts with skipped txns zeroed
    now_rel: int,
    gc_rel: int,
    bufs: Optional[Dict[str, np.ndarray]] = None,
) -> Dict[str, np.ndarray]:
    """Native pass 2: kernel batch dict for txns [t0, t1) straight from wire
    bytes — the row groups are written into their padded arrays by C, the
    int lanes by vectorized numpy. The per-range Python of build_batch_arrays
    never runs on this path. `bufs` (HostPackArena.lease) supplies reusable
    buffers; rows beyond each valid prefix stay stale — masked by the
    *_valid lanes (see HostPackArena)."""
    import ctypes

    lib = keypack._fastpack()
    K = cfg.lanes
    n = t1 - t0
    if bufs is None:
        bufs = dict(HostPackArena._make_shared(cfg, 1))
        bufs.update(HostPackArena._make_bufs(cfg, 1))
    rpb = bufs["rpb"]
    rp_txn = bufs["rp_txn"]
    wpb = bufs["wpb"]
    wp_txn = bufs["wp_txn"]
    out_n = np.zeros((2,), np.int64)
    lib.build_point_rows(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        t0, t1, bytes(skip),
        cfg.key_words,
        rpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        rp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wpb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        wp_txn.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_n.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    n_rp, n_wp = int(out_n[0]), int(out_n[1])
    rp_snap = bufs["rp_snap"]
    rp_snap[:n_rp] = np.repeat(snap_rel[t0:t1], eff_r[t0:t1])
    t_ok = bufs["t_ok"]
    t_too_old = bufs["t_too_old"]
    t_ok.fill(False)
    t_too_old.fill(False)
    t_too_old[:n] = skip[t0:t1] != 0
    t_ok[:n] = ~t_too_old[:n]
    rp_valid = bufs["rp_valid"]
    wp_valid = bufs["wp_valid"]
    np.less(bufs["_arange_rp"], n_rp, out=rp_valid)
    np.less(bufs["_arange_wp"], n_wp, out=wp_valid)
    return {
        "rpb": rpb,
        "rp_snap": rp_snap,
        "rp_txn": rp_txn,
        "rp_valid": rp_valid,
        "rb": bufs["rb"],
        "re": bufs["re"],
        "r_snap": bufs["r_snap"],
        "r_txn": bufs["r_txn"],
        "r_valid": bufs["r_valid"],
        "wpb": wpb,
        "wp_txn": wp_txn,
        "wp_valid": wp_valid,
        "wb": bufs["wb"],
        "we": bufs["we"],
        "w_txn": bufs["w_txn"],
        "w_valid": bufs["w_valid"],
        "t_ok": t_ok,
        "t_too_old": t_too_old,
        "now": np.asarray(now_rel, np.int32),
        "gc": np.asarray(gc_rel, np.int32),
    }


#: dispatch-ring size of EnginePerf.recent (module-level so the dataclass
#: default factory stays picklable/simple)
DISPATCH_RING_SIZE = 64


def _dispatch_ring():
    from collections import deque

    return deque(maxlen=DISPATCH_RING_SIZE)


@dataclass
class EnginePerf:
    """Serving-path performance counters of a bucketed engine, read by
    bench.py's `bucket_ladder` section and the compile regression guard."""

    #: programs built (one per (bucket, chunk-count) shape ever dispatched);
    #: after warmup() this must NOT grow in steady state
    compiles: int = 0
    #: chunks dispatched per bucket T
    bucket_hits: Dict[int, int] = field(default_factory=dict)
    #: dispatches per fused-scan length (1 = single-chunk program)
    scan_dispatches: Dict[int, int] = field(default_factory=dict)
    #: resolved history-search mode per bucket T (docs/perf.md): what the
    #: `resolver_history_search_mode` knob / auto rule picked at ladder
    #: build — the mode each compiled program actually traces
    search_modes: Dict[int, str] = field(default_factory=dict)
    #: chunks dispatched per history-search mode (the mode-pick counters
    #: core/telemetry.py exports as `search_mode_hits.*`)
    search_mode_hits: Dict[str, int] = field(default_factory=dict)
    #: chunks dispatched per dispatch mode ("step" = per-unit launch +
    #: force, "loop" = device-resident server loop enqueue; docs/perf.md
    #: "Device-resident loop") — exported as `dispatch_mode_hits.*` so the
    #: telemetry frontends show which path served traffic
    dispatch_mode_hits: Dict[str, int] = field(default_factory=dict)
    #: abort-cause counters: transactions by final verdict (committed /
    #: conflicts / too_old), aggregated across every dispatch path —
    #: before this, the verdict split was only visible per batch in
    #: status_of and never aggregated anywhere (docs/observability.md
    #: "Keyspace heat & occupancy")
    verdicts: Dict[str, int] = field(default_factory=dict)
    #: sampled enqueue->ready device timing per bucket T (the
    #: `resolver_device_time_sample_rate` knob; docs/observability.md
    #: "Performance observatory"): {T: {samples, chunks, ms_total}}
    device_time: Dict[int, Dict[str, float]] = field(default_factory=dict)
    warmup_ms: float = 0.0
    warmed: bool = False
    #: flight recorder (docs/observability.md): a bounded ring of recent
    #: dispatch units — bucket, fused-scan length, txns covered, and the
    #: force/readback wall ms once the unit was forced. Always on: records
    #: are tiny dicts in a fixed-size deque, and a device incident report
    #: needs the dispatches that LED UP to it, which can never be sampled
    #: after the fact.
    recent: "deque" = field(default_factory=lambda: _dispatch_ring())

    def record_dispatch(self, bucket: int, scan: int, txns: int) -> dict:
        rec = {"bucket": bucket, "scan": scan, "txns": txns, "force_ms": None}
        self.recent.append(rec)
        return rec

    def record_search_mode(self, bucket: int, chunks: int) -> None:
        mode = self.search_modes.get(bucket, "fused_sort")
        self.search_mode_hits[mode] = self.search_mode_hits.get(mode, 0) + chunks

    def record_dispatch_mode(self, mode: str, chunks: int) -> None:
        self.dispatch_mode_hits[mode] = (
            self.dispatch_mode_hits.get(mode, 0) + chunks)

    def record_device_time(self, bucket: int, ms: float,
                           chunks: int = 1) -> None:
        """Fold one SAMPLED dispatch unit's measured enqueue->ready wall
        interval into the per-bucket accumulators (docs/observability.md
        "Performance observatory"). The interval covers `chunks` fused
        chunks, so the per-chunk mean is what compares against injected
        per-bucket device times; it is an upper bound on device time —
        exact when the host was waiting on the unit, inflated by host
        slack when results sat ready in a ring before the drain looked."""
        d = self.device_time.setdefault(
            bucket, {"samples": 0, "chunks": 0, "ms_total": 0.0})
        d["samples"] += 1
        d["chunks"] += chunks
        d["ms_total"] += float(ms)

    def device_time_ms_by_bucket(self) -> Dict[int, float]:
        """Mean measured per-CHUNK device ms per bucket over every
        sample — the measured figure `latency_attribution` reports
        alongside the sim's injected per-bucket times."""
        return {b: round(d["ms_total"] / d["chunks"], 4)
                for b, d in self.device_time.items() if d["chunks"]}

    def record_verdicts(self, status) -> None:
        """Fold one batch's final statuses (any int iterable / np array of
        TransactionCommitResult codes) into the abort-cause counters."""
        arr = np.asarray(status, dtype=np.int64)
        if arr.size == 0:
            return
        committed = int(np.sum(arr == int(TransactionCommitResult.COMMITTED)))
        too_old = int(np.sum(arr == int(TransactionCommitResult.TOO_OLD)))
        v = self.verdicts
        v["committed"] = v.get("committed", 0) + committed
        v["too_old"] = v.get("too_old", 0) + too_old
        v["conflicts"] = (v.get("conflicts", 0)
                          + int(arr.size) - committed - too_old)

    def as_dict(self) -> dict:
        return {
            "compiles": self.compiles,
            "bucket_hits": {str(k): v for k, v in sorted(self.bucket_hits.items())},
            "scan_dispatches": {str(k): v
                                for k, v in sorted(self.scan_dispatches.items())},
            "search_modes": {str(k): v
                             for k, v in sorted(self.search_modes.items())},
            "search_mode_hits": dict(sorted(self.search_mode_hits.items())),
            "dispatch_mode_hits": dict(sorted(self.dispatch_mode_hits.items())),
            "verdicts": dict(sorted(self.verdicts.items())),
            "device_time_ms": {str(b): v for b, v in
                               sorted(self.device_time_ms_by_bucket().items())},
            "device_time_samples": {
                str(b): d["samples"]
                for b, d in sorted(self.device_time.items())},
            "warmup_ms": round(self.warmup_ms, 1),
            "warmed": self.warmed,
            "recent_dispatches": len(self.recent),
        }


def ladder_from_knob() -> Optional[List[int]]:
    """Parse the `resolver_bucket_ladder` knob ("512,1024,2048") into bucket
    sizes; empty/unset means single-bucket (today's behavior). Entries are
    NOT validated here: an engine keeps only the sizes below its own top
    shape (the global knob serves engines of every size — a 128-txn test
    engine under a "512,1024" production knob runs single-bucket), while a
    size that fits but breaks the %32 layout fails loudly in bucket()."""
    from ..core.knobs import SERVER_KNOBS

    raw = str(getattr(SERVER_KNOBS, "resolver_bucket_ladder", "") or "").strip()
    if not raw:
        return None
    return [int(tok) for tok in raw.replace(" ", "").split(",") if tok]


class RoutedConflictEngineBase:
    """Host side of a device-backed ConflictSet engine. Subclasses implement
    `_run_step(per_shard_batches) -> (status[T] np.ndarray, overflow bool)`
    and `_reset_device_state(version_rel)`.

    Bucketed kernel ladder: `ladder` lists sub-capacity batch sizes (each a
    divisor-ish T < cfg.max_txns; cfg itself is always the top bucket).
    Every bucket's program shares the one `capacity`-sized interval-table
    state, so the host may dispatch any chunk on the smallest bucket whose
    batch-side shapes fit — a light batch no longer pays the heavy batch's
    device time. warmup() compiles the whole ladder eagerly so the serving
    path never hits a JIT stall; consecutive same-bucket chunks fuse into
    one lax.scan dispatch (`scan_sizes`)."""

    name = "routed"
    #: how columnar_dispatch hands chunks to the device: "step" launches a
    #: program per dispatch unit and force() blocks on its outputs; "loop"
    #: (ops/device_loop.py) enqueues onto the device-resident server
    #: loop's queue and force() drains a result ring non-blockingly.
    #: Telemetry (dispatch_mode_hits), the BudgetBatcher's EWMA keys and
    #: the span split all key off this.
    dispatch_mode = "step"

    def __init__(self, cfg: KernelConfig, shards: KeyShardMap,
                 ladder: Optional[Sequence[int]] = None,
                 scan_sizes: Sequence[int] = (2, 4, 8),
                 arena: bool = True,
                 history_search: Optional[str] = None,
                 heat_buckets: Optional[int] = None,
                 device_time_sample_rate: Optional[float] = None,
                 history_structure: Optional[str] = None):
        # Subclasses seed their device state (incl. any initial version, as a
        # base-relative offset) via _reset_device_state.
        cfg = self._resolve_history_search(cfg, history_search)
        cfg = self._resolve_history_structure(cfg, history_structure)
        cfg = self._resolve_heat(cfg, heat_buckets)
        self.cfg = cfg
        self.shards = shards
        self.n_shards = shards.n_shards
        self.base: Version = 0
        self.oldest_version: Version = 0
        self._window = keypack.max_key_bytes(cfg.key_words)
        #: exact host tier for out-of-window keys (absolute versions);
        #: short-key-only workloads never touch it
        self.tier_map = VersionIntervalMap(0)
        self._tier_has_writes = False
        # Shard split keys in the wire form the native router consumes.
        splits = self.shards.begins[1:]
        self._splits_blob = b"".join(splits)
        self._splits_offs = np.zeros((len(splits) + 1,), np.int64)
        np.cumsum(
            np.fromiter((len(s) for s in splits), np.int64, count=len(splits)),
            out=self._splits_offs[1:],
        )
        # -- bucket ladder ------------------------------------------------
        if ladder is None:
            ladder = ladder_from_knob() or []
        # only sizes below this engine's top shape apply (ladder_from_knob)
        sizes = sorted({t for t in ladder if t < cfg.max_txns})
        self.buckets: List[KernelConfig] = [cfg.bucket(t) for t in sizes] + [cfg]
        self._scan_sizes = tuple(sorted({int(c) for c in scan_sizes if c > 1}))
        #: (bucket_T, n_chunks) -> device program (engine-specific handle)
        self._programs: Dict[Tuple[int, int], Any] = {}
        self.perf = EnginePerf(
            bucket_hits={b.max_txns: 0 for b in self.buckets},
            search_modes={b.max_txns: ck.resolved_history_search(b)
                          for b in self.buckets})
        # compile & memory ledger (core/perfledger.py): every program
        # build recorded with duration + cost/memory analysis; "warmup"
        # vs "steady" classified by the flag warmup() holds
        from ..core import perfledger

        self.perf_ledger = perfledger.PerfLedger()
        self._warming = False
        # sampled enqueue->ready device timing (docs/observability.md
        # "Performance observatory"): deterministic 1-in-N dispatch
        # cadence, no rng; 0 = off
        self._sample_every = perfledger.sample_every_from_rate(
            device_time_sample_rate)
        self._dispatch_seq = 0
        self.arena: Optional[HostPackArena] = HostPackArena() if arena else None
        # keyspace-heat aggregator (core/heatmap.py): merges the device's
        # per-batch heat aggregates; None when the layer is off — the
        # disabled path allocates nothing
        from ..core import heatmap

        self.heat = heatmap.aggregator_for(cfg, n_shards=self.n_shards)
        #: batch version the in-flight dispatch belongs to (heat labels)
        self._heat_version = None
        # unified telemetry (core/telemetry.py): perf counters become
        # TDMetric series a MetricLogger can persist; registration draws no
        # rng and costs one list append
        from ..core import telemetry

        telemetry.hub().register_engine_perf(self.perf, name=self.name)
        telemetry.hub().register_perf_ledger(self.perf_ledger, name=self.name)
        if self.heat is not None:
            telemetry.hub().register_heat(self.heat, name=self.name)
        if ck.resolved_history_structure(cfg) == "tiered":
            # tiered-history eyes (the `history.*` / fdbtpu_history
            # series): registered only when the structure is live so the
            # monolithic fleet's exposition stays byte-stable
            telemetry.hub().register_history(self, name=self.name)

    # -- history search mode (docs/perf.md) ---------------------------------
    @staticmethod
    def _resolve_history_search(cfg: KernelConfig, requested: Optional[str]) -> KernelConfig:
        """Fold the mode request into the config the ladder is built from.
        Precedence: explicit constructor argument > a non-auto
        cfg.history_search > the `resolver_history_search_mode` knob. The
        result may still be "auto": the per-bucket pick then happens at
        trace time (small buckets on a large capacity go bsearch)."""
        from ..core.knobs import SERVER_KNOBS

        mode = requested
        if mode is None:
            mode = cfg.history_search
        if mode == "auto":
            mode = str(getattr(SERVER_KNOBS, "resolver_history_search_mode",
                               "auto") or "auto").strip()
        if mode not in ck.HISTORY_SEARCH_MODES:
            raise ValueError(
                f"unknown history search mode {mode!r}; expected one of "
                f"{ck.HISTORY_SEARCH_MODES}")
        if mode == cfg.history_search:
            return cfg
        import dataclasses

        return dataclasses.replace(cfg, history_search=mode)

    def history_search_modes(self) -> Dict[int, str]:
        """Resolved history-search mode per ladder bucket {T: mode} — what
        BudgetBatcher keys its per-(bucket, mode) EWMAs by."""
        return dict(self.perf.search_modes)

    # -- history structure (docs/perf.md "Incremental history maintenance") --
    @staticmethod
    def _resolve_history_structure(cfg: KernelConfig,
                                   requested: Optional[str]) -> KernelConfig:
        """Fold the history-structure request into the config the ladder
        is built from. Precedence: explicit constructor argument > a
        non-default cfg.history_structure > the
        `resolver_history_structure` knob. The resolved structure is baked
        into every bucket's compiled program AND its state tree (bucket()
        clones propagate it together with the materialized run-row
        capacity), so the whole ladder shares one structure."""
        from ..core.knobs import SERVER_KNOBS

        structure = requested
        if structure is None:
            structure = cfg.history_structure
            if structure == "monolithic":
                structure = str(getattr(SERVER_KNOBS,
                                        "resolver_history_structure",
                                        "monolithic")
                                or "monolithic").strip()
        if structure not in ck.HISTORY_STRUCTURES:
            raise ValueError(
                f"unknown history structure {structure!r}; expected one of "
                f"{ck.HISTORY_STRUCTURES}")
        runs = cfg.history_runs
        if structure == "tiered" and runs == KernelConfig.history_runs:
            # run-slot count: a non-default cfg.history_runs wins; the
            # dataclass default defers to the `resolver_history_runs` knob
            runs = int(getattr(SERVER_KNOBS, "resolver_history_runs",
                               runs) or runs)
        if structure == cfg.history_structure and runs == cfg.history_runs:
            ck.resolved_history_structure(cfg)  # validate run geometry
            return cfg
        import dataclasses

        cfg = dataclasses.replace(cfg, history_structure=structure,
                                  history_runs=runs)
        ck.resolved_history_structure(cfg)
        return cfg

    @property
    def history_structure(self) -> str:
        """The resolved history structure ("monolithic" | "tiered")."""
        return ck.resolved_history_structure(self.cfg)

    def _history_fingerprint(self) -> str:
        """The history-structure half of the progcache key (core/progcache
        `key(structure=)`): "" for the monolithic table so pre-existing
        cache entries keep their hashes, "tiered:<runs>x<rows>" when the
        compiled programs bake the tiered sorted-run planes into the
        state tree — a structure (or run-geometry) flip must be a clean
        progcache miss, never a poisoned hit."""
        if ck.resolved_history_structure(self.cfg) != "tiered":
            return ""
        return f"tiered:{self.cfg.run_slots}x{self.cfg.run_rows}"

    def history_stats_snapshot(self) -> Dict[str, Any]:
        """Tiered-history accounting for telemetry/status documents: the
        structure identity plus the run/merge counters the heat
        aggregator mirrors from the device heat aggregate's `runs` leaf
        (core/heatmap.py history_snapshot) — the accounting rides the
        existing per-batch heat output, so it costs zero extra host syncs
        on every dispatch surface (step / fused scan / loop / mesh). With
        heat off the counters read 0 (identity rows stay accurate)."""
        out: Dict[str, Any] = {
            "structure": ck.resolved_history_structure(self.cfg),
            "run_slots": self.cfg.run_slots
            if ck.resolved_history_structure(self.cfg) == "tiered" else 0,
            "run_rows": self.cfg.run_rows
            if ck.resolved_history_structure(self.cfg) == "tiered" else 0,
            "appends": 0, "merges": 0, "runs_live": 0, "run_rows_live": 0,
        }
        if self.heat is not None:
            out.update(self.heat.history_snapshot())
        return out

    def history_run_snapshots(self, since_runs: Optional[Sequence[int]] = None):
        """Per-shard tiered run snapshots (ck.history_run_snapshot) — the
        O(delta) export the ResilientEngine shadow rebuild and the
        pre-copy handoff consume. `since_runs` is the per-shard run
        watermark from the previous snapshot; a snapshot whose `nruns`
        dropped below the watermark means a lazy merge compacted the
        stack and the consumer must fall back to a full resync. None for
        monolithic engines (no incremental export — full replay)."""
        if ck.resolved_history_structure(self.cfg) != "tiered":
            return None
        states = self._device_states_for_snapshot()
        if states is None:
            return None
        out = []
        for s, st in enumerate(states):
            since = 0 if since_runs is None else int(since_runs[s])
            out.append(ck.history_run_snapshot(self.cfg, st, since_runs=since))
        return out

    def _device_states_for_snapshot(self):
        """Per-shard device state dicts for history_run_snapshots; None
        when this engine family keeps no host-readable state handle."""
        return None

    # -- keyspace heat (docs/observability.md "Keyspace heat & occupancy") ---
    @staticmethod
    def _resolve_heat(cfg: KernelConfig, requested: Optional[int]) -> KernelConfig:
        """Fold the heat-bucket request into the config the ladder is
        built from. Precedence: explicit constructor argument > a non-zero
        cfg.heat_buckets > the `resolver_heat_buckets` knob. The resolved
        count is baked into every bucket's compiled program (bucket()
        clones propagate it), so warmup covers the heat outputs too."""
        b = requested
        if b is None:
            b = cfg.heat_buckets
            if b == 0:
                from ..core.heatmap import heat_buckets_from_knobs

                b = heat_buckets_from_knobs()
        b = int(b)
        if b < 0:
            raise ValueError(f"resolver_heat_buckets must be >= 0, got {b}")
        if b == cfg.heat_buckets:
            return cfg
        import dataclasses

        return dataclasses.replace(cfg, heat_buckets=b)

    def heat_snapshot(self, top_n: int = 8, brief: bool = False):
        """The keyspace-heat/occupancy fragment (core/heatmap.py) riding
        engine_health -> ratekeeper -> CC status doc -> `cli heat`, spans
        and flight-recorder records; None when the layer is off."""
        if self.heat is None:
            return None
        return self.heat.snapshot(top_n=top_n, brief=brief)

    def _merge_heat(self, heat_host, version=None, base=None,
                    layout: str = "") -> None:
        """Merge a forced heat subtree into the aggregator. `layout`
        names the leading axes of the leaves so chunk and shard axes are
        NOT conflated — a chunk ([C] fused scan, [Q] loop slot) is a
        distinct set of transactions and counts fully, while a shard
        axis ([S]) re-describes the SAME transactions across key-range
        shards and must fold through ONE merge_shards call (counting the
        replicated committed/conflicts/too_old per shard would inflate
        the verdict totals n_shards-fold and tick the decay S times per
        batch):

          ""   — one single-shard chunk (resolve_step)
          "c"  — chunk-leading [C, ...] (fused scan / loop slot prefix)
          "s"  — shard-leading [S, ...], one chunk (stacked/mesh step)
          "cs" — [C, S, ...] (sub-sharded fused scan)
          "sc" — [S, C, ...] (mesh fused scan: shard axis outermost)

        `base` is the engine version base the batch was packed against
        (witness versions are base-relative); default: the current base."""
        if self.heat is None or heat_host is None:
            return
        if base is None:
            base = self.base

        def at(tree, i):
            return {k: np.asarray(v)[i] for k, v in tree.items()}

        n = np.asarray(heat_host["bounds"]).shape[0] if layout else 0
        if layout == "":
            self.heat.merge({k: np.asarray(v) for k, v in heat_host.items()},
                            base=base, version=version)
        elif layout == "c":
            for c in range(n):
                self._merge_heat(at(heat_host, c), version, base, "")
        elif layout == "s":
            self.heat.merge_shards([at(heat_host, s) for s in range(n)],
                                   base=base, version=version)
        elif layout == "cs":
            for c in range(n):
                self._merge_heat(at(heat_host, c), version, base, "s")
        elif layout == "sc":
            per_shard = [at(heat_host, s) for s in range(n)]
            n_chunks = np.asarray(per_shard[0]["bounds"]).shape[0]
            for c in range(n_chunks):
                self.heat.merge_shards([at(sh, c) for sh in per_shard],
                                       base=base, version=version)
        else:
            raise ValueError(f"unknown heat layout {layout!r}")

    # -- bucket ladder / program cache --------------------------------------
    def bucket_for(self, n_txns: int, n_reads: int, n_writes: int) -> KernelConfig:
        """Smallest bucket that fits a chunk's txn count and point-row
        counts (per-shard maxima for S > 1); the top bucket always fits by
        chunk construction."""
        for b in self.buckets:
            if n_txns <= b.max_txns and n_reads <= b.rp and n_writes <= b.wp:
                return b
        return self.buckets[-1]

    def _program(self, bucket: KernelConfig, n_chunks: int):
        key = (bucket.max_txns, n_chunks)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._build_and_record(bucket, n_chunks)
            self._programs[key] = prog
        return prog

    def _progcache_fingerprint(self) -> str:
        """The sharding-layout half of the progcache key (core/progcache
        `key(mesh=)`): "" for single-device engines; mesh-backed engines
        override with their device topology so a program compiled against
        one mesh shape is never served to another. The device COUNT of
        the process itself rides `backend_fingerprint()`."""
        return ""

    def _build_and_record(self, bucket: KernelConfig, n_chunks: int,
                          variant: str = "", make=None):
        """Build one program, bump the compile counter, and file the
        build in the compile & memory ledger (core/perfledger.py):
        duration plus the compiled artifact's cost/memory analysis, keyed
        (bucket, search mode, dispatch mode), classified warmup vs
        steady by the flag warmup() holds. `variant` + `make` let an
        engine whose dispatch unit is a PAIR of programs (the mesh
        engine's split scan/exchange) build each half under its own
        progcache key; the default is the engine's one `_make_program`.

        When an on-disk program cache is installed (core/progcache.py)
        the cache is consulted FIRST under the same key: a hit returns
        the deserialized executable with no compile at all — filed as a
        progcache hit, never a compile, so `perf.compiles` and the
        zero-steady-state-compile guard keep their meaning — and a fresh
        compile is stored back so the next restart warms by loading."""
        search_mode = self.perf.search_modes.get(
            bucket.max_txns, ck.resolved_history_search(bucket))
        cache = progcache.active()
        key = None
        if cache is not None:
            key = cache.key(engine=self.name, bucket=bucket.max_txns,
                            n_chunks=n_chunks, search_mode=search_mode,
                            dispatch_mode=self.dispatch_mode,
                            mesh=self._progcache_fingerprint(),
                            variant=variant,
                            structure=self._history_fingerprint())
            b0 = cache.stats["hit_bytes"]
            t0 = time.perf_counter()
            prog = cache.load(key)
            if prog is not None:
                self.perf_ledger.record_progcache(
                    engine=self.name, bucket=bucket.max_txns,
                    event="hit", nbytes=cache.stats["hit_bytes"] - b0,
                    duration_ms=(time.perf_counter() - t0) * 1e3)
                return prog
            self.perf_ledger.record_progcache(
                engine=self.name, bucket=bucket.max_txns, event="miss")
        t0 = time.perf_counter()
        prog = (make or self._make_program)(bucket, n_chunks)
        self.perf.compiles += 1
        self.perf_ledger.record_compile(
            engine=self.name, bucket=bucket.max_txns, n_chunks=n_chunks,
            search_mode=search_mode,
            dispatch_mode=self.dispatch_mode,
            kind="warmup" if self._warming else "steady",
            duration_ms=(time.perf_counter() - t0) * 1e3,
            compiled=prog)
        if cache is not None:
            b0 = cache.stats["store_bytes"]
            t0 = time.perf_counter()
            if cache.store(key, prog):
                self.perf_ledger.record_progcache(
                    engine=self.name, bucket=bucket.max_txns,
                    event="store",
                    nbytes=cache.stats["store_bytes"] - b0,
                    duration_ms=(time.perf_counter() - t0) * 1e3)
        return prog

    def _make_program(self, bucket: KernelConfig, n_chunks: int):
        """Build (and compile) the device program for `n_chunks` stacked
        chunks at `bucket` shapes (1 = plain step, >1 = fused lax.scan)."""
        raise NotImplementedError

    def _warm_program(self, bucket: KernelConfig, n_chunks: int, prog) -> None:
        """Post-build warm hook: AOT-compiled engines need nothing (the
        build IS the compile); jit-based engines execute a no-op batch."""

    def warmup(self, buckets: Optional[Sequence[KernelConfig]] = None,
               scan_sizes: Optional[Sequence[int]] = None) -> "RoutedConflictEngineBase":
        """Eagerly compile every (bucket, scan-size) program the serving
        path can dispatch, so steady state never hits a compile stall.
        Idempotent; returns self for chaining."""
        t0 = time.perf_counter()
        self._warming = True
        try:
            for b in (buckets if buckets is not None else self.buckets):
                for c in (1,) + tuple(scan_sizes if scan_sizes is not None
                                      else self._scan_sizes):
                    self._warm_program(b, c, self._program(b, c))
        finally:
            self._warming = False
        self.perf.warmup_ms += (time.perf_counter() - t0) * 1e3
        self.perf.warmed = True
        return self

    def ensure_warm(self, used_only: bool = True) -> None:
        """(Re-)warm program coverage — after a fault-path rebuild, only
        the buckets actually serving traffic (fault/resilient.py re-warm);
        a stream that used no bucket yet warms nothing (its first dispatch
        compiles lazily, and the next rebuild sees the hit counts)."""
        if not used_only:
            self.warmup()
            return
        used = [b for b in self.buckets
                if self.perf.bucket_hits.get(b.max_txns, 0) > 0]
        if used:
            self.warmup(buckets=used)

    def _split_run(self, n: int) -> List[int]:
        """Decompose a run of n same-bucket chunks into dispatchable scan
        lengths (largest precompiled size first, singles as remainder)."""
        out: List[int] = []
        for c in sorted(self._scan_sizes, reverse=True):
            while n >= c:
                out.append(c)
                n -= c
        out.extend([1] * n)
        return out

    # -- subclass interface -------------------------------------------------
    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        """Fused detect+fix+apply (the fast path; no host tier involved)."""
        raise NotImplementedError

    def _dispatch_unit(self, bucket: KernelConfig,
                       per_chunks: List[List[Dict[str, np.ndarray]]]):
        """Dispatch C = len(per_chunks) same-bucket chunks as ONE device
        program (C > 1: the fused lax.scan) via JAX ASYNC dispatch —
        nothing is forced to the host. Returns force() -> (status [C, T]
        np.ndarray, overflow bool), which blocks on the device values.

        The dispatched program may still be reading the chunks' host
        arrays — CPU-backend jax aliases well-aligned numpy inputs
        ZERO-COPY — so implementations must keep whatever the program
        reads referenced until force() ran (closure capture), and callers
        must not recycle arena buffers earlier (columnar_dispatch releases
        leases inside force()). The default runs the synchronous per-chunk
        step (no overlap) — device engines override."""
        results = [self._run_step(per) for per in per_chunks]
        status = np.stack([np.asarray(s) for s, _ in results])
        overflow = any(bool(o) for _, o in results)
        return lambda: (status, overflow)

    # -- sampled device timing (docs/observability.md "Performance
    # -- observatory") -------------------------------------------------------
    def _sample_next_dispatch(self) -> bool:
        """Deterministic 1-in-N sampling decision for the next dispatch
        unit (counter-based — no rng, so sampling can never perturb a
        seeded simulation or the abort stream)."""
        if not self._sample_every:
            return False
        self._dispatch_seq += 1
        return self._dispatch_seq % self._sample_every == 0

    def _sampled_unit(self, bucket: KernelConfig,
                      per_chunks: List[List[Dict[str, np.ndarray]]]):
        """_dispatch_unit, with the sampled fraction of units timed
        enqueue->ready. The measurement rides the EXISTING drain paths —
        a step unit's force() already blocks on its outputs, a loop
        ticket's readiness is already probed non-blockingly — so sampling
        adds two clock reads and no device sync anywhere."""
        if not self._sample_next_dispatch():
            return self._dispatch_unit(bucket, per_chunks)
        return self._dispatch_sampled(bucket, per_chunks)

    def _dispatch_sampled(self, bucket: KernelConfig,
                          per_chunks: List[List[Dict[str, np.ndarray]]]):
        """Step-family implementation: stamp the enqueue, record when the
        unit's force() returns (its outputs just landed). The loop engine
        overrides this to stamp the ticket instead — its results become
        ready in poll()/_finish, long before force() may be called."""
        from ..core.trace import g_spans, span_now

        version = self._heat_version
        t0_span = span_now() if g_spans.enabled else 0.0
        t0 = time.perf_counter()
        unit = self._dispatch_unit(bucket, per_chunks)
        chunks = len(per_chunks)

        def force() -> Tuple[np.ndarray, bool]:
            out = unit()
            self._record_device_sample(bucket.max_txns, chunks, t0, t0_span,
                                       version)
            return out

        return force

    def _record_device_sample(self, bucket_txns: int, chunks: int,
                              t0_wall: float, t0_span: float,
                              version) -> None:
        ms = (time.perf_counter() - t0_wall) * 1e3
        self.perf.record_device_time(bucket_txns, ms, chunks=chunks)
        from ..core.trace import g_spans, span_event, span_now

        if g_spans.enabled:
            # the measured device interval as its own span: the Chrome
            # export renders `track="device"` spans on a separate device
            # track next to the host spans (tools/trace_export.py); the
            # segment is registered in ATTRIBUTION_SEGMENTS as an OVERLAY
            # — it overlaps device_dispatch/device_resident, so the
            # attribution excludes it from the partition sum
            span_event("engine.device_time", version, t0_span, span_now(),
                       device_ms=round(ms, 4), bucket=bucket_txns,
                       chunks=chunks, track="device",
                       parent="resolver.queue_wait")

    def _run_detect(self, per_shard: List[Dict[str, np.ndarray]]):
        """Phases 1-2; returns an opaque device context for _run_fix/_run_apply."""
        raise NotImplementedError

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        """Earlier-in-batch-wins fixpoint under an updated t_ok; committed[T]."""
        raise NotImplementedError

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        """Apply globally-agreed writes; returns (status[T], overflow)."""
        raise NotImplementedError

    def _reset_device_state(self, version_rel: int) -> None:
        raise NotImplementedError

    # -- shared implementation ---------------------------------------------
    def clear(self, version: Version) -> None:
        """reference: clearConflictSet (SkipList.cpp:957-959)."""
        self._reset_device_state(self._rel(version))
        self.tier_map = VersionIntervalMap(version)
        self._tier_has_writes = False

    def _rel(self, v: Version) -> int:
        r = v - self.base
        if r >= 2**30:
            raise error.client_invalid_operation(
                f"version {v} too far beyond base {self.base} for int32 device window"
            )
        return max(r, -1)

    def _packed_empty(self, begin: Key, end: Key) -> bool:
        """True iff a truly non-empty [begin, end) becomes empty under
        endpoint truncation (both endpoints share the window prefix): the
        device would mis-evaluate it as an empty read, so it is tier-only."""
        w = self._window
        a = (begin[:w], min(len(begin), w + 1))
        b = (end[:w], min(len(end), w + 1))
        return a >= b

    def _route_txn(self, tr: CommitTransaction) -> _RoutedTxn:
        S = self.n_shards
        rt = _RoutedTxn([], [], [], [], [0] * S, [0] * S, [0] * S, [0] * S,
                        tr.read_snapshot, [], [], [], [], [])
        w_cap = self._window
        for r in tr.read_conflict_ranges:
            if r.begin >= r.end:
                k = r.begin
                if len(k) > w_cap and not (len(k) == w_cap + 1 and k[-1] == 0):
                    # Long empty read [k, k): the interval strictly below k
                    # borders long keys, whose values only tier-visible
                    # writes (range writes, long points) can set — the tier
                    # answer is exact. The ONE exception is k = s+'\x00'
                    # with a window-sized s: there the below-interval is
                    # {s}, owned by device-side point writes, and packing k
                    # (length window+1) is exact — so that shape routes to
                    # the device below.
                    rt.tier_ereads.append(k)
                    rt.has_long = True
                    continue
                s = self.shards.shard_of_point_below(k)
                rt.rreads.append((s, k, r.end))
                rt.n_rreads[s] += 1
            elif _is_point(r.begin, r.end) and len(r.begin) > w_cap:
                rt.tier_preads.append(r.begin)
                rt.has_long = True
            elif self._packed_empty(r.begin, r.end):
                rt.tier_rreads.append((r.begin, r.end))
                rt.has_long = True
            else:
                # Every non-point range may contain out-of-window keys: the
                # tier answers for those, the device for the in-window rest.
                if not _is_point(r.begin, r.end):
                    rt.tier_rreads.append((r.begin, r.end))
                    if len(r.begin) > w_cap or len(r.end) > w_cap:
                        rt.has_long = True
                # A point range never straddles a shard split (a split key
                # strictly inside [k, k+'\x00') would have to equal k).
                for s, cb, ce in self.shards.shards_of_range(r.begin, r.end):
                    if _is_point(cb, ce):
                        if len(cb) > w_cap:
                            # long split key carved a long point zone:
                            # tier-owned (the full range is in tier_rreads)
                            rt.has_long = True
                            continue
                        rt.preads.append((s, cb))
                        rt.n_preads[s] += 1
                    else:
                        if self._packed_empty(cb, ce):
                            rt.has_long = True
                            continue
                        rt.rreads.append((s, cb, ce))
                        rt.n_rreads[s] += 1
        for w in tr.write_conflict_ranges:
            if w.begin < w.end:
                if _is_point(w.begin, w.end) and len(w.begin) > w_cap:
                    rt.tier_pwrites.append(w.begin)
                    rt.has_long = True
                    continue
                if not _is_point(w.begin, w.end):
                    rt.tier_rwrites.append((w.begin, w.end))
                    if len(w.begin) > w_cap or len(w.end) > w_cap:
                        rt.has_long = True
                for s, cb, ce in self.shards.shards_of_range(w.begin, w.end):
                    if _is_point(cb, ce):
                        if len(cb) > w_cap:
                            rt.has_long = True
                            continue
                        rt.pwrites.append((s, cb))
                        rt.n_pwrites[s] += 1
                    else:
                        if self._packed_empty(cb, ce):
                            # collapses to nothing on device; tier-owned
                            rt.has_long = True
                            continue
                        rt.rwrites.append((s, cb, ce))
                        rt.n_rwrites[s] += 1
        cfg = self.cfg
        if (
            max(rt.n_preads) > cfg.rp
            or max(rt.n_rreads) > cfg.max_reads
            or max(rt.n_pwrites) > cfg.wp
            or max(rt.n_rwrites) > cfg.max_writes
        ):
            raise error.client_invalid_operation(
                "single transaction exceeds device conflict-range capacity"
            )
        return rt

    def resolve(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> List[TransactionCommitResult]:
        if transactions:
            res = self._resolve_columnar(transactions, now, new_oldest)
            if res is not None:
                return res
        cfg = self.cfg
        S = self.n_shards
        routed = [self._route_txn(tr) for tr in transactions]
        results: List[TransactionCommitResult] = []
        i = 0
        ntx = len(transactions)
        caps = (
            ("n_preads", cfg.rp),
            ("n_rreads", cfg.max_reads),
            ("n_pwrites", cfg.wp),
            ("n_rwrites", cfg.max_writes),
        )
        while True:
            # Greedy prefix respecting every shard's device caps.
            j = i
            used = {f: [0] * S for f, _ in caps}
            while j < ntx and (j - i) < cfg.max_txns:
                rt = routed[j]
                if any(
                    used[f][s] + getattr(rt, f)[s] > cap
                    for f, cap in caps
                    for s in range(S)
                ):
                    break
                for f, _ in caps:
                    for s in range(S):
                        used[f][s] += getattr(rt, f)[s]
                j += 1
            last = j >= ntx
            results.extend(self._resolve_chunk(routed[i:j], now, new_oldest if last else 0))
            if last:
                break
            i = j
        if new_oldest > self.oldest_version:
            self.oldest_version = new_oldest
            self.base += max(0, new_oldest - self.base)
        return results

    def _resolve_columnar(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> Optional[List[TransactionCommitResult]]:
        """Columnar fast path = pack + dispatch + force, in one call."""
        plan = self.columnar_pack(transactions, now, new_oldest)
        if plan is None:
            return None
        return self.columnar_dispatch(plan)()

    def columnar_pack(
        self,
        transactions: Sequence[CommitTransaction],
        now: Version,
        new_oldest: Version,
    ) -> Optional[dict]:
        """Host half of the columnar fast path over conflict-wire blocks
        (any shard count): when every range is a short-key POINT row, batch
        assembly is two native passes + numpy (no per-range Python); for
        S > 1 the C pass routes each point row to its owning shard (a point
        range never straddles a split key, so no clipping is needed). Point
        reads of in-window keys never couple with the host long-key tier
        (keypack.py: short-key membership is device-exact), so the fused
        device step is always safe here.

        Returns an opaque plan for columnar_dispatch, or None when
        preconditions fail (the general router must handle the batch).
        Mutates NO engine state, but the packed arrays embed base-relative
        versions: the matching columnar_dispatch must run before any LATER
        batch packs (the ResolverPipeline keeps this ordering)."""
        from ..core.trace import g_spans, span_event, span_now

        cfg = self.cfg
        S = self.n_shards
        ntx = len(transactions)
        if ntx == 0:
            return None
        t_pack = span_now() if g_spans.enabled else 0.0
        blocks = []
        for tr in transactions:
            blk, all_point, max_len = tr.conflict_wire_info()
            if not all_point or max_len > self._window:
                return None  # early out: later txns are not even encoded
            blocks.append(blk)
        if S == 1:
            p1 = wire_pass1(self._window, blocks)
        else:
            p1 = wire_pass1_sharded(
                self._window, blocks, self._splits_blob, self._splits_offs, S)
        if p1 is None:
            return None
        blob, offs, rp_cnt, wp_cnt = p1
        # caps bind per shard (S>1: rp_cnt/wp_cnt are [ntx, S] columns)
        if int(rp_cnt.max()) > cfg.rp or int(wp_cnt.max()) > cfg.wp:
            raise error.client_invalid_operation(
                "single transaction exceeds device conflict-range capacity"
            )
        has_reads = rp_cnt.sum(axis=1) > 0 if S > 1 else rp_cnt > 0
        snaps = np.fromiter(
            (tr.read_snapshot for tr in transactions), np.int64, count=ntx)
        rel = snaps - self.base
        if int(rel.max()) >= 2**30 or now - self.base >= 2**30:
            raise error.client_invalid_operation(
                f"version too far beyond base {self.base} for int32 device window"
            )
        snap_rel = np.maximum(rel, -1).astype(np.int32)
        too_old = (snaps < self.oldest_version) & has_reads
        skip = too_old.astype(np.uint8)
        if S > 1:
            eff_r = np.where(too_old[:, None], 0, rp_cnt).astype(np.int32)
            eff_w = np.where(too_old[:, None], 0, wp_cnt).astype(np.int32)
        else:
            eff_r = np.where(too_old, 0, rp_cnt).astype(np.int32)
            eff_w = np.where(too_old, 0, wp_cnt).astype(np.int32)
        cr = np.cumsum(eff_r, axis=0)
        cw = np.cumsum(eff_w, axis=0)

        now_rel = self._rel(now)
        #: (per_shard_arrays, n_txns, bucket_cfg, arena_lease) per chunk
        chunks: List[Tuple[List[Dict[str, np.ndarray]], int, KernelConfig, Optional[ArenaLease]]] = []
        i = 0
        while i < ntx:
            r0 = cr[i - 1] if i else np.zeros_like(cr[0])
            w0 = cw[i - 1] if i else np.zeros_like(cw[0])
            j = min(i + cfg.max_txns, ntx)
            if S > 1:
                for s in range(S):
                    j = min(
                        j,
                        int(np.searchsorted(cr[:, s], r0[s] + cfg.rp, side="right")),
                        int(np.searchsorted(cw[:, s], w0[s] + cfg.wp, side="right")),
                    )
            else:
                j = min(
                    j,
                    int(np.searchsorted(cr, int(r0) + cfg.rp, side="right")),
                    int(np.searchsorted(cw, int(w0) + cfg.wp, side="right")),
                )
            j = max(j, i + 1)  # a single txn always fits (checked above)
            last = j >= ntx
            gc_rel = (
                self._rel(new_oldest)
                if last and new_oldest > self.oldest_version
                else 0
            )
            # Smallest ladder bucket the chunk fits (per-shard row maxima).
            if S > 1:
                nr = int((cr[j - 1] - r0).max())
                nw = int((cw[j - 1] - w0).max())
            else:
                nr = int(cr[j - 1] - r0)
                nw = int(cw[j - 1] - w0)
            bucket = self.bucket_for(j - i, nr, nw)
            bufs = lease = None
            if self.arena is not None:
                bufs, lease = self.arena.lease(bucket, 1 if S == 1 else S)
            if S == 1:
                per = [wire_chunk_arrays(
                    bucket, blob, offs, i, j, skip, snap_rel, eff_r, now_rel,
                    gc_rel, bufs=bufs,
                )]
            else:
                per = wire_chunk_arrays_sharded(
                    bucket, blob, offs, i, j, skip, snap_rel, eff_r, now_rel,
                    gc_rel, self._splits_blob, self._splits_offs, S, bufs=bufs,
                )
            chunks.append((per, j - i, bucket, lease))
            i = j
        if g_spans.enabled:
            # wall-clock host-pack segment of the engine's columnar fast
            # path, keyed by the batch's commit version like every other
            # commit-path span
            span_event("engine.host_pack", now, t_pack, span_now(), txns=ntx,
                       parent="resolver.queue_wait")
        return {"chunks": chunks, "new_oldest": new_oldest, "now": now,
                "chunk_buckets": [c[2].max_txns for c in chunks]}

    def columnar_dispatch(self, plan: dict):
        """Device half of the columnar fast path: group consecutive
        same-bucket chunks into fused lax.scan dispatch units (one device
        program threading the interval-table state across chunks instead of
        one program per chunk), dispatch every unit via JAX ASYNC dispatch
        (nothing is forced to the host) and advance the host version
        bookkeeping. Returns force() -> List[TransactionCommitResult],
        which blocks on the device values.

        The ResolverPipeline keeps several dispatched batches in flight —
        the host packs batch i+1 while the device still runs batch i — and
        forces them in commit-version order, so abort sets are bit-identical
        to the serial resolve() path (scan order == the per-chunk dispatch
        order on the one device queue either way). One observable
        difference: a boundary-table overflow raises at force() time, after
        any later chunks of the SAME batch were already dispatched (the
        serial path stops at the overflowing chunk); overflow is a fatal
        capacity error in both cases."""
        from ..core.trace import g_spans, span_event, span_now

        chunks = plan["chunks"]
        loop_mode = self.dispatch_mode == "loop"
        #: batch version for heat-attribution labels: _dispatch_unit
        #: closures capture it at dispatch time (cleared after the loop)
        self._heat_version = plan.get("now")
        t_enq = span_now() if g_spans.enabled else 0.0
        #: (unit_force, [n_txns per chunk], [leases per chunk], flight rec)
        outs: List[Tuple[Callable, List[int], List[Optional[ArenaLease]], dict]] = []
        i = 0
        while i < len(chunks):
            bucket = chunks[i][2]
            j = i
            while j < len(chunks) and chunks[j][2] is bucket:
                j += 1
            run = chunks[i:j]
            self.perf.bucket_hits[bucket.max_txns] = (
                self.perf.bucket_hits.get(bucket.max_txns, 0) + len(run))
            self.perf.record_search_mode(bucket.max_txns, len(run))
            self.perf.record_dispatch_mode(self.dispatch_mode, len(run))
            for c in self._split_run(len(run)):
                sub, run = run[:c], run[c:]
                unit = self._sampled_unit(bucket, [ch[0] for ch in sub])
                self.perf.scan_dispatches[c] = (
                    self.perf.scan_dispatches.get(c, 0) + 1)
                rec = self.perf.record_dispatch(
                    bucket.max_txns, c, sum(ch[1] for ch in sub))
                outs.append((unit, [ch[1] for ch in sub],
                             [ch[3] for ch in sub], rec))
            i = j
        self._heat_version = None
        if g_spans.enabled and loop_mode:
            # loop engines: the dispatch loop above only packed queue slots
            # and enqueued async server steps — the queue_enqueue share of
            # what used to be one opaque device_dispatch segment
            span_event("engine.queue_enqueue", plan.get("now"), t_enq,
                       span_now(), units=len(outs),
                       parent="resolver.queue_wait")
        new_oldest = plan["new_oldest"]
        if new_oldest > self.oldest_version:
            self.tier_map.gc(new_oldest)
            self.oldest_version = new_oldest
            self.base += max(0, new_oldest - self.base)
        capacity = self.cfg.capacity

        version = plan.get("now")

        def force() -> List[TransactionCommitResult]:
            from ..core.trace import g_spans, span_event, span_now

            t_force = span_now() if g_spans.enabled else 0.0
            results: List[TransactionCommitResult] = []
            for unit, ns, leases, rec in outs:
                t_unit = time.perf_counter()
                status, overflow = unit()
                # flight record completes when the unit's device values land
                rec["force_ms"] = round(
                    (time.perf_counter() - t_unit) * 1e3, 4)
                if overflow:
                    raise error.conflict_capacity_exceeded(
                        f"a shard's boundary table needs > {capacity} rows"
                    )
                for c, n in enumerate(ns):
                    # abort-cause counters aggregate the verdict split that
                    # was previously only visible per batch in status_of
                    self.perf.record_verdicts(status[c, :n])
                    results.extend(
                        TransactionCommitResult(int(v)) for v in status[c, :n])
                # the unit's outputs are forced: its programs can no longer
                # be reading the chunks' host buffers — recycle them
                for lease in leases:
                    if lease is not None:
                        lease.release()
            if g_spans.enabled:
                # readback segment of the wall-clock engine path: a step
                # engine blocks on device outputs here; a loop engine
                # drains its result ring (ready results decode without a
                # sync — the segment name keeps the two attributable) and
                # attaches its batch-time loop_stats snapshot (queue/ring
                # occupancy + sync accounting, ops/device_loop.py) so the
                # span says whether the ring was backed up
                extra = {}
                if loop_mode:
                    snap_fn = getattr(self, "loop_stats_snapshot", None)
                    if snap_fn is not None:
                        extra["loop_stats"] = snap_fn()
                if self.heat is not None:
                    # hot-key-pressure context rides the readback span, so
                    # a slow batch's trace says whether the keyspace was
                    # hot when it ran (docs/observability.md)
                    extra["heat"] = self.heat.brief()
                span_event(
                    "engine.result_drain" if loop_mode else "engine.force",
                    version, t_force, span_now(), units=len(outs), **extra)
            return results

        return force

    def _resolve_chunk(
        self, routed: Sequence[_RoutedTxn], now: Version, new_oldest: Version
    ) -> List[TransactionCommitResult]:
        cfg = self.cfg
        S = self.n_shards
        n = len(routed)
        assert n <= cfg.max_txns
        # general-router chunks always run the top shape; count its mode
        # picks so the telemetry counters cover the slow path too
        self.perf.record_search_mode(cfg.max_txns, 1)
        self.perf.record_dispatch_mode(self.dispatch_mode, 1)
        self._heat_version = now

        too_old = np.zeros((cfg.max_txns,), bool)
        t_ok = np.zeros((cfg.max_txns,), bool)
        rpk: List[List[bytes]] = [[] for _ in range(S)]
        rps: List[List[int]] = [[] for _ in range(S)]
        rpt: List[List[int]] = [[] for _ in range(S)]
        rb: List[List[bytes]] = [[] for _ in range(S)]
        re_: List[List[bytes]] = [[] for _ in range(S)]
        rs: List[List[int]] = [[] for _ in range(S)]
        rt_: List[List[int]] = [[] for _ in range(S)]
        wpk: List[List[bytes]] = [[] for _ in range(S)]
        wpt: List[List[int]] = [[] for _ in range(S)]
        wb: List[List[bytes]] = [[] for _ in range(S)]
        we: List[List[bytes]] = [[] for _ in range(S)]
        wt: List[List[int]] = [[] for _ in range(S)]
        for t, rt in enumerate(routed):
            is_old = rt.snapshot < self.oldest_version and rt.has_reads()
            too_old[t] = is_old
            t_ok[t] = not is_old
            if is_old:
                continue
            snap = self._rel(rt.snapshot)
            for s, k in rt.preads:
                rpk[s].append(k)
                rps[s].append(snap)
                rpt[s].append(t)
            for s, cb, ce in rt.rreads:
                rb[s].append(cb)
                re_[s].append(ce)
                rs[s].append(snap)
                rt_[s].append(t)
            for s, k in rt.pwrites:
                wpk[s].append(k)
                wpt[s].append(t)
            for s, cb, ce in rt.rwrites:
                wb[s].append(cb)
                we[s].append(ce)
                wt[s].append(t)

        now_rel = self._rel(now)
        gc_rel = self._rel(new_oldest) if new_oldest > self.oldest_version else 0
        per = [
            build_batch_arrays(
                cfg,
                rpk[s], rps[s], rpt[s],
                rb[s], re_[s], rs[s], rt_[s],
                wpk[s], wpt[s],
                wb[s], we[s], wt[s],
                t_ok, too_old, now_rel, gc_rel,
            )
            for s in range(S)
        ]

        chunk_has_long = any(rt.has_long for rt in routed)
        chunk_has_rreads = any(rt.tier_rreads for rt in routed)
        chunk_has_rwrites = any(rt.tier_rwrites for rt in routed)
        # Slow (split-step) path only when verdicts can couple across tiers:
        # long rows present, or range reads that tier-held write history
        # could hit. Range-write-only chunks stay fused and just record.
        slow = chunk_has_long or (self._tier_has_writes and chunk_has_rreads)

        if not slow:
            status, overflow = self._run_step(per)
            if overflow:
                raise error.conflict_capacity_exceeded(
                    f"a shard's boundary table needs > {cfg.capacity} rows"
                )
            results = [TransactionCommitResult(int(v)) for v in status[:n]]
            self.perf.record_verdicts(status[:n])
            if chunk_has_rwrites:
                self._tier_record(routed, results, now, new_oldest)
            elif new_oldest > self.oldest_version:
                self.tier_map.gc(new_oldest)
            return results

        # ---- split-step path: global verdicts BEFORE any writes ----------
        # Tier history hits are t_ok-level aborts; tier intra-batch edges
        # join the device fixpoint through an outer iteration that converges
        # to the oracle's sequential-sweep verdicts (all edges point earlier
        # txn -> later txn, so each round finalizes a growing prefix).
        tier_hist = np.zeros((cfg.max_txns,), bool)
        for t, rt in enumerate(routed):
            if not t_ok[t]:
                continue
            snap = rt.snapshot
            hit = False
            for k in rt.tier_preads:
                if self.tier_map.range_max(k, k + b"\x00") > snap:
                    hit = True
                    break
            if not hit:
                for k in rt.tier_ereads:
                    if self.tier_map.version_strictly_below(k) > snap:
                        hit = True
                        break
            if not hit:
                for b, e in rt.tier_rreads:
                    if self.tier_map.range_max(b, e) > snap:
                        hit = True
                        break
            tier_hist[t] = hit

        # Unconditional tier intra-batch edges (u writes, t reads, u < t);
        # whether an edge blocks depends on u's GLOBAL verdict each round.
        edges: List[Tuple[int, int]] = []
        writes_by_txn: List[List[Tuple[Key, Key]]] = []
        for u, ru in enumerate(routed):
            ws = [(k, k + b"\x00") for k in ru.tier_pwrites] + list(ru.tier_rwrites)
            writes_by_txn.append(ws)
        for t, rt in enumerate(routed):
            if not t_ok[t]:
                continue
            reads = [(k, k + b"\x00") for k in rt.tier_preads] + list(rt.tier_rreads)
            if not reads:
                continue
            for u in range(t):
                if any(rb_ < we_ and wb_ < re__
                       for (rb_, re__) in reads
                       for (wb_, we_) in writes_by_txn[u]):
                    edges.append((u, t))

        ctx = self._run_detect(per)
        cur_abort = tier_hist.copy()
        committed = self._run_fix(ctx, per, t_ok & ~cur_abort)
        for _ in range(n + 1):
            blocked = np.zeros((cfg.max_txns,), bool)
            for u, t in edges:
                if committed[u]:
                    blocked[t] = True
            new_abort = tier_hist | blocked
            if np.array_equal(new_abort, cur_abort):
                break
            cur_abort = new_abort
            committed = self._run_fix(ctx, per, t_ok & ~cur_abort)

        status, overflow = self._run_apply(ctx, per, committed)
        if overflow:
            raise error.conflict_capacity_exceeded(
                f"a shard's boundary table needs > {cfg.capacity} rows"
            )
        results = [TransactionCommitResult(int(v)) for v in status[:n]]
        self.perf.record_verdicts(status[:n])
        self._tier_record(routed, results, now, new_oldest)
        return results

    def _write_lossy_on_device(self, b: Key, e: Key) -> bool:
        """True iff the device's truncated image of write [b, e) loses
        coverage somewhere — only such writes force later range reads onto
        the split-step path (a short-endpoint range write is fully visible
        on device, so device range-maxes already include it)."""
        w = self._window
        if len(b) > w or len(e) > w or self._packed_empty(b, e):
            return True
        for s, cb, ce in self.shards.shards_of_range(b, e):
            if _is_point(cb, ce):
                if len(cb) > w:
                    return True
            elif self._packed_empty(cb, ce):
                return True
        return False

    def _tier_record(self, routed, results, now: Version, new_oldest: Version) -> None:
        """Record COMMITTED tier writes into the host tier map + GC."""
        for t, rt in enumerate(routed):
            if results[t] != TransactionCommitResult.COMMITTED:
                continue
            for k in rt.tier_pwrites:
                self.tier_map.write(k, k + b"\x00", now)
                self._tier_has_writes = True
            for b, e in rt.tier_rwrites:
                self.tier_map.write(b, e, now)
                if not self._tier_has_writes and self._write_lossy_on_device(b, e):
                    self._tier_has_writes = True
        if new_oldest > self.oldest_version:
            self.tier_map.gc(new_oldest)


class SubshardedConflictEngine(RoutedConflictEngineBase):
    """S key-range sub-shards resident on ONE device (vmap over a leading
    axis): the single-chip throughput configuration. Each sub-shard holds a
    pro-rata boundary table, so the step runs S small sorts instead of one
    big one (conflict_kernel.resolve_step_stacked) while the host routes
    rows with the same native sharded passes the mesh engine uses. Verdicts
    are bit-identical to JaxConflictEngine/the oracle."""

    name = "subsharded"

    def __init__(self, cfg: KernelConfig, shards: KeyShardMap,
                 initial_version: Version = 0,
                 ladder: Optional[Sequence[int]] = None,
                 scan_sizes: Sequence[int] = (2, 4, 8),
                 arena: bool = True,
                 history_search: Optional[str] = None,
                 heat_buckets: Optional[int] = None,
                 device_time_sample_rate: Optional[float] = None,
                 history_structure: Optional[str] = None):
        super().__init__(cfg, shards, ladder=ladder, scan_sizes=scan_sizes,
                         arena=arena, history_search=history_search,
                         heat_buckets=heat_buckets,
                         device_time_sample_rate=device_time_sample_rate,
                         history_structure=history_structure)
        cfg = self.cfg   # base resolved the history-search mode into it
        self._reset_device_state(initial_version)
        self.tier_map = VersionIntervalMap(initial_version)
        self._detect = jax.jit(functools.partial(ck.detect_step_stacked, cfg))
        self._fix = jax.jit(functools.partial(ck.fix_step_stacked, cfg))
        self._apply = jax.jit(
            functools.partial(ck.apply_step_stacked, cfg), **donate_state_kwargs())

    def _reset_device_state(self, version_rel: int) -> None:
        per = [
            ck.initial_state(self.cfg, version_rel=version_rel,
                             first_key=self.shards.begins[s])
            for s in range(self.n_shards)
        ]
        self.state = jax.tree.map(lambda *xs: jnp.stack(xs), *per)

    def _device_states_for_snapshot(self):
        return [jax.tree.map(lambda x, s=s: x[s], self.state)
                for s in range(self.n_shards)]

    def _stack(self, per_shard: List[Dict[str, np.ndarray]]):
        return jax.tree.map(
            lambda *xs: jnp.asarray(np.stack([np.asarray(x) for x in xs])),
            *per_shard)

    def _make_program(self, bucket: KernelConfig, n_chunks: int):
        S = self.n_shards
        st = ck.state_struct(self.cfg, stack=(S,))
        if n_chunks == 1:
            fn = functools.partial(ck.resolve_step_stacked, bucket)
            bt = ck.batch_struct(bucket, stack=(S,))
        else:
            fn = functools.partial(ck.resolve_step_stacked_scan, bucket)
            bt = ck.batch_struct(bucket, stack=(n_chunks, S))
        return jax.jit(fn, **donate_state_kwargs()).lower(st, bt).compile()

    def _dispatch_unit(self, bucket: KernelConfig,
                       per_chunks: List[List[Dict[str, np.ndarray]]]):
        C = len(per_chunks)
        prog = self._program(bucket, C)
        if C == 1:
            batch = {k: np.stack([np.asarray(sh[k]) for sh in per_chunks[0]])
                     for k in per_chunks[0][0]}
        else:
            batch = {k: np.stack([np.stack([np.asarray(sh[k]) for sh in pc])
                                  for pc in per_chunks])
                     for k in per_chunks[0][0]}
        self.state, out = prog(self.state, batch)
        status_dev, overflow_dev = out["status"], out["overflow"]
        heat_dev = out.get("heat")           # [S, ...] or [C, S, ...]
        heat_layout = "s" if C == 1 else "cs"
        heat_base, heat_version = self.base, self._heat_version
        keep = batch   # zero-copy keepalive (see _dispatch_unit contract)

        def force() -> Tuple[np.ndarray, bool]:
            status = np.asarray(status_dev)
            overflow = bool(np.any(np.asarray(overflow_dev)))
            if heat_dev is not None:
                self._merge_heat(heat_dev, version=heat_version,
                                 base=heat_base, layout=heat_layout)
            _ = keep   # pinned until the outputs above were forced
            return (status[None] if C == 1 else status), overflow

        return force

    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        status, overflow = self._dispatch_unit(self.cfg, [per_shard])()
        return status[0], overflow

    def _run_detect(self, per_shard):
        batch = self._stack(per_shard)
        hist, edges, wpos = self._detect(self.state, batch)
        return {"batch": batch, "hist": hist, "ovp": edges, "wpos": wpos}

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        committed = self._fix(
            jnp.asarray(t_ok), ctx["hist"], ctx["ovp"], ctx["batch"])
        return np.asarray(committed)

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        cm = jnp.asarray(committed)
        self.state, overflow = self._apply(
            self.state, ctx["batch"], cm, ctx["wpos"])
        status = ck.status_of(np.asarray(ctx["batch"]["t_too_old"])[0], committed)
        return np.asarray(status), bool(overflow)


class JaxConflictEngine(RoutedConflictEngineBase):
    """Single-chip ConflictSet engine backed by the XLA/TPU kernel
    (one shard, plain jit). Same resolve() contract as OracleConflictEngine."""

    name = "jax"

    def __init__(self, cfg: KernelConfig = KernelConfig(), initial_version: Version = 0,
                 ladder: Optional[Sequence[int]] = None,
                 scan_sizes: Sequence[int] = (2, 4, 8),
                 arena: bool = True,
                 history_search: Optional[str] = None,
                 heat_buckets: Optional[int] = None,
                 device_time_sample_rate: Optional[float] = None,
                 history_structure: Optional[str] = None):
        super().__init__(cfg, KeyShardMap([]), ladder=ladder,
                         scan_sizes=scan_sizes, arena=arena,
                         history_search=history_search,
                         heat_buckets=heat_buckets,
                         device_time_sample_rate=device_time_sample_rate,
                         history_structure=history_structure)
        cfg = self.cfg   # base resolved the history-search mode into it
        self.state = ck.initial_state(cfg, version_rel=initial_version)
        self.tier_map = VersionIntervalMap(initial_version)
        # Split-step programs for the long-key tier path, compiled lazily
        # (short-key-only workloads never pay for them).
        self._detect = jax.jit(functools.partial(ck.detect_step, cfg))
        self._fix = jax.jit(functools.partial(ck.fix_step, cfg))
        self._apply = jax.jit(functools.partial(ck.apply_step, cfg), **donate_state_kwargs())

    def _reset_device_state(self, version_rel: int) -> None:
        self.state = ck.initial_state(self.cfg, version_rel=version_rel)

    def _device_states_for_snapshot(self):
        return [self.state]

    def _make_program(self, bucket: KernelConfig, n_chunks: int):
        st = ck.state_struct(bucket)
        if n_chunks == 1:
            fn = functools.partial(ck.resolve_step, bucket)
            bt = ck.batch_struct(bucket)
        else:
            fn = functools.partial(ck.resolve_step_scan, bucket)
            bt = ck.batch_struct(bucket, stack=(n_chunks,))
        # AOT: .lower().compile() eagerly; the stored executable can never
        # re-trace or re-compile, so a warmed ladder is compile-stall-proof
        # by construction.
        return jax.jit(fn, **donate_state_kwargs()).lower(st, bt).compile()

    def _dispatch_unit(self, bucket: KernelConfig,
                       per_chunks: List[List[Dict[str, np.ndarray]]]):
        C = len(per_chunks)
        prog = self._program(bucket, C)
        if C == 1:
            (batch,) = per_chunks[0]
        else:
            batch = {k: np.stack([pc[0][k] for pc in per_chunks])
                     for k in per_chunks[0][0]}
        self.state, out = prog(self.state, batch)
        status_dev, overflow_dev = out["status"], out["overflow"]
        heat_dev = out.get("heat")           # unstacked or [C, ...]
        heat_layout = "" if C == 1 else "c"
        heat_base, heat_version = self.base, self._heat_version
        keep = batch   # zero-copy keepalive (see _dispatch_unit contract)

        def force() -> Tuple[np.ndarray, bool]:
            status = np.asarray(status_dev)
            overflow = bool(np.any(np.asarray(overflow_dev)))
            if heat_dev is not None:
                self._merge_heat(heat_dev, version=heat_version,
                                 base=heat_base, layout=heat_layout)
            _ = keep   # pinned until the outputs above were forced
            return (status[None] if C == 1 else status), overflow

        return force

    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        status, overflow = self._dispatch_unit(self.cfg, [per_shard])()
        return status[0], overflow

    def _run_detect(self, per_shard):
        (arrays,) = per_shard
        batch = {k: jnp.asarray(v) for k, v in arrays.items()}
        hist, ovp, wpos = self._detect(self.state, batch)
        return {"batch": batch, "hist": hist, "ovp": ovp, "wpos": wpos}

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        committed = self._fix(jnp.asarray(t_ok), ctx["hist"], ctx["ovp"], ctx["batch"])
        return np.asarray(committed)

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        batch = ctx["batch"]
        cm = jnp.asarray(committed)
        self.state, overflow = self._apply(self.state, batch, cm, ctx["wpos"])
        status = ck.status_of(np.asarray(batch["t_too_old"]), committed)
        return np.asarray(status), bool(overflow)


#: the engine-mode router: every device-backed ConflictSet family by its
#: serving mode — "jax" (single chip, step dispatch), "subsharded" (S
#: key-range sub-shards on one device), "sharded" (multi-chip mesh, jit
#: + blocking force), "mesh" (multi-chip mesh, AOT split scan/exchange
#: with the overlapped result-ring drain; parallel/mesh_engine.py),
#: "device_loop" (single chip, device-resident server loop;
#: ops/device_loop.py). make_engine resolves lazily so importing this
#: module never pulls the mesh or loop machinery.
ENGINE_MODES = ("jax", "subsharded", "sharded", "mesh", "device_loop")


def default_engine_mode() -> str:
    """The single-chip mode the `resolver_device_loop` knob selects:
    "device_loop" when the knob is set, else "jax" (step dispatch)."""
    from .device_loop import device_loop_requested

    return "device_loop" if device_loop_requested() else "jax"


def make_engine(mode: str, cfg: KernelConfig, **kw):
    """Registry entry point: build the engine family `mode` names.
    Sharded families take their KeyShardMap via kw (`shards=`)."""
    if mode == "jax":
        return JaxConflictEngine(cfg, **kw)
    if mode == "subsharded":
        return SubshardedConflictEngine(cfg, **kw)
    if mode == "sharded":
        from ..parallel.sharding import ShardedConflictEngine

        return ShardedConflictEngine(cfg, **kw)
    if mode == "mesh":
        from ..parallel.mesh_engine import MeshShardedConflictEngine

        return MeshShardedConflictEngine(cfg, **kw)
    if mode == "device_loop":
        from .device_loop import DeviceLoopEngine

        return DeviceLoopEngine(cfg, **kw)
    raise ValueError(
        f"unknown engine mode {mode!r}; expected one of {ENGINE_MODES}")
