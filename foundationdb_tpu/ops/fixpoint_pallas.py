"""Fused Pallas kernel for the earlier-in-batch-wins commit fixpoint.

The XLA while_loop version (conflict_kernel.commit_fixpoint) is launch-
overhead-bound: ~20 small fused kernels per iteration at ~15us each, ~5.4
iterations at the bench shape — ~1.6ms of the 4.5ms step. This module runs
the ENTIRE fixpoint as ONE Pallas program, with every per-iteration
gather/scatter reformulated as vectorizable word sweeps (TPUs have no
vector gather):

  committed mask c      [1, T/32] i32 bit words
  c[txn] per row        word sweep: for each word w, broadcast the scalar
                        and select rows whose txn lives in w (variable
                        vector shifts extract the bit)
  point-vs-point        rows pre-sorted by (gid, txn, is_write) in XLA;
                        "min committed earlier writer in my key group"
                        becomes an inclusive prefix-max over
                        gid*2 + committed_write_bit (log-step doubling) —
                        no scatter, no segment boundaries
  blocked per txn       word sweep + OR-reduce-by-doubling over the
                        concatenated hit rows
  range-row edges       the bit-packed ovw/ovrp blocks stored as per-word
                        [rows/128, 128] planes; per-word scalar AND sweeps

Verdict parity: every operation is integer and order-insensitive; the
fixpoint iterates the same monotone function from the same start, so the
committed set is bit-identical to the XLA path (asserted by tests on the
interpreter and by the bench's parity gate on hardware).

Used by the single-device engines only: the mesh (multi-chip) engine keeps
the XLA fixpoint, whose per-iteration psum is its collective round.

jax 0.4.3x interpreter note: the Pallas INTERPRETER promotes the result
dtype of integer reductions (`jnp.sum` over int32 lowers through an int64
accumulator), so any reduction feeding the fixpoint's while_loop carry
used to blow up mid-trace with an int32-vs-int64 carry mismatch — the
pre-PR-6 xfail. Every kernel-side reduction below therefore casts back to
I32 explicitly (a no-op on the compiled TPU path, where the reductions
already produce int32); the carry entries are pinned to I32 at the loop
boundary for the same reason. That workaround is what lets the fused
kernel run on CPU CI and lets the device-resident loop
(ops/device_loop.py, `resolver_device_loop` knob) bake the Pallas
fixpoint into its loop bodies with an interpreter fallback instead of an
xfail.
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .conflict_kernel import KernelConfig

I32 = jnp.int32
NEG = -(2**31) + 1


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def supported(cfg: KernelConfig) -> bool:
    """Shapes/encodings the kernel handles; callers fall back to XLA
    otherwise."""
    T = cfg.max_txns
    if T % 32:
        return False
    # the prefix-max trick needs gid*2+1 in i32 (the point-row sort is
    # 2-operand, so gid and txn never share an encoding)
    if 2 * (cfg.gid_space + 2) >= 2**31:
        return False
    return True


def _pack_bits_words(bits: jnp.ndarray, tw: int) -> jnp.ndarray:
    """[T] bool -> [1, tw] i32 bit words (bit t -> word t>>5, bit t&31)."""
    b = bits.astype(jnp.uint32).reshape(tw, 32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return lax.bitcast_convert_type(
        jnp.sum(b * weights, axis=1, dtype=jnp.uint32), I32).reshape(1, tw)


def _rows(x: jnp.ndarray, nrows: int, fill) -> jnp.ndarray:
    """Pad a flat [n] i32 array to [nrows, 128] (row-major)."""
    n = x.shape[0]
    pad = nrows * 128 - n
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
    return x.reshape(nrows, 128)


def _prep(cfg: KernelConfig, t_ok, hist_hits, edges, batch):
    """XLA-side preparation: one 1-operand sort + packing into the kernel's
    row-plane layout. Returns (operand list, static dims dict)."""
    T = cfg.max_txns
    TW = T // 32
    Rp, Wp = cfg.rp, cfg.wp
    Rr, Wr = cfg.max_reads, cfg.max_writes
    P = Rp + Wp
    PR = _cdiv(P, 128)
    RALL = cfg.r_all
    RA = _cdiv(RALL, 128)
    RRR = _cdiv(Rr, 128)
    WRR = _cdiv(Wr, 128)
    WPR = _cdiv(Wp, 128)
    WRW = cfg.wr_words
    WPW = cfg.wp_words

    base = t_ok & ~(hist_hits > 0)
    base_words = _pack_bits_words(base, TW)

    # ---- point rows sorted by (gid, txn, is_write), 2-operand sort ----
    # (gid and txn*2+isw as separate keys: a packed single-key encoding
    # capped T*gid_space at 2^30 and locked the big weak-scaled shard
    # shapes out of the kernel)
    gid = jnp.concatenate([edges["gid_rp"], edges["gid_wp"]])
    txn = jnp.concatenate([batch["rp_txn"], batch["wp_txn"]])
    isw = jnp.concatenate([
        jnp.zeros((Rp,), I32), jnp.ones((Wp,), I32)])
    valid = jnp.concatenate([batch["rp_valid"], batch["wp_valid"]])
    key1 = jnp.where(valid, gid, jnp.int32(2**30) + jnp.arange(P, dtype=I32))
    key2 = jnp.where(valid, txn * 2 + isw, 0)
    skey, srem = lax.sort((key1, key2), num_keys=2)
    s_valid = skey < 2**30
    s_txn = srem >> 1
    s_isw = srem & 1
    s_gid2 = jnp.where(s_valid, skey * 2, 0)
    pp_gid2 = _rows(s_gid2, PR, 0)
    pp_isw = _rows(jnp.where(s_valid, s_isw, 0), PR, 0)
    pp_isread = _rows((s_valid & (s_isw == 0)).astype(I32), PR, 0)
    pp_word = _rows(jnp.where(s_valid, s_txn >> 5, TW), PR, TW)
    pp_shift = _rows(jnp.where(s_valid, s_txn & 31, 0), PR, 0)

    # ---- gather table: [pp ; range-writes ; point-writes] ----
    wr_word = jnp.where(batch["w_valid"], batch["w_txn"] >> 5, TW)
    wr_shift = jnp.where(batch["w_valid"], batch["w_txn"] & 31, 0)
    wp_word = jnp.where(batch["wp_valid"], batch["wp_txn"] >> 5, TW)
    wp_shift = jnp.where(batch["wp_valid"], batch["wp_txn"] & 31, 0)
    gword = jnp.concatenate(
        [pp_word, _rows(wr_word, WRR, TW), _rows(wp_word, WPR, TW)])
    gshift = jnp.concatenate(
        [pp_shift, _rows(wr_shift, WRR, 0), _rows(wp_shift, WPR, 0)])

    # ---- scatter table: [pp ; all-reads rows ; range-read rows] ----
    rall_txn = jnp.concatenate([batch["rp_txn"], batch["r_txn"]])
    rall_valid = jnp.concatenate([batch["rp_valid"], batch["r_valid"]])
    ra_word = jnp.where(rall_valid, rall_txn >> 5, TW)
    ra_shift = jnp.where(rall_valid, rall_txn & 31, 0)
    rr_word = jnp.where(batch["r_valid"], batch["r_txn"] >> 5, TW)
    rr_shift = jnp.where(batch["r_valid"], batch["r_txn"] & 31, 0)
    sword = jnp.concatenate(
        [pp_word, _rows(ra_word, RA, TW), _rows(rr_word, RRR, TW)])
    sshift = jnp.concatenate(
        [pp_shift, _rows(ra_shift, RA, 0), _rows(rr_shift, RRR, 0)])

    # ---- edge planes: per packed word, a [rows, 128] plane ----
    ovw = lax.bitcast_convert_type(edges["ovw"], I32)        # [RALL, WRW]
    ovwp = jnp.transpose(ovw)                                # [WRW, RALL]
    pad = RA * 128 - RALL
    if pad:
        ovwp = jnp.concatenate(
            [ovwp, jnp.zeros((WRW, pad), I32)], axis=1)
    ovw_planes = ovwp.reshape(WRW * RA, 128)
    ovrp = lax.bitcast_convert_type(edges["ovrp"], I32)      # [Rr, WPW]
    ovrpp = jnp.transpose(ovrp)                              # [WPW, Rr]
    pad = RRR * 128 - Rr
    if pad:
        ovrpp = jnp.concatenate(
            [ovrpp, jnp.zeros((WPW, pad), I32)], axis=1)
    ovrp_planes = ovrpp.reshape(WPW * RRR, 128)

    dims = dict(T=T, TW=TW, PR=PR, RA=RA, RRR=RRR, WRR=WRR, WPR=WPR,
                WRW=WRW, WPW=WPW)
    ops = [base_words, pp_gid2, pp_isw, pp_isread, gword, gshift,
           sword, sshift, ovw_planes, ovrp_planes]
    return ops, dims


def _or_reduce_scalar(x: jnp.ndarray) -> jnp.ndarray:
    """OR of every element of a 2D i32 array, by doubling (rank-0)."""
    x = x.astype(I32)
    r = x.shape[0]
    while r > 1:
        h = r // 2
        if r % 2:
            x = jnp.concatenate([x[:h] | x[h:2 * h], x[2 * h:]], axis=0)
            r = h + 1
        else:
            x = x[:h] | x[h:]
            r = h
    l = x.shape[1]
    while l > 1:
        h = l // 2
        if l % 2:
            x = jnp.concatenate([x[:, :h] | x[:, h:2 * h], x[:, 2 * h:]], axis=1)
            l = h + 1
        else:
            x = x[:, :h] | x[:, h:]
            l = h
    # .astype: the 0.4.3x interpreter's sum accumulates in int64 (see the
    # module docstring); compiled TPU already yields int32, so this is free
    return jnp.sum(x).astype(I32)


def _prefix_max_rowmajor(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive prefix max of a [R, 128] i32 array in row-major order."""
    sh = 1
    while sh < x.shape[1]:
        shifted = jnp.concatenate(
            [jnp.full((x.shape[0], sh), NEG, I32), x[:, :-sh]], axis=1)
        x = jnp.maximum(x, shifted)
        sh *= 2
    carry = jnp.max(x, axis=1, keepdims=True)
    sh = 1
    while sh < x.shape[0]:
        shifted = jnp.concatenate(
            [jnp.full((sh, 1), NEG, I32), carry[:-sh]], axis=0)
        carry = jnp.maximum(carry, shifted)
        sh *= 2
    excl = jnp.concatenate(
        [jnp.full((1, 1), NEG, I32), carry[:-1]], axis=0)
    return jnp.maximum(x, excl)


def _make_kernel(dims):
    T, TW = dims["T"], dims["TW"]
    PR, RA, RRR = dims["PR"], dims["RA"], dims["RRR"]
    WRR, WPR = dims["WRR"], dims["WPR"]
    WRW, WPW = dims["WRW"], dims["WPW"]

    def lane_tw():
        return lax.broadcasted_iota(I32, (1, TW), 1)

    def gather_bits(c, word, shift):
        """bit (c >> txn) per row via a word-broadcast sweep."""
        one = jnp.full((), 1, I32)
        lane = lane_tw()
        acc = jnp.zeros_like(word)
        for w in range(TW):
            cw = jnp.sum(jnp.where(lane == w, c, 0)).astype(I32)
            acc = acc | jnp.where(
                word == w, lax.shift_right_logical(cw, shift) & one, 0)
        return acc

    def scatter_or(hit, word, shift):
        """[rows,128] hit bits -> [1, TW] blocked words."""
        one = jnp.full((), 1, I32)
        lane = lane_tw()
        vals = jnp.where(hit > 0, lax.shift_left(one, shift), 0)
        out = jnp.zeros((1, TW), I32)
        for w in range(TW):
            s = _or_reduce_scalar(jnp.where(word == w, vals, 0))
            out = out | jnp.where(lane == w, s, 0)
        return out

    def pack32(bits):
        """[R,128] 0/1 -> [R,4] packed words (word r*4+j = bits[r,32j:])."""
        parts = []
        one = jnp.full((), 1, I32)
        w32 = lax.shift_left(one, lax.broadcasted_iota(I32, (1, 32), 1))
        for j in range(4):
            sl = bits[:, 32 * j:32 * (j + 1)]
            parts.append(jnp.sum(sl * w32, axis=1, keepdims=True).astype(I32))
        return jnp.concatenate(parts, axis=1)

    def word_scalar(packed, w):
        """Scalar word w out of a [R,4] packed block."""
        r, j = w // 4, w % 4
        return jnp.sum(packed[r:r + 1, j:j + 1]).astype(I32)

    def kernel(base_ref, ppg2_ref, ppisw_ref, ppisread_ref,
               gword_ref, gshift_ref, sword_ref, sshift_ref,
               ovw_ref, ovrp_ref, out_ref):
        base = base_ref[:]
        ppg2 = ppg2_ref[:]
        ppisw = ppisw_ref[:]
        ppisread = ppisread_ref[:]
        gword = gword_ref[:]
        gshift = gshift_ref[:]
        sword = sword_ref[:]
        sshift = sshift_ref[:]
        ovw = ovw_ref[:]
        ovrp = ovrp_ref[:]

        def blocked_words(c):
            g = gather_bits(c, gword, gshift)
            cw_pp = g[0:PR]
            cwr = g[PR:PR + WRR]
            cwp = g[PR + WRR:PR + WRR + WPR]
            # point-vs-point: segmented "any committed earlier writer"
            combined = ppg2 + cw_pp * ppisw
            pm = _prefix_max_rowmajor(combined)
            hit_pp = jnp.where((pm == ppg2 + 1) & (ppisread > 0), 1, 0)
            # reads vs committed RANGE writes
            packed_wr = pack32(cwr)
            hit_w = jnp.zeros((RA, 128), I32)
            for w in range(WRW):
                mv = word_scalar(packed_wr, w)
                plane = ovw[w * RA:(w + 1) * RA]
                hit_w = hit_w | jnp.where((plane & mv) != 0, 1, 0)
            # RANGE reads vs committed point writes
            packed_wp = pack32(cwp)
            hit_rp = jnp.zeros((RRR, 128), I32)
            for w in range(WPW):
                mv = word_scalar(packed_wp, w)
                plane = ovrp[w * RRR:(w + 1) * RRR]
                hit_rp = hit_rp | jnp.where((plane & mv) != 0, 1, 0)
            hits = jnp.concatenate([hit_pp, hit_w, hit_rp], axis=0)
            return scatter_or(hits, sword, sshift).astype(I32)

        def cond(carry):
            c, prev, it = carry
            return jnp.any(c != prev) & (it < T)

        def body(carry):
            c, prev, it = carry
            return base & ~blocked_words(c), c, it + 1

        # carry entries pinned to I32: the interpreter's promoted
        # intermediates must never leak into the while_loop signature
        c0 = base.astype(I32)
        c1 = (base & ~blocked_words(c0)).astype(I32)
        c, _, _ = lax.while_loop(cond, body, (c1, c0, jnp.int32(0)))
        out_ref[:] = c

    return kernel


@functools.lru_cache(maxsize=16)
def _kernel_call(dims_tuple, interpret):
    dims = dict(dims_tuple)
    kernel = _make_kernel(dims)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, dims["TW"]), I32),
        interpret=interpret,
    )


def commit_fixpoint_pallas(
    cfg: KernelConfig,
    t_ok: jnp.ndarray,
    hist_hits: jnp.ndarray,
    edges: Dict[str, jnp.ndarray],
    batch: Dict[str, jnp.ndarray],
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in replacement for commit_fixpoint (single shard only)."""
    ops, dims = _prep(cfg, t_ok, hist_hits, edges, batch)
    call = _kernel_call(tuple(sorted(dims.items())), interpret)
    words = call(*ops)
    T = cfg.max_txns
    t = jnp.arange(T, dtype=I32)
    w = lax.bitcast_convert_type(words.reshape(-1), jnp.uint32)
    bits = (w[t >> 5] >> (t & 31).astype(jnp.uint32)) & 1
    return bits.astype(bool)
