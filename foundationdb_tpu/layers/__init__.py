"""Sample layers: higher-level data models built purely on the
transactional KV API (reference: layers/ — pubsub, bulkload,
containers). Nothing here touches server internals; every structure is
ordinary keys under a Subspace, so they work identically against the
sim cluster and a real one."""
from ._util import read_all
from .bulkload import bulk_load
from .containers import FdbSet, Vector
from .pubsub import PubSub

__all__ = ["PubSub", "bulk_load", "FdbSet", "Vector", "read_all"]
