"""PubSub layer: feeds, inboxes, fan-in on read.

Re-design of layers/pubsub/pubsub.py (315 LoC): feeds publish an ordered
message log; inboxes subscribe to feeds and read by MERGING the
subscribed logs past a per-feed watermark — messages are written once
(no fan-out amplification on post) and delivery state is one watermark
key per (inbox, feed) edge.

Layout under the layer's subspace:
    ("feed", feed)                        -> b""        (existence)
    ("msg",  feed, seq)                   -> payload
    ("next", feed)                        -> str(seq)   (allocator)
    ("sub",  inbox, feed)                 -> b""        (edge)
    ("mark", inbox, feed)                 -> str(seq)   (read watermark)
    ("rot",  inbox)                       -> feed       (fairness cursor)
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..bindings.fdb_api import Subspace
from ._util import read_all


class PubSub:
    def __init__(self, subspace: Optional[Subspace] = None):
        self.ss = subspace if subspace is not None else Subspace((b"pubsub",))

    # -- feeds ---------------------------------------------------------------
    async def create_feed(self, tr, feed: bytes) -> None:
        tr.set(self.ss.pack(("feed", feed)), b"")

    async def post(self, tr, feed: bytes, payload: bytes) -> int:
        """Append to the feed's log; returns the message's sequence."""
        if await tr.get(self.ss.pack(("feed", feed))) is None:
            raise KeyError(f"no such feed: {feed!r}")
        nk = self.ss.pack(("next", feed))
        seq = int(await tr.get(nk) or b"0")
        tr.set(nk, b"%d" % (seq + 1))
        tr.set(self.ss.pack(("msg", feed, seq)), payload)
        return seq

    async def feed_messages(self, tr, feed: bytes,
                            limit: int = 100) -> List[bytes]:
        lo, hi = self.ss.range(("msg", feed))
        return [v for _k, v in await tr.get_range(lo, hi, limit=limit)]

    # -- inboxes -------------------------------------------------------------
    async def subscribe(self, tr, inbox: bytes, feed: bytes) -> None:
        if await tr.get(self.ss.pack(("feed", feed))) is None:
            raise KeyError(f"no such feed: {feed!r}")
        tr.set(self.ss.pack(("sub", inbox, feed)), b"")

    async def unsubscribe(self, tr, inbox: bytes, feed: bytes) -> None:
        tr.clear(self.ss.pack(("sub", inbox, feed)))
        tr.clear(self.ss.pack(("mark", inbox, feed)))

    async def subscriptions(self, tr, inbox: bytes) -> List[bytes]:
        lo, hi = self.ss.range(("sub", inbox))
        return [self.ss.unpack(k)[2] for k, _v in await read_all(tr, lo, hi)]

    async def fetch(self, tr, inbox: bytes,
                    limit: int = 100) -> List[Tuple[bytes, int, bytes]]:
        """Unread (feed, seq, payload) across every subscribed feed,
        advancing each feed's watermark past what was returned. The start
        feed rotates each call so a busy lexicographically-early feed
        can't eat the whole limit forever and starve the rest."""
        feeds = await self.subscriptions(tr, inbox)
        if not feeds:
            return []
        rk = self.ss.pack(("rot", inbox))
        cursor = await tr.get(rk)
        i = feeds.index(cursor) if cursor in feeds else 0
        out: List[Tuple[bytes, int, bytes]] = []
        for feed in feeds[i:] + feeds[:i]:
            mk = self.ss.pack(("mark", inbox, feed))
            mark = int(await tr.get(mk) or b"0")
            lo = self.ss.pack(("msg", feed, mark))
            _, hi = self.ss.range(("msg", feed))
            rows = await tr.get_range(lo, hi, limit=limit - len(out))
            for k, v in rows:
                seq = self.ss.unpack(k)[2]
                out.append((feed, seq, v))
            if rows:
                tr.set(mk, b"%d" % (self.ss.unpack(rows[-1][0])[2] + 1))
            if len(out) >= limit:
                break
        if out:
            # rotate only when something was delivered: an empty poll
            # stays a read-only transaction (no cursor write, no commit)
            tr.set(rk, feeds[(i + 1) % len(feeds)])
        return out
