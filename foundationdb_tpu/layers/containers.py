"""Container layers: Vector and Set over tuple-packed keys.

Re-design of layers/containers/{vector.py,set.py}: each container is a
Subspace; elements are individual keys, so every operation is a handful
of point reads/writes and containers of any size never rewrite
themselves. A sparse Vector stores only set indices (size = last index
+ 1, reads of holes return the default), matching the reference
vector's sparse representation."""
from __future__ import annotations

from typing import Any, List, Optional

from ..bindings.fdb_api import Subspace
from ._util import read_all


class Vector:
    """Sparse vector: (index,) -> value under the subspace; size derives
    from the last populated index."""

    def __init__(self, subspace: Subspace, default: bytes = b""):
        self.ss = subspace
        self.default = default

    async def size(self, tr) -> int:
        lo, hi = self.ss.range()
        rows = await tr.get_range(lo, hi, limit=1, reverse=True)
        if not rows:
            return 0
        return self.ss.unpack(rows[0][0])[0] + 1

    async def get(self, tr, index: int) -> bytes:
        v = await tr.get(self.ss.pack((index,)))
        return self.default if v is None else v

    def set(self, tr, index: int, value: bytes) -> None:
        tr.set(self.ss.pack((index,)), value)

    async def push(self, tr, value: bytes) -> int:
        i = await self.size(tr)
        tr.set(self.ss.pack((i,)), value)
        return i

    async def pop(self, tr) -> Optional[bytes]:
        """Remove and return the back element; size shrinks by EXACTLY
        one — when the new back is a hole, the default is materialized
        there so trailing holes don't collapse with it."""
        n = await self.size(tr)
        if n == 0:
            return None
        back = self.ss.pack((n - 1,))
        v = await tr.get(back)
        tr.clear(back)
        if n >= 2:
            new_back = self.ss.pack((n - 2,))
            if await tr.get(new_back) is None:
                tr.set(new_back, self.default)
        return self.default if v is None else v

    async def items(self, tr, max_items: int = 1_000_000) -> List[bytes]:
        """Dense read-out: holes filled with the default. One far-flung
        sparse index implies size() entries of output, so the
        materialized length is capped — raise rather than OOM."""
        n = await self.size(tr)
        if n > max_items:
            raise ValueError(
                f"dense read of {n} logical elements exceeds "
                f"max_items={max_items}; read the sparse keys instead")
        lo, hi = self.ss.range()
        rows = await read_all(tr, lo, hi)
        out: List[bytes] = []
        for k, v in rows:
            i = self.ss.unpack(k)[0]
            out.extend(self.default for _ in range(i - len(out)))
            out.append(v)
        return out


class FdbSet:
    """Unordered set of tuple-encodable members; one key per member."""

    def __init__(self, subspace: Subspace):
        self.ss = subspace

    def add(self, tr, member: Any) -> None:
        tr.set(self.ss.pack((member,)), b"")

    def discard(self, tr, member: Any) -> None:
        tr.clear(self.ss.pack((member,)))

    async def contains(self, tr, member: Any) -> bool:
        return await tr.get(self.ss.pack((member,))) is not None

    async def members(self, tr) -> List[Any]:
        lo, hi = self.ss.range()
        return [self.ss.unpack(k)[0] for k, _v in await read_all(tr, lo, hi)]
