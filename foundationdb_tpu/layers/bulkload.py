"""Bulk loader: parallel chunked imports through ordinary transactions.

Re-design of layers/bulkload/bulk.py: split a row stream into bounded
batches and commit them with N concurrent worker actors, each batch one
transaction (so a retried batch is idempotent — blind sets). Rows in one
batch share a commit version; batches land independently, which is the
point: aggregate throughput scales with workers until the proxies'
batch pipeline saturates, not with any single txn's latency."""
from __future__ import annotations

from typing import Iterable, List, Tuple

from ..sim.actors import all_of_cancelling
from ..sim.loop import spawn


async def bulk_load(db, rows: Iterable[Tuple[bytes, bytes]],
                    batch_size: int = 100, workers: int = 4) -> int:
    """Write every (key, value); returns the row count."""
    batches: List[List[Tuple[bytes, bytes]]] = [[]]
    for kv in rows:
        if len(batches[-1]) >= batch_size:
            batches.append([])
        batches[-1].append(kv)
    if batches == [[]]:
        return 0
    total = sum(len(b) for b in batches)
    cursor = iter(batches)

    async def worker() -> None:
        for batch in cursor:   # shared iterator: workers pull next batch
            async def put(tr, b=batch):
                for k, v in b:
                    tr.set(k, v)
            await db.run(put)

    await all_of_cancelling([spawn(worker(), name=f"bulkload-{i}")
                             for i in range(max(1, workers))])
    return total
