"""Shared layer plumbing."""
from __future__ import annotations

from typing import List, Tuple


async def read_all(tr, lo: bytes, hi: bytes,
                   page: int = 1000) -> List[Tuple[bytes, bytes]]:
    """Every (key, value) in [lo, hi), paginated — a bare get_range
    silently truncates at the client's default limit, which breaks any
    layer method presenting itself as a COMPLETE read."""
    out: List[Tuple[bytes, bytes]] = []
    cur = lo
    while True:
        rows = await tr.get_range(cur, hi, limit=page)
        out.extend(rows)
        if len(rows) < page:
            return out
        cur = rows[-1][0] + b"\x00"
