"""Crash-stop recovery: durable resolver restart from the black-box journal.

The reference's defining robustness property is that recovery is the
COMMON case — any process dies at any instant and the cluster
reconverges to bit-identical state. Everything below the process
boundary already survives here (device faults, network chaos, live
resharding), but a `kill -9` of a resolver lost everything above the
durable journal: the interval-table state existed only in the in-memory
shadow. This module closes that gap with the PAM shape (PAPERS.md):
periodic snapshots plus O(delta) journal replay.

  * **Snapshots** (`SnapshotManager`): the supervised engine's committed
    write-history window — the same shadow whose sufficiency argument
    makes failover rebuilds bit-identical (fault/resilient.py) — is
    COALESCED through the handoff pre-copy machinery (fault/handoff.py),
    so a snapshot is bounded by distinct keys, not history length. It is
    wire-serialized, crc-framed (`FBSN` magic) and written atomically
    BESIDE the journal segments (`snap-*.snap`; the journal's
    `bbox-*.seg` globbing never sees them) every
    `resolver_recovery_snapshot_interval` commit versions.

  * **Recovery** (`recover()`): newest readable snapshot (a torn tail
    falls back to the previous one) replays into the fresh supervised
    engine — too-old gate pinned first, then one write-only batch per
    distinct version at its ORIGINAL version, the `_replay_shadow`
    contract — then the journal's batch suffix above the snapshot
    version re-resolves through the engine at original versions. The
    replayed verdicts diff bit-for-bit against the journal's recorded
    ones: a clean run converges to verdict-bit-identical state vs. an
    uninterrupted engine (tests/test_recovery.py pins it across a
    reshard epoch flip).

  * **Honest coverage**: rotation may have eaten the horizon between the
    snapshot and the retained journal head. That is a TYPED degraded
    mode (`from_floor`), not silently-wrong history: the too-old gate is
    pinned at the first retained version, so reads below the missing
    window answer `transaction_too_old` instead of resolving against
    state that cannot be proven (`coverage_ok=False` in the result, the
    forensics diff_replay convention).

The arc lands in the journal itself (`snapshot` / `recovery` events,
core/blackbox.py) — `cli recovery` renders the last recovery from the
durable record — and in a `recovery.blackout` span the crash campaign
(real/nemesis.py --crash) verifies against `resolver_recovery_budget_ms`.
A `RecoveryTracker` registered with the telemetry hub feeds the
watchdog's `recovery_stalled` rule (core/watchdog.py).
"""
from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core import blackbox, progcache, telemetry, wire
from ..core.trace import span_event, span_now
from . import handoff

#: snapshot file header: magic + format version
SNAP_MAGIC = b"FBSN"
SNAP_VERSION = 1
_HEADER = SNAP_MAGIC + bytes([SNAP_VERSION])
#: one crc frame per snapshot: little-endian (payload length, crc32)
_FRAME = struct.Struct("<II")

#: typed recovery modes (RecoveryResult.mode)
MODE_COMPLETE = "complete"      #: snapshot + full suffix — provably exact
MODE_FROM_FLOOR = "from_floor"  #: rotation ate the horizon — gate pinned
MODE_COLD = "cold"              #: nothing durable retained — empty engine


@dataclass
class EngineSnapshot:
    """One coalesced engine-state snapshot (wire-serialized)."""

    version: int = 0      #: newest shadow version captured (recovery floor)
    oldest: int = 0       #: the MVCC too-old gate at capture
    t: float = 0.0
    proc: str = ""
    #: ((version, ((begin, end), ...)), ...) — one write-only batch per
    #: distinct surviving version, ascending (handoff.coalesce output)
    entries: Tuple = ()


wire.register_record(EngineSnapshot)


# -- snapshot files ------------------------------------------------------------

def snapshot_path(directory: str, version: int) -> str:
    return os.path.join(directory, f"snap-{version:014d}.snap")


def snapshot_paths(directory: str) -> List[Tuple[int, str]]:
    """(version, path) for every snapshot file, ascending by version."""
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("snap-") and n.endswith(".snap")]
    except OSError:
        return []
    out = []
    for n in sorted(names):
        try:
            out.append((int(n[len("snap-"):-len(".snap")]),
                        os.path.join(directory, n)))
        except ValueError:
            continue
    return out


def capture(engine, proc: str = "", now_fn=span_now) -> EngineSnapshot:
    """The supervised engine's full shadow window, coalesced to the
    effective interval map (bounded by distinct keys, not history)."""
    entries = handoff.coalesce(
        handoff.shadow_slice(engine, b"", None, 0), b"", None)
    return EngineSnapshot(
        version=int(handoff.last_shadow_version(engine)),
        oldest=int(getattr(engine, "_oldest", 0)),
        t=round(float(now_fn()), 6), proc=proc,
        entries=tuple((int(v), tuple(w)) for v, w in entries))


def write_snapshot(directory: str, snap: EngineSnapshot,
                   disk: Optional[Any] = None) -> Optional[dict]:
    """Serialize `snap` atomically (tmp + rename) beside the journal
    segments. Never raises: a refused write (full disk, injected fault)
    degrades the snapshot cadence, not serving. Returns accounting
    {path, bytes, ms} or None."""
    t0 = time.perf_counter()
    os.makedirs(directory, exist_ok=True)
    try:
        raw = wire.dumps(snap)
    except (ValueError, TypeError):
        return None
    data = _HEADER + _FRAME.pack(len(raw), zlib.crc32(raw)) + raw
    path = snapshot_path(directory, snap.version)
    tmp = path + ".tmp"
    try:
        if disk is not None:
            data = disk.apply("snapshot", data)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        prefix = getattr(e, "prefix", None)
        if prefix:
            # a torn snapshot write leaves the PREFIX at the final path —
            # the nastiest crash shape — which read_snapshot must reject
            # by crc and recovery must survive by falling back
            try:
                with open(path, "wb") as f:
                    f.write(prefix)
            except OSError:
                pass
        try:
            os.remove(tmp)
        except OSError:
            pass
        return None
    return {"path": path, "bytes": len(data),
            "ms": (time.perf_counter() - t0) * 1e3}


def read_snapshot(path: str) -> Optional[EngineSnapshot]:
    """One snapshot file; None for any torn/rotted/alien content (the
    journal reader's crc tolerance, applied to the snapshot frame)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    if len(data) < len(_HEADER) + _FRAME.size or \
            data[:len(_HEADER)] != _HEADER:
        return None
    length, crc = _FRAME.unpack_from(data, len(_HEADER))
    raw = data[len(_HEADER) + _FRAME.size:
               len(_HEADER) + _FRAME.size + length]
    if len(raw) != length or zlib.crc32(raw) != crc:
        return None
    try:
        snap = wire.loads(raw)
    except (ValueError, KeyError, TypeError):
        return None
    return snap if isinstance(snap, EngineSnapshot) else None


def latest_snapshot(directory: str) -> Optional[EngineSnapshot]:
    """The newest READABLE snapshot — a torn tail (crash mid-snapshot)
    falls back to the previous one instead of failing recovery."""
    for _v, path in reversed(snapshot_paths(directory)):
        snap = read_snapshot(path)
        if snap is not None:
            return snap
    return None


class SnapshotManager:
    """Cadenced snapshot writer a serving loop notifies per batch."""

    def __init__(self, directory: str, interval: Optional[int] = None,
                 keep: int = 2, disk: Optional[Any] = None,
                 proc: str = ""):
        from ..core.knobs import SERVER_KNOBS

        self.directory = str(directory)
        self.interval = int(
            interval if interval is not None
            else SERVER_KNOBS.resolver_recovery_snapshot_interval)
        self.keep = max(1, int(keep))
        self.disk = disk
        self.proc = proc
        self._last_version = 0
        self.stats = {"written": 0, "bytes": 0, "errors": 0, "ms": 0.0}

    def note_batch(self, engine, version: int) -> Optional[dict]:
        """Called once per resolved batch; snapshots when the cadence is
        due. Never raises into the serving path."""
        if self.interval <= 0:
            return None
        if int(version) - self._last_version < self.interval:
            return None
        return self.snapshot(engine)

    def snapshot(self, engine) -> Optional[dict]:
        try:
            snap = capture(engine, proc=self.proc)
        except Exception:
            self.stats["errors"] += 1
            return None
        acct = write_snapshot(self.directory, snap, disk=self.disk)
        self._last_version = snap.version
        if acct is None:
            self.stats["errors"] += 1
            return None
        self.stats["written"] += 1
        self.stats["bytes"] += acct["bytes"]
        self.stats["ms"] += acct["ms"]
        blackbox.record_snapshot(snap.version, snap.oldest,
                                 len(snap.entries), acct["bytes"],
                                 acct["ms"], path=acct["path"])
        self._prune()
        return acct

    def _prune(self) -> None:
        paths = snapshot_paths(self.directory)
        while len(paths) > self.keep:
            _v, path = paths.pop(0)
            try:
                os.remove(path)
            except OSError:
                break


# -- recovery ------------------------------------------------------------------

@dataclass
class RecoveryResult:
    """What a restart recovered, honestly typed (`cli recovery` renders
    the journaled copy of exactly these fields)."""

    mode: str = MODE_COLD
    coverage_ok: bool = True
    snapshot_version: int = -1
    recovered_version: int = -1
    oldest: int = 0
    snapshot_entries: int = 0
    replayed_batches: int = 0
    verdict_mismatches: int = 0
    blackout_ms: float = 0.0
    warm_ms: float = 0.0
    progcache_hits: int = 0
    progcache_misses: int = 0
    error: Optional[str] = None
    mismatch_detail: List[dict] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "mode": self.mode, "coverage_ok": self.coverage_ok,
            "snapshot_version": self.snapshot_version,
            "recovered_version": self.recovered_version,
            "oldest": self.oldest,
            "snapshot_entries": self.snapshot_entries,
            "replayed_batches": self.replayed_batches,
            "verdict_mismatches": self.verdict_mismatches,
            "blackout_ms": round(self.blackout_ms, 3),
            "warm_ms": round(self.warm_ms, 3),
            "progcache_hits": self.progcache_hits,
            "progcache_misses": self.progcache_misses,
            "error": self.error,
        }


async def _resolve(engine, transactions, now_v, new_oldest):
    r = engine.resolve(transactions, now_v, new_oldest)
    if hasattr(r, "__await__"):
        r = await r
    return r


async def recover(engine, directory: str,
                  journal_events: Optional[List] = None,
                  warm: bool = True,
                  tracker: Optional["RecoveryTracker"] = None,
                  proc: str = "") -> RecoveryResult:
    """Reconstruct `engine`'s interval-table state from the durable
    directory: newest readable snapshot, then differential replay of the
    journal's batch suffix at original versions. Works on supervised
    (async resolve) and raw (sync resolve) engines. Records the arc into
    the installed journal and as a `recovery.blackout` span."""
    t0 = time.perf_counter()
    wall0 = span_now()
    if tracker is not None:
        tracker.begin()
    res = RecoveryResult()
    try:
        snap = latest_snapshot(directory)
        events = (journal_events if journal_events is not None
                  else blackbox.read_journal(directory))
        batches = [e for e in events if e.kind == "batch"]
        complete = bool(events) and min(e.seq for e in events) == 0

        engine.clear(0)
        snap_v = -1
        if snap is not None:
            snap_v = int(snap.version)
            res.snapshot_version = snap_v
            res.snapshot_entries = len(snap.entries)
            res.oldest = int(snap.oldest)
            if snap.oldest > 0:
                # pin the too-old gate FIRST (the _replay_shadow order):
                # replayed reads must face the same horizon they did live
                await _resolve(engine, [], snap.oldest, snap.oldest)
            await handoff.replay_slice(engine, list(snap.entries))

        suffix = [e for e in batches if int(e.payload.version) > snap_v]
        # rotation ate the horizon when the retained journal neither
        # reaches back to its own birth (seq 0) nor overlaps the
        # snapshot version (the diff_replay convention) — a typed
        # degraded mode, never silently-wrong history
        gap = (not complete and bool(batches)
               and (snap is None
                    or int(batches[0].payload.version) > snap_v))
        if gap and suffix:
            floor_v = int(suffix[0].payload.version)
            res.mode = MODE_FROM_FLOOR
            res.coverage_ok = False
            res.oldest = max(res.oldest, floor_v)
            # recover-from-MVCC-floor: everything below the first
            # retained version answers transaction_too_old rather than
            # resolving against unprovable history
            await _resolve(engine, [], floor_v, floor_v)
        elif snap is not None or suffix:
            res.mode = MODE_COMPLETE
        for e in suffix:
            p = e.payload
            got = [int(x) for x in await _resolve(
                engine, list(p.txns), int(p.version), int(p.new_oldest))]
            want = [int(x) for x in p.verdicts]
            res.replayed_batches += 1
            res.recovered_version = int(p.version)
            if got != want:
                res.verdict_mismatches += 1
                if len(res.mismatch_detail) < 8:
                    res.mismatch_detail.append(
                        {"version": int(p.version), "got": got,
                         "want": want})
        if res.recovered_version < 0:
            res.recovered_version = snap_v if snap_v >= 0 else 0
        if warm:
            cache = progcache.active()
            h0 = (cache.stats["hits"], cache.stats["misses"]) \
                if cache is not None else (0, 0)
            tw = time.perf_counter()
            fn = getattr(engine, "ensure_warm", None)
            if fn is not None:
                fn(used_only=True)
            else:
                fn = getattr(engine, "warmup", None)
                if fn is not None:
                    fn()
            res.warm_ms = (time.perf_counter() - tw) * 1e3
            if cache is not None:
                res.progcache_hits = cache.stats["hits"] - h0[0]
                res.progcache_misses = cache.stats["misses"] - h0[1]
    except Exception as e:                     # noqa: BLE001 — recovery
        # must fail TYPED (the caller decides cold-start vs. abort),
        # never half-recovered with the error swallowed
        res.error = f"{type(e).__name__}: {e}"
        res.coverage_ok = False
    res.blackout_ms = (time.perf_counter() - t0) * 1e3
    if tracker is not None:
        tracker.end(res)
    span_event("recovery.blackout", None, wall0, span_now(),
               mode=res.mode, snapshot_version=res.snapshot_version,
               replayed=res.replayed_batches,
               blackout_ms=round(res.blackout_ms, 3), proc=proc or None)
    blackbox.record_recovery(res.as_dict())
    return res


# -- the watchdog's eyes -------------------------------------------------------

class RecoveryTracker:
    """Registered with the telemetry hub (`recovery.<label>.*` series):
    an in-flight recovery's age feeds the watchdog's `recovery_stalled`
    rule, completed arcs feed blackout gauges, and the live tracker
    composes the rule's speakable detail line."""

    def __init__(self, name: str = "recovery", now_fn=span_now):
        self.now_fn = now_fn
        self._started: Optional[float] = None
        self.recoveries = 0
        self.failures = 0
        self.blackout_ms_max = 0.0
        self.last: Optional[dict] = None
        self.label = telemetry.hub().register_recovery(self, name)

    def begin(self) -> None:
        self._started = float(self.now_fn())

    def end(self, res: RecoveryResult) -> None:
        self._started = None
        self.recoveries += 1
        if res.error is not None:
            self.failures += 1
        self.blackout_ms_max = max(self.blackout_ms_max, res.blackout_ms)
        self.last = res.as_dict()

    def in_flight(self) -> bool:
        return self._started is not None

    def in_flight_age_s(self) -> float:
        if self._started is None:
            return 0.0
        return max(0.0, float(self.now_fn()) - self._started)

    def in_flight_detail(self) -> str:
        if self._started is None:
            return ""
        return f"recovery in flight for {self.in_flight_age_s():.2f}s"
