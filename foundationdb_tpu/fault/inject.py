"""Deterministic device-fault injection at the conflict-engine boundary.

The analog of the reference's machine-level fault injection
(sim2.actor.cpp's AsyncFileNonDurable, clogging, kills) applied to OUR
new failure domain: the accelerator dispatch. A FaultInjectingEngine
wraps any conflict engine and, from its own seeded rng (one draw off the
simulation stream at construction, so per-dispatch draws never perturb
the rest of the world), injects the fault menagerie a real TPU serving
path sees:

  * dispatch exceptions   — XLA runtime errors, transfer failures;
  * hangs                 — a dispatch that never completes (the watchdog
                            in fault/resilient.py must fire);
  * slow batches          — stragglers that complete late;
  * outages               — bursty windows (the preemption model) where
                            EVERY dispatch fails until the device returns;
  * flipped verdict bits  — silent corruption (off by default: an escaped
                            flip is data loss; the supervisor's sampled
                            probe exists to catch exactly this).

Faults that surface after the inner engine ran (`applied_fraction`) model
the nastiest shape: the dispatch landed on the device, only the reply was
lost — device state holds the batch, the host does not know. The
supervisor must re-warm device state before any retry or the batch's own
writes would alias into its history and change verdicts.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..core import error
from ..core.rng import DeterministicRandom
from ..core.types import TransactionCommitResult
from ..sim.loop import TaskPriority, current_scheduler, delay, never, now


@dataclass
class FaultRates:
    """Per-dispatch fault probabilities (the nemesis campaign's defaults).

    The acceptance bar (ISSUE 2) runs exceptions, hangs and slow batches at
    these rates; `flip` defaults to 0 because a flipped verdict that the
    sampled probe misses is emitted — corruption coverage lives in the
    supervisor unit tests with probe_rate=1, not in cluster sims."""

    exception: float = 0.01
    hang: float = 0.008
    slow: float = 0.04
    flip: float = 0.0
    #: probability of entering a bursty outage window in which every
    #: dispatch faults until it expires (TPU preemption / runtime restart)
    outage: float = 0.02
    #: outage length in virtual seconds, uniform in [0.5x, 1.5x]
    outage_seconds: float = 1.5
    #: mean straggler delay, uniform in [0.5x, 1.5x]
    slow_seconds: float = 0.2
    #: fraction of exception/hang faults where the inner engine RAN before
    #: the fault surfaced (dispatch landed, reply lost)
    applied_fraction: float = 0.5


class FaultInjectingEngine:
    """Seed-driven fault wrapper over any ConflictSet engine."""

    name = "fault-injecting"

    def __init__(self, inner, rates: Optional[FaultRates] = None,
                 rng: Optional[DeterministicRandom] = None):
        self.inner = inner
        self.rates = rates or FaultRates()
        if rng is None:
            rng = DeterministicRandom(
                current_scheduler().rng.random_int(0, 2**31 - 1))
        self.rng = rng
        self.injected = {"exceptions": 0, "hangs": 0, "slow": 0, "flips": 0,
                         "outages": 0}
        self._outage_until = 0.0

    # -- engine interface ----------------------------------------------------
    def clear(self, version) -> None:
        self.inner.clear(version)

    def rewarm_target(self):
        """State-rebuild bypass: re-warming device state goes through the
        trusted host-side path (a real system DMAs the rebuilt table rather
        than re-running every historical program through the flaky dispatch
        queue). The supervisor still models re-warm failure via its own
        buggify site."""
        return self.inner

    def resolve(self, transactions, now_v, new_oldest):
        """Synchronous dispatch: exceptions and flips only (a sync call
        cannot hang or straggle in zero virtual time)."""
        kind = self._fault_kind()
        if kind in (None, "slow"):
            return self.inner.resolve(transactions, now_v, new_oldest)
        if kind == "flip":
            return self._flipped(transactions, now_v, new_oldest)
        self._maybe_apply(transactions, now_v, new_oldest)
        self.injected["exceptions"] += 1
        raise error.device_fault(f"injected dispatch {kind} at {now_v}")

    async def resolve_async(self, transactions, now_v, new_oldest):
        """Asynchronous dispatch: the full fault menagerie. The supervisor
        awaits this under its watchdog."""
        kind = self._fault_kind()
        if kind is None:
            return self.inner.resolve(transactions, now_v, new_oldest)
        if kind == "slow":
            self.injected["slow"] += 1
            await delay(self.rates.slow_seconds * (0.5 + self.rng.random01()),
                        TaskPriority.PROXY_RESOLVER_REPLY)
            return self.inner.resolve(transactions, now_v, new_oldest)
        if kind == "flip":
            return self._flipped(transactions, now_v, new_oldest)
        applied = self._maybe_apply(transactions, now_v, new_oldest)
        if kind == "hang":
            self.injected["hangs"] += 1
            await never()
        self.injected["exceptions"] += 1
        raise error.device_fault(
            f"injected dispatch exception at {now_v} (applied={applied})")

    # -- internals -----------------------------------------------------------
    def _fault_kind(self) -> Optional[str]:
        r, rng = self.rates, self.rng
        t = now()
        if t < self._outage_until:
            # device down wholesale: nothing completes until it returns
            return "hang" if rng.random01() < 0.5 else "exception"
        if r.outage > 0 and rng.random01() < r.outage:
            self.injected["outages"] += 1
            self._outage_until = t + r.outage_seconds * (0.5 + rng.random01())
            return "exception"
        x = rng.random01()
        for kind, p in (("exception", r.exception), ("hang", r.hang),
                        ("slow", r.slow), ("flip", r.flip)):
            if x < p:
                return kind
            x -= p
        return None

    def _maybe_apply(self, transactions, now_v, new_oldest) -> bool:
        applied = self.rng.random01() < self.rates.applied_fraction
        if applied:
            self.inner.resolve(transactions, now_v, new_oldest)
        return applied

    def _flipped(self, transactions, now_v, new_oldest):
        """Silent corruption: the device computed (and applied) the true
        verdicts; one reported bit flips on the way back."""
        verdicts = list(self.inner.resolve(transactions, now_v, new_oldest))
        if verdicts:
            self.injected["flips"] += 1
            i = self.rng.random_int(0, len(verdicts))
            flip = (TransactionCommitResult.CONFLICT
                    if int(verdicts[i]) == int(TransactionCommitResult.COMMITTED)
                    else TransactionCommitResult.COMMITTED)
            verdicts[i] = flip
        return verdicts
