"""Deterministic device-fault injection at the conflict-engine boundary.

The analog of the reference's machine-level fault injection
(sim2.actor.cpp's AsyncFileNonDurable, clogging, kills) applied to OUR
new failure domain: the accelerator dispatch. A FaultInjectingEngine
wraps any conflict engine and, from its own seeded rng (one draw off the
simulation stream at construction, so per-dispatch draws never perturb
the rest of the world), injects the fault menagerie a real TPU serving
path sees:

  * dispatch exceptions   — XLA runtime errors, transfer failures;
  * hangs                 — a dispatch that never completes (the watchdog
                            in fault/resilient.py must fire);
  * slow batches          — stragglers that complete late;
  * outages               — bursty windows (the preemption model) where
                            EVERY dispatch fails until the device returns;
  * flipped verdict bits  — silent corruption (off by default: an escaped
                            flip is data loss; the supervisor's sampled
                            probe exists to catch exactly this).

Faults that surface after the inner engine ran (`applied_fraction`) model
the nastiest shape: the dispatch landed on the device, only the reply was
lost — device state holds the batch, the host does not know. The
supervisor must re-warm device state before any retry or the batch's own
writes would alias into its history and change verdicts.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..core import error
from ..core.rng import DeterministicRandom
from ..core.types import TransactionCommitResult
from ..sim.loop import TaskPriority, current_scheduler, delay, never, now


@dataclass
class FaultRates:
    """Per-dispatch fault probabilities (the nemesis campaign's defaults).

    The acceptance bar (ISSUE 2) runs exceptions, hangs and slow batches at
    these rates; `flip` defaults to 0 because a flipped verdict that the
    sampled probe misses is emitted — corruption coverage lives in the
    supervisor unit tests with probe_rate=1, not in cluster sims."""

    exception: float = 0.01
    hang: float = 0.008
    slow: float = 0.04
    flip: float = 0.0
    #: probability of entering a bursty outage window in which every
    #: dispatch faults until it expires (TPU preemption / runtime restart)
    outage: float = 0.02
    #: outage length in virtual seconds, uniform in [0.5x, 1.5x]
    outage_seconds: float = 1.5
    #: mean straggler delay, uniform in [0.5x, 1.5x]
    slow_seconds: float = 0.2
    #: fraction of exception/hang faults where the inner engine RAN before
    #: the fault surfaced (dispatch landed, reply lost)
    applied_fraction: float = 0.5


class FaultInjectingEngine:
    """Seed-driven fault wrapper over any ConflictSet engine."""

    name = "fault-injecting"

    def __init__(self, inner, rates: Optional[FaultRates] = None,
                 rng: Optional[DeterministicRandom] = None):
        self.inner = inner
        self.rates = rates or FaultRates()
        if rng is None:
            rng = DeterministicRandom(
                current_scheduler().rng.random_int(0, 2**31 - 1))
        self.rng = rng
        self.injected = {"exceptions": 0, "hangs": 0, "slow": 0, "flips": 0,
                         "outages": 0}
        self._outage_until = 0.0

    # -- engine interface ----------------------------------------------------
    def clear(self, version) -> None:
        self.inner.clear(version)

    def rewarm_target(self):
        """State-rebuild bypass: re-warming device state goes through the
        trusted host-side path (a real system DMAs the rebuilt table rather
        than re-running every historical program through the flaky dispatch
        queue). The supervisor still models re-warm failure via its own
        buggify site."""
        return self.inner

    def resolve(self, transactions, now_v, new_oldest):
        """Synchronous dispatch: exceptions and flips only (a sync call
        cannot hang or straggle in zero virtual time)."""
        kind = self._fault_kind()
        if kind in (None, "slow"):
            return self.inner.resolve(transactions, now_v, new_oldest)
        if kind == "flip":
            return self._flipped(transactions, now_v, new_oldest)
        self._maybe_apply(transactions, now_v, new_oldest)
        self.injected["exceptions"] += 1
        raise error.device_fault(f"injected dispatch {kind} at {now_v}")

    async def resolve_async(self, transactions, now_v, new_oldest):
        """Asynchronous dispatch: the full fault menagerie. The supervisor
        awaits this under its watchdog."""
        kind = self._fault_kind()
        if kind is None:
            return self.inner.resolve(transactions, now_v, new_oldest)
        if kind == "slow":
            self.injected["slow"] += 1
            await delay(self.rates.slow_seconds * (0.5 + self.rng.random01()),
                        TaskPriority.PROXY_RESOLVER_REPLY)
            return self.inner.resolve(transactions, now_v, new_oldest)
        if kind == "flip":
            return self._flipped(transactions, now_v, new_oldest)
        applied = self._maybe_apply(transactions, now_v, new_oldest)
        if kind == "hang":
            self.injected["hangs"] += 1
            await never()
        self.injected["exceptions"] += 1
        raise error.device_fault(
            f"injected dispatch exception at {now_v} (applied={applied})")

    # -- internals -----------------------------------------------------------
    def _fault_kind(self) -> Optional[str]:
        r, rng = self.rates, self.rng
        t = now()
        if t < self._outage_until:
            # device down wholesale: nothing completes until it returns
            return "hang" if rng.random01() < 0.5 else "exception"
        if r.outage > 0 and rng.random01() < r.outage:
            self.injected["outages"] += 1
            self._outage_until = t + r.outage_seconds * (0.5 + rng.random01())
            return "exception"
        x = rng.random01()
        for kind, p in (("exception", r.exception), ("hang", r.hang),
                        ("slow", r.slow), ("flip", r.flip)):
            if x < p:
                return kind
            x -= p
        return None

    def _maybe_apply(self, transactions, now_v, new_oldest) -> bool:
        applied = self.rng.random01() < self.rates.applied_fraction
        if applied:
            self.inner.resolve(transactions, now_v, new_oldest)
        return applied

    def _flipped(self, transactions, now_v, new_oldest):
        """Silent corruption: the device computed (and applied) the true
        verdicts; one reported bit flips on the way back."""
        verdicts = list(self.inner.resolve(transactions, now_v, new_oldest))
        if verdicts:
            self.injected["flips"] += 1
            i = self.rng.random_int(0, len(verdicts))
            flip = (TransactionCommitResult.CONFLICT
                    if int(verdicts[i]) == int(TransactionCommitResult.COMMITTED)
                    else TransactionCommitResult.COMMITTED)
            verdicts[i] = flip
        return verdicts


# -- disk faults ---------------------------------------------------------------

class TornWrite(OSError):
    """A write that persisted only a prefix before failing — the
    crash-mid-append shape. `prefix` is what DID reach the disk; the
    journal writes it so the crc-framed reader's torn-tail tolerance is
    exercised against real torn bytes, not just truncated files."""

    def __init__(self, prefix: bytes):
        super().__init__("injected torn write")
        self.prefix = prefix


@dataclass
class DiskFaultRates:
    """Per-durable-write fault probabilities for the disk nemesis. All
    zero by default (campaign-armed); `from_knobs()` reads the
    `chaos_disk_*` family so campaigns steer injection by knob override,
    the ChaosConfig pattern (real/chaos.py)."""

    stall: float = 0.0
    stall_ms: float = 20.0
    torn: float = 0.0
    enospc: float = 0.0
    rot: float = 0.0

    @classmethod
    def from_knobs(cls) -> "DiskFaultRates":
        from ..core.knobs import SERVER_KNOBS

        return cls(
            stall=float(SERVER_KNOBS.chaos_disk_stall_prob),
            stall_ms=float(SERVER_KNOBS.chaos_disk_stall_ms),
            torn=float(SERVER_KNOBS.chaos_disk_torn_prob),
            enospc=float(SERVER_KNOBS.chaos_disk_enospc_prob),
            rot=float(SERVER_KNOBS.chaos_disk_rot_prob))


class DiskFaults:
    """Seeded per-write fault decisions for the durability surfaces: the
    black-box journal writer, the recovery snapshot writer and the AOT
    program cache (the sim2 AsyncFileNonDurable role for OUR disk layer).

    One `apply(surface, data)` call per durable write draws at most one
    fault: a stall sleeps (a contended fsync), ENOSPC raises plain
    OSError, a torn write raises `TornWrite` carrying the prefix that
    landed, and bit-rot returns silently-corrupted bytes the crc framing
    must catch at read time. Every injection is counted per (surface,
    kind) and reported through `on_fault` — real/chaos.py's DiskNemesis
    wires that to the telemetry hub's chaos.* counters and its kinded
    fault-window log."""

    def __init__(self, rates: Optional[DiskFaultRates] = None,
                 rng: Optional[DeterministicRandom] = None,
                 seed: int = 0, sleep_fn=None, on_fault=None):
        self.rates = rates or DiskFaultRates()
        self.rng = rng if rng is not None else DeterministicRandom(seed)
        #: injected-fault counters keyed "surface.kind"
        self.injected: dict = {}
        self.on_fault = on_fault
        if sleep_fn is None:
            import time as _time

            sleep_fn = _time.sleep
        self._sleep = sleep_fn

    def _draw(self) -> Optional[str]:
        r = self.rates
        x = self.rng.random01()
        for kind, p in (("stall", r.stall), ("torn", r.torn),
                        ("enospc", r.enospc), ("rot", r.rot)):
            if x < p:
                return kind
            x -= p
        return None

    def _count(self, surface: str, kind: str) -> None:
        key = f"{surface}.{kind}"
        self.injected[key] = self.injected.get(key, 0) + 1
        if self.on_fault is not None:
            self.on_fault(surface, kind)

    def apply(self, surface: str, data: bytes) -> bytes:
        """Draw for one durable write of `data` to `surface`. Returns the
        (possibly bit-rotted) bytes to write, sleeps through a stall, or
        raises OSError/TornWrite. Callers must already treat any OSError
        as a degraded write, never a crash."""
        kind = self._draw()
        if kind is None:
            return data
        self._count(surface, kind)
        if kind == "stall":
            self._sleep(self.rates.stall_ms
                        * (0.5 + self.rng.random01()) / 1e3)
            return data
        if kind == "enospc":
            raise OSError(28, f"injected ENOSPC on {surface}")
        if kind == "torn":
            raise TornWrite(bytes(data[:self.rng.random_int(
                1, max(2, len(data)))]))
        # rot: flip one bit in place — the write SUCCEEDS; only the crc
        # framing at read time can tell, and it must quarantine, not crash
        buf = bytearray(data)
        i = self.rng.random_int(0, len(buf))
        buf[i] ^= 1 << self.rng.random_int(0, 8)
        return bytes(buf)
