"""Device-fault tolerance for the resolver's conflict engine.

The north-star accelerator boundary is not infallible: real TPU serving
sees preemptions, hung dispatches, XLA runtime errors and (rarely) silent
corruption. Harmonia (arXiv:1904.08964) keeps its in-network conflict
accelerator trustworthy by pairing it with a replicated authoritative
path; we pair the device engine with the reference-exact CPU oracle
(ops/oracle.py), which already pins every engine bit-for-bit — so it can
serve as a live failover target, not just a test fixture.

Two pieces:

  * FaultInjectingEngine (inject.py) — a deterministic, seed-driven
    wrapper over any conflict engine that injects dispatch exceptions,
    never-completing hangs, slow batches, bursty outages (the preemption
    model) and flipped verdict bits.
  * ResilientEngine (resilient.py) — the supervisor: per-dispatch
    watchdog, bounded retries with jittered exponential backoff, a
    health state machine (healthy -> suspect -> failed -> probation),
    a host-side shadow of the committed write-history window that
    rebuilds the CPU oracle mid-stream with bit-identical verdicts, and
    a sampled cross-validation probe that quarantines a corrupting
    device.

The module-level registry lets test harnesses find every supervisor a
simulation created (including ones whose processes have since died);
Simulator.__init__ resets it per run, like sim/validation.py.
"""
from __future__ import annotations

from typing import List

from .inject import FaultInjectingEngine, FaultRates
from .resilient import (
    HEALTHY,
    SUSPECT,
    FAILED,
    PROBATION,
    QUARANTINED,
    FlightRecorder,
    ResilienceConfig,
    ResilientEngine,
    abort_set_digest,
)

#: every ResilientEngine constructed since the last reset (sim-wide; the
#: nemesis validation workload audits journals/health of dead generations'
#: engines through this, the way sim/validation.py records violations).
#: Recording is armed by Simulator.__init__ via reset_registry() — a
#: real-mode cluster never arms it, so dead generations' engines are not
#: pinned in memory outside simulation.
_registry: List["ResilientEngine"] = []
_recording = False


def register_engine(engine: "ResilientEngine") -> None:
    if _recording:
        _registry.append(engine)


def registered_engines() -> List["ResilientEngine"]:
    return list(_registry)


def reset_registry() -> None:
    global _recording
    _recording = True
    del _registry[:]


def maybe_wrap(engine, cluster_cfg):
    """The one wrap decision for role wiring (server/worker.py recruitment
    and the static server/cluster.py assembly): supervise the factory's
    engine when the cluster config asks for it and the factory didn't
    already build a supervised engine."""
    if (getattr(cluster_cfg, "resilient_resolver", False)
            and not hasattr(engine, "health_stats")):
        engine = ResilientEngine(engine)
    return engine


__all__ = [
    "FaultInjectingEngine",
    "FaultRates",
    "ResilienceConfig",
    "ResilientEngine",
    "maybe_wrap",
    "HEALTHY",
    "SUSPECT",
    "FAILED",
    "PROBATION",
    "QUARANTINED",
    "register_engine",
    "registered_engines",
    "reset_registry",
]
