"""Range history handoff: move a key range's committed write history
between supervised engines without losing a verdict.

The donor side of an online reshard (server/reshard.py) must hand the
recipient everything that can still decide a verdict for the moving
range. The ResilientEngine's shadow (fault/resilient.py) is exactly that
window: one (version, committed write ranges, new_oldest) entry per
resolved batch, trimmed to version >= the GC horizon — the same
sufficiency argument that makes failover rebuilds bit-identical (any
read passing the too-old gate has snapshot >= oldest, so writes below
the horizon can never conflict) makes a RANGE-CLIPPED slice of the
shadow sufficient for the moving range.

Transfer happens in two stages, the classic live-migration shape:

  * pre-copy (unfrozen): the slice as of a version watermark is
    COALESCED to the effective interval map (key -> last write version,
    restricted to the range — a hot range overwrites the same keys over
    and over, so the coalesced form is bounded by distinct keys, not by
    history length) and replayed into the recipient as synthetic
    write-only transactions, one batch per distinct version in ascending
    order. The donor keeps serving; writes landing after the watermark
    are the next round's delta.
  * delta (frozen): once the range is frozen the few entries above the
    final watermark replay raw — this is the only part inside the
    blackout, which is what keeps the per-range unavailability under
    `reshard_blackout_budget_ms`.

Replaying through the recipient's ResilientEngine (not its raw device)
is the point: the synthetic batches land in the recipient's OWN shadow
and journal, so a later failover, probe or re-warm of the recipient
rebuilds WITH the adopted history, and the campaign's clean-oracle
journal replay covers the handoff batches like any others. Write-only
transactions commit unconditionally (no reads -> no conflicts, no
too-old), so adoption can never flip a verdict.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..core.types import CommitTransaction, Key, KeyRange, Version
from ..ops.oracle import VersionIntervalMap

#: (version, ((begin, end), ...)) — one replayable write-history batch
HistoryBatch = Tuple[Version, Tuple[Tuple[Key, Key], ...]]

#: history-maintenance span segments, on their own timeline like the
#: reshard protocol arcs (registered with the fdbtpu-lint span-registry
#: rule; docs/static_analysis.md#span-registry)
HISTORY_SEGMENTS = (
    "snapshot",   # device run-plane readback (history_run_snapshots)
    "slice",      # run-interval decode + range clip + version regroup
)


def _unwrap(engine):
    unwrap = getattr(engine, "_rewarm_engine", None)
    return unwrap() if unwrap is not None else engine


def _merge_epoch(engine) -> Optional[int]:
    """Cumulative compaction count the donor's heat layer has observed
    (KeyRangeHeatAggregator.history_merges_total) — the monotone epoch
    an incremental run_slice chain is valid within. None when the donor
    runs without the heat layer (no epoch -> no incremental proof)."""
    heat = getattr(engine, "heat", None)
    total = getattr(heat, "history_merges_total", None)
    return int(total) if total is not None else None


def run_watermarks(engine) -> Optional[Tuple[List[int], Optional[int]]]:
    """(per-shard nruns vector, merge epoch) seeding an incremental
    run_slice chain; None when the donor does not serve the tiered
    path. Capture BEFORE reading the shadow for the same round: a batch
    landing in between is then re-fetched (idempotent duplicate), never
    skipped."""
    engine = _unwrap(engine)
    fn = getattr(engine, "history_run_snapshots", None)
    if fn is None:
        return None
    snaps = fn(since_runs=None)
    if snaps is None:
        return None
    return [int(s["nruns"]) for s in snaps], _merge_epoch(engine)


def run_slice(engine, begin: Key, end: Optional[Key],
              since_runs: Optional[List[int]] = None,
              since_epoch: Optional[int] = None) -> Optional[dict]:
    """Pre-copy source straight off a tiered donor's device run planes —
    the O(delta) sibling of shadow_slice (docs/perf.md "Incremental
    history maintenance").

    A tiered engine's un-merged sorted runs ARE the committed-write
    history since the last compaction, so a repeat pre-copy round only
    needs the runs appended after the previous round's watermark:
    `since_runs` is the per-shard nruns vector returned by the prior
    call; pass None for the first round (all active runs). Rows come
    back range-clipped and regrouped into ascending-version
    HistoryBatch entries, ready for replay_slice.

    Returns None when the donor cannot serve the path — monolithic
    structure, no device-state accessor, or a run row whose endpoint
    was window-truncated (the exact byte key is not recoverable from
    the device image; the host shadow has it) — callers then fall back
    to shadow_slice, which is always sufficient. Otherwise returns
    {"entries": [HistoryBatch...], "watermarks": [per-shard nruns],
    "epoch": Optional[int], "resync": bool} — resync=True means a
    compaction consumed runs below a caller watermark (the LSM manifest
    contract: the delta chain broke, redo a full pre-copy with
    since_runs=None).

    `since_epoch` is the `epoch` of the prior round (run_watermarks'
    second element for a fresh chain). It closes the ABA hole the nruns
    vector alone cannot see: a merge can absorb an uncopied run and
    subsequent appends can push nruns back past the caller's watermark,
    so pass the epoch whenever the chain must be PROVEN unbroken —
    any intervening merge (or a donor without the heat layer to count
    them) then flags resync."""
    engine = _unwrap(engine)        # supervised donor: reach the device
    fn = getattr(engine, "history_run_snapshots", None)
    if fn is None:
        return None
    from ..core.trace import g_spans, span_event, span_now

    spans_on = g_spans.enabled
    t0 = span_now()
    snaps = fn(since_runs=since_runs)
    if snaps is None:
        return None
    t_snap = span_now()
    from ..ops import conflict_kernel as ck
    from ..ops import keypack

    cfg = engine.cfg
    kw = cfg.key_words
    kb = keypack.max_key_bytes(kw)
    base = int(getattr(engine, "base", 0))
    epoch = _merge_epoch(engine)
    resync = since_epoch is not None and (epoch is None
                                          or epoch != since_epoch)
    watermarks: List[int] = []
    by_version: Dict[Version, List[Tuple[Key, Key]]] = {}
    for s, snap in enumerate(snaps):
        watermarks.append(int(snap["nruns"]))
        if since_runs is not None and int(snap["nruns"]) < since_runs[s]:
            resync = True
        for kb_row, ke_row, rel_v in ck.run_intervals(snap):
            if int(kb_row[kw]) > kb or int(ke_row[kw]) > kb:
                return None     # window-truncated endpoint: shadow has it
            b = keypack.unpack_key(kb_row, kw)
            e = keypack.unpack_key(ke_row, kw)
            c = clip_range(b, e, begin, end)
            if c is not None:
                by_version.setdefault(base + rel_v, []).append(c)
    entries = [(v, tuple(sorted(by_version[v]))) for v in sorted(by_version)]
    if spans_on:
        span_event("history.snapshot", base, t0, t_snap,
                   shards=len(snaps))
        span_event("history.slice", base, t_snap, span_now(),
                   entries=len(entries), resync=resync)
    return {"entries": entries, "watermarks": watermarks, "epoch": epoch,
            "resync": resync}


def clip_range(b: Key, e: Key, begin: Key,
               end: Optional[Key]) -> Optional[Tuple[Key, Key]]:
    """Concrete [b, e) intersected with the shard span [begin, end);
    None when empty. A `None` span end means +inf (the last span)."""
    cb = max(b, begin)
    ce = e if end is None else min(e, end)
    return (cb, ce) if cb < ce else None


def shadow_slice(engine, begin: Key, end: Optional[Key],
                 min_version: Version = 0) -> List[HistoryBatch]:
    """The donor ResilientEngine's shadow entries above `min_version`,
    clipped to [begin, end); empty clips drop. Entries come back in
    shadow (= resolution) order."""
    out: List[HistoryBatch] = []
    for version, writes, _new_oldest in getattr(engine, "_shadow", ()):
        if version <= min_version:
            continue
        clipped = []
        for b, e in writes:
            c = clip_range(b, e, begin, end)
            if c is not None:
                clipped.append(c)
        if clipped:
            out.append((version, tuple(clipped)))
    return out


def coalesce(entries: Sequence[HistoryBatch],
             begin: Key, end: Optional[Key]) -> List[HistoryBatch]:
    """Entries -> the EFFECTIVE interval map restricted to [begin, end),
    re-expressed as one write-only batch per distinct surviving version,
    ascending. Observable-state equivalent to replaying every entry:
    later writes overwrite earlier ones key-by-key exactly as the
    interval map records, and sub-horizon residue was already trimmed
    from the shadow. A hot range that overwrote the same keys thousands
    of times coalesces to a handful of intervals — this is what keeps
    pre-copy (and with it the frozen delta) small."""
    if not entries:
        return []
    m = VersionIntervalMap(0)
    for version, writes in entries:
        for b, e in writes:
            if e is None:
                e = b"\xff\xff\xff\xff\xff\xff"
            m.write(b, e, version)
    by_version: Dict[Version, List[Tuple[Key, Key]]] = {}
    keys, vers = m.keys, m.vers
    for i, v in enumerate(vers):
        if v <= 0:
            continue
        b = keys[i]
        e = keys[i + 1] if i + 1 < len(keys) else b"\xff\xff\xff\xff\xff\xff"
        rows = by_version.setdefault(v, [])
        # merge adjacency within one version: the map splits intervals at
        # every historical boundary; re-fusing keeps batches minimal
        if rows and rows[-1][1] == b:
            rows[-1] = (rows[-1][0], e)
        else:
            rows.append((b, e))
    return [(v, tuple(by_version[v])) for v in sorted(by_version)]


async def replay_slice(recipient, entries: Sequence[HistoryBatch]) -> int:
    """Adopt `entries` into the recipient supervised engine: one
    synthetic write-only transaction per batch, resolved at the entry's
    own version (write versions must be preserved exactly — quantizing
    them upward would manufacture conflicts for snapshots in between).
    new_oldest rides as 0 so adoption never advances the recipient's
    too-old gate. Returns the number of batches replayed."""
    n = 0
    for version, writes in entries:
        txn = CommitTransaction(
            read_snapshot=version,
            write_conflict_ranges=[KeyRange(b, e) for b, e in writes])
        r = recipient.resolve([txn], version, 0)
        if hasattr(r, "__await__"):
            await r
        n += 1
    return n


def last_shadow_version(engine) -> Version:
    """The donor's newest shadow version — the pre-copy watermark."""
    shadow = getattr(engine, "_shadow", None)
    if not shadow:
        return 0
    return max(entry[0] for entry in shadow)


def migrate_ewmas(src_batcher, dst_batcher) -> int:
    """Carry a donor batcher's observed per-(bucket, search-mode,
    dispatch-mode) latency EWMAs onto the recipient so the moved range's
    batch sizing starts from the donor's measurements instead of
    re-learning from cold (pipeline/resolver_pipeline.BudgetBatcher).
    Keys the recipient has already observed win. Returns entries copied."""
    if src_batcher is None or dst_batcher is None:
        return 0
    copied = 0
    for key, ms in src_batcher.ewma_ms.items():
        if key not in dst_batcher.ewma_ms:
            dst_batcher.ewma_ms[key] = float(ms)
            copied += 1
    return copied
