"""ResilientEngine: the resolver survives a misbehaving device with
bit-identical abort sets.

The supervisor wraps the production conflict engine ("the device") and
pairs it with the reference-exact CPU oracle (ops/oracle.py) as a live
failover target, the Harmonia pattern (arXiv:1904.08964): the accelerated
path is fast, the authoritative path is always reconstructible.

Health state machine::

            dispatch fault                 retry budget exhausted
  HEALTHY ----------------> SUSPECT -----------------------------> FAILED
     ^       (retrying with jittered backoff,                        |
     |        device re-warmed before each retry)                    |
     |                                                               |
     |  probation_batches clean       failover_min_batches on the    |
     |  (device vs oracle equal)      oracle, then re-warm device    |
     +------------------- PROBATION <--------------------------------+
                              |
                              | device/oracle verdict mismatch
                              v                   (also from a sampled
                         QUARANTINED               probe in HEALTHY)

Why verdicts stay bit-identical through every transition: the supervisor
keeps a host-side shadow of the committed write history — one entry per
resolved batch, (version, committed write ranges, new_oldest), trimmed to
the window >= oldest_version. The oracle's own GC proof (ops/oracle.py:
any read passing the too-old gate has snapshot >= oldestVersion, so
intervals last written below the horizon can never conflict) means that
window is sufficient to rebuild the OBSERVABLE conflict state of any
engine from scratch: replaying the shadow's writes into a fresh oracle
(or back into a cleared device) yields the same verdict for every future
batch as an engine that lived through the whole history. Failover
mid-stream therefore changes nothing about abort sets, and the sampled
cross-validation probe (re-resolving a device batch on a shadow-rebuilt
oracle) is an exact corruption detector, not a heuristic.

Retries re-warm the device first because a failed dispatch may have
half-applied — or fully applied with the reply lost (the injector's
`applied_fraction` models this): re-running the batch against state that
already contains it would alias the batch's own writes into its history
and flip verdicts.
"""
from __future__ import annotations

import zlib
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Tuple

from ..core import blackbox, buggify, error, telemetry
from ..core.knobs import SERVER_KNOBS
from ..core.rng import DeterministicRandom
from ..core.trace import Severity, TraceEvent, g_spans, span_event, span_now
from ..core.types import CommitTransaction, KeyRange, TransactionCommitResult
from ..ops.oracle import OracleConflictEngine
from ..sim.actors import any_of
from ..sim.loop import TaskPriority, current_scheduler, delay, spawn

HEALTHY = "healthy"
SUSPECT = "suspect"
FAILED = "failed"
PROBATION = "probation"
QUARANTINED = "quarantined"


@dataclass
class ResilienceConfig:
    """Supervisor knobs (docs/fault_tolerance.md). No field defaults: the
    single source of default values is the resolver_* knob registry
    (core/knobs.py), read at engine construction via from_knobs() so
    per-run knob overrides apply."""

    dispatch_timeout: float
    retry_budget: int
    retry_backoff: float
    probe_rate: float
    probation_batches: int
    failover_min_batches: int

    @classmethod
    def from_knobs(cls) -> "ResilienceConfig":
        k = SERVER_KNOBS
        return cls(
            dispatch_timeout=k.resolver_dispatch_timeout,
            retry_budget=k.resolver_retry_budget,
            retry_backoff=k.resolver_retry_backoff,
            probe_rate=k.resolver_probe_rate,
            probation_batches=k.resolver_probation_batches,
            failover_min_batches=k.resolver_failover_min_batches,
        )


def abort_set_digest(verdicts) -> str:
    """Stable 32-bit digest of a batch's verdict vector — the flight
    recorder's compact abort-set fingerprint. Replaying the batch through a
    clean oracle and digesting its verdicts must reproduce this exactly
    (DeviceFaultValidationWorkload's post-mortem parity check)."""
    return format(zlib.crc32(bytes(int(v) & 0xFF for v in verdicts)), "08x")


class FlightRecorder:
    """Bounded ring of recent device dispatches (docs/observability.md).

    A quarantine SevError used to say only "the device corrupted verdicts"
    with no record of the dispatches that led up to it; this ring keeps the
    last N dispatch records — version, txn/conflict-row counts, health
    state at dispatch, service latency, retries consumed, which path served
    (device/oracle), and the abort-set digest — and is dumped whole into
    the quarantine/failover trace events for post-mortem replay."""

    __slots__ = ("ring",)

    def __init__(self, size: Optional[int] = None):
        if size is None:
            size = int(SERVER_KNOBS.resolver_flight_recorder_size)
        self.ring: Deque[dict] = deque(maxlen=max(1, size))

    def record(self, **rec) -> None:
        self.ring.append(rec)

    def dump(self) -> List[dict]:
        return list(self.ring)

    def __len__(self) -> int:
        return len(self.ring)


class ResilientEngine:
    """Fault-tolerant supervisor over a device conflict engine."""

    name = "resilient"

    def __init__(self, device, cfg: Optional[ResilienceConfig] = None,
                 record_journal: bool = False,
                 oracle_factory=OracleConflictEngine):
        self.device = device
        self.cfg = cfg or ResilienceConfig.from_knobs()
        # own rng stream (one draw off the world's): per-batch probe and
        # backoff draws must not perturb the rest of the simulation
        self.rng = DeterministicRandom(
            current_scheduler().rng.random_int(0, 2**31 - 1))
        self.state = HEALTHY
        self.stats = {"batches": 0, "dispatch_faults": 0, "retries": 0,
                      "failovers": 0, "swap_backs": 0, "rewarm_failures": 0,
                      "probes": 0, "probe_mismatches": 0, "oracle_batches": 0}
        #: committed write history window: (version, ((begin, end), ...),
        #: new_oldest) per batch, trimmed to version >= the GC horizon
        self._shadow: Deque[Tuple] = deque()
        self._oldest = 0
        self._oracle_factory = oracle_factory
        self._failover: Optional[OracleConflictEngine] = None
        self._failed_batches = 0
        self._probation_left = 0
        #: (version, transactions, new_oldest, verdicts) per batch when
        #: journaling — the nemesis check replays it through a clean oracle
        #: to assert the emitted abort sets are bit-identical to a fault-free
        #: engine's. Off by default: the journal is unbounded by design
        #: (test-harness memory), so only sim campaigns opt in.
        self.journal: Optional[List[Tuple]] = [] if record_journal else None
        #: bounded ring of recent dispatches, dumped into quarantine/
        #: failover trace events (docs/observability.md)
        self.flight = FlightRecorder()
        #: per-batch retry bookkeeping for the flight record
        self._batch_retries = 0
        from . import register_engine

        register_engine(self)
        self._telemetry_label = telemetry.hub().register_health(self)
        telemetry.hub().record_health_transition(self._telemetry_label,
                                                 self.state)

    # -- public surface ------------------------------------------------------
    @property
    def degraded(self) -> bool:
        """True while the device is not serving cleanly: the pipeline
        collapses its window to depth 1 and the ratekeeper throttles."""
        return self.state != HEALTHY

    def health_stats(self) -> dict:
        return {"state": self.state, "degraded": self.degraded,
                "device": getattr(self.device, "name", type(self.device).__name__),
                "shadow_entries": len(self._shadow), **self.stats}

    def clear(self, version) -> None:
        self.device.clear(version)
        if self._failover is not None:
            self._failover.clear(version)
        self._shadow.clear()

    def warmup(self, **kw) -> "ResilientEngine":
        """Pass-through to a bucketed device engine's ladder warmup
        (ops/host_engine.py) so supervised serving is compile-stall-proof
        too; a no-op for engines without a ladder (the oracle)."""
        fn = getattr(self._rewarm_engine(), "warmup", None)
        if fn is not None:
            fn(**kw)
        return self

    def _rewarm_engine(self):
        """The engine whose device state/programs a re-warm rebuilds (the
        fault injector's rewarm_target bypasses the flaky dispatch path)."""
        target = self.device
        fn = getattr(target, "rewarm_target", None)
        return fn() if fn is not None else target

    def history_search_modes(self):
        """Pass-through to a bucketed device engine's resolved per-bucket
        history-search modes (docs/perf.md), so a supervised resolver's
        BudgetBatcher still keys its EWMAs per (bucket, mode); {} for
        engines without a ladder (the oracle)."""
        fn = getattr(self._rewarm_engine(), "history_search_modes", None)
        return fn() if fn is not None else {}

    def loop_stats_snapshot(self):
        """Pass-through to a device-loop engine's sync-accounting/occupancy
        snapshot (ops/device_loop.py) — the span/flight-record attachment
        survives supervision; None for step-dispatch engines."""
        fn = getattr(self._rewarm_engine(), "loop_stats_snapshot", None)
        return fn() if fn is not None else None

    def heat_snapshot(self, top_n: int = 8, brief: bool = False):
        """Pass-through to the device engine's keyspace-heat/occupancy
        snapshot (core/heatmap.py) — engine_health, spans and the flight
        recorder keep their heat context under supervision; None for
        engines without the layer (the oracle, heat off)."""
        fn = getattr(self._rewarm_engine(), "heat_snapshot", None)
        return fn(top_n=top_n, brief=brief) if fn is not None else None

    def history_stats_snapshot(self):
        """Pass-through to the device engine's tiered-history counters
        (ops/host_engine.py; docs/perf.md "Incremental history
        maintenance") — run-stack depth and append/merge totals stay
        visible under supervision; None for engines without the layer."""
        fn = getattr(self._rewarm_engine(), "history_stats_snapshot", None)
        return fn() if fn is not None else None

    def history_run_snapshots(self, since_runs=None):
        """Pass-through to the device engine's O(delta) run-snapshot
        export (fault/handoff.py run_slice consumes it on the donor side
        of a reshard) — None for monolithic devices, where the shadow
        replay is the only rebuild path."""
        fn = getattr(self._rewarm_engine(), "history_run_snapshots", None)
        return fn(since_runs=since_runs) if fn is not None else None

    async def resolve(self, transactions, now_v, new_oldest):
        """One batch through the supervisor; callers (server/resolver.py,
        pipeline/service.py) enter strictly in commit-version order."""
        self.stats["batches"] += 1
        self._batch_retries = 0
        t_dispatch = span_now()
        state_at_dispatch = self.state
        if self.state == FAILED:
            # re-warm BEFORE resolving this batch: the shadow and the
            # failover oracle are both exactly one-batch-behind states, so
            # the rebuilt device enters probation in lockstep
            self._maybe_rewarm()
        if self.state in (FAILED, QUARANTINED):
            verdicts = self._oracle_resolve(transactions, now_v, new_oldest)
            self._failed_batches += 1
        elif self.state == PROBATION:
            verdicts = await self._probation_batch(transactions, now_v, new_oldest)
        else:
            verdicts = await self._healthy_batch(transactions, now_v, new_oldest)
        self._record(now_v, transactions, new_oldest, verdicts)
        # flight records name the device's dispatch path and, for loop
        # engines, snapshot the queue/ring state at this dispatch — so a
        # quarantine dump from a loop-mode engine is diagnosable (was the
        # ring backed up? did a drain fall back to a blocking sync?)
        inner = self._rewarm_engine()
        loop_snap = self.loop_stats_snapshot()
        # heat/occupancy context rides next to the abort-set digest: a
        # quarantine or failover dump says whether the keyspace was hot
        # and how full the history table was when the batch ran
        # (docs/observability.md "Keyspace heat & occupancy")
        heat_snap = self.heat_snapshot(brief=True)
        self.flight.record(
            version=now_v,
            new_oldest=new_oldest,
            txns=len(transactions),
            reads=sum(len(t.read_conflict_ranges) for t in transactions),
            writes=sum(len(t.write_conflict_ranges) for t in transactions),
            state=state_at_dispatch,
            served_by=("device" if state_at_dispatch in (HEALTHY, SUSPECT)
                       else "oracle"),
            retries=self._batch_retries,
            ms=round((span_now() - t_dispatch) * 1e3, 4),
            digest=abort_set_digest(verdicts),
            dispatch_mode=getattr(inner, "dispatch_mode", "step"),
            **({"loop_stats": loop_snap} if loop_snap is not None else {}),
            **({"heat": heat_snap} if heat_snap is not None else {}),
        )
        return verdicts

    # -- state machine -------------------------------------------------------
    def _set_state(self, state: str) -> None:
        if state != self.state:
            TraceEvent("ResolverEngineHealth",
                       severity=(Severity.WARN if state != HEALTHY
                                 else Severity.INFO)) \
                .detail("From", self.state).detail("To", state).log()
            if blackbox.enabled():
                # the transition onto the durable black-box journal:
                # `cli explain` renders the failover/swap-back arc a
                # version's batch ran under, hours after the process died
                blackbox.record_health(self._telemetry_label,
                                       self.state, state)
            self.state = state
            # transition into the unified TDMetric registry: the change
            # history of this Int64 series IS the incident timeline
            telemetry.hub().record_health_transition(
                self._telemetry_label, state)

    async def _healthy_batch(self, transactions, now_v, new_oldest):
        try:
            got = await self._attempt(transactions, now_v, new_oldest,
                                      1 + max(0, self.cfg.retry_budget))
        except error.FDBError as e:
            self._fail_over(now_v, e)
            return self._oracle_resolve(transactions, now_v, new_oldest)
        if self.state == SUSPECT:
            self._set_state(HEALTHY)   # a retry recovered the device
        if self.cfg.probe_rate > 0 and self.rng.random01() < self.cfg.probe_rate:
            self.stats["probes"] += 1
            probe = self._rebuild_oracle()   # pre-batch: shadow excludes this batch
            want = probe.resolve(transactions, now_v, new_oldest)
            if [int(x) for x in got] != [int(x) for x in want]:
                self._quarantine(now_v, got, want)
                self._failover = probe       # already advanced past this batch
                return want
        return got

    async def _probation_batch(self, transactions, now_v, new_oldest):
        # the oracle stays authoritative: a device relapse mid-probation
        # cannot corrupt the emitted stream
        want = self._oracle_resolve(transactions, now_v, new_oldest)
        try:
            got = await self._attempt(transactions, now_v, new_oldest, 1)
        except error.FDBError as e:
            TraceEvent("ResolverEngineProbationFault").error(e).log()
            self._failed_batches = 0
            self._set_state(FAILED)
            return want
        self.stats["probes"] += 1
        if [int(x) for x in got] != [int(x) for x in want]:
            self._quarantine(now_v, got, want)
            return want
        self._probation_left -= 1
        if self._probation_left <= 0:
            self.stats["swap_backs"] += 1
            self._failover = None
            self._set_state(HEALTHY)
            TraceEvent("ResolverEngineSwapBack").detail("Version", now_v).log()
        return want

    async def _attempt(self, transactions, now_v, new_oldest, attempts: int):
        """Bounded watchdog-guarded dispatch attempts with jittered
        exponential backoff; device state is re-warmed from the shadow
        before every retry (the failed attempt may have applied)."""
        last: Optional[error.FDBError] = None
        for i in range(attempts):
            # retry time (backoff + re-warm + the re-dispatch itself) gets
            # its own span segment so latency attribution charges it to the
            # fault path, not to the healthy device-dispatch figure
            t_retry = span_now() if (i and g_spans.enabled) else None
            try:
                if i:
                    self.stats["retries"] += 1
                    self._batch_retries += 1
                    backoff = (self.cfg.retry_backoff * (2 ** (i - 1))
                               * (0.5 + self.rng.random01()))
                    await delay(backoff, TaskPriority.PROXY_RESOLVER_REPLY)
                    try:
                        self._rewarm_device()
                    except error.FDBError as e:
                        self.stats["rewarm_failures"] += 1
                        last = e
                        continue
                try:
                    return await self._dispatch_once(transactions, now_v, new_oldest)
                except error.FDBError as e:
                    self.stats["dispatch_faults"] += 1
                    if self.state == HEALTHY:
                        self._set_state(SUSPECT)
                    last = e
            finally:
                if t_retry is not None and g_spans.enabled:
                    span_event("resolver.retry", now_v, t_retry, span_now(),
                               attempt=i, parent="resolver.device_dispatch")
        raise last if last is not None else error.device_fault("no attempts")

    async def _dispatch_once(self, transactions, now_v, new_oldest):
        if buggify.buggify():
            # engine-boundary fault: every sim spec (attrition, clogging,
            # recovery) exercises the watchdog/retry path for free
            raise error.device_fault("buggify: dispatch failed at engine boundary")
        if buggify.buggify():
            # straggling device: completes, but late
            await delay(self.cfg.dispatch_timeout * 0.5,
                        TaskPriority.PROXY_RESOLVER_REPLY)
        eng = self.device
        if not hasattr(eng, "resolve_async"):
            # synchronous engine: runs inline in zero virtual time (cannot
            # hang); exceptions propagate to the retry loop
            try:
                return eng.resolve(transactions, now_v, new_oldest)
            except error.FDBError:
                raise
            except Exception as e:
                raise error.device_fault(f"device dispatch raised: {e}") from e
        task = spawn(self._run_async(eng, transactions, now_v, new_oldest),
                     TaskPriority.PROXY_RESOLVER_REPLY, name="deviceDispatch")
        timer = delay(self.cfg.dispatch_timeout, TaskPriority.PROXY_RESOLVER_REPLY)
        try:
            idx, value = await any_of([task, timer])
        except BaseException:
            # our own cancellation (role killed mid-dispatch) must not
            # leave a hung device task orphaned behind the dead role
            task.cancel()
            raise
        if idx == 1:
            task.cancel()
            raise error.device_fault(
                f"dispatch watchdog: no completion in {self.cfg.dispatch_timeout}s")
        return value

    async def _run_async(self, eng, transactions, now_v, new_oldest):
        try:
            return await eng.resolve_async(transactions, now_v, new_oldest)
        except error.FDBError:
            raise
        except Exception as e:
            raise error.device_fault(f"device dispatch raised: {e}") from e

    def _fail_over(self, now_v, err) -> None:
        """Persistent device failure: rebuild the CPU oracle from the
        shadow (one-batch-behind state) and serve from it mid-stream."""
        self.stats["failovers"] += 1
        self._failover = self._rebuild_oracle()
        self._failed_batches = 0
        self._set_state(FAILED)
        if blackbox.enabled():
            blackbox.record_flight("failover", now_v, self.flight.dump())
        TraceEvent("ResolverEngineFailover", severity=Severity.WARN) \
            .detail("Version", now_v).detail("ShadowEntries", len(self._shadow)) \
            .detail("FlightRecorder", self.flight.dump()) \
            .error(err).log()

    def _maybe_rewarm(self) -> None:
        """After enough batches on the oracle, try to re-warm device state
        from the shadow and enter probation; a re-warm failure leaves us on
        the oracle for another round."""
        if self._failed_batches < max(1, self.cfg.failover_min_batches):
            return
        self._failed_batches = 0
        try:
            self._rewarm_device()
        except error.FDBError as e:
            self.stats["rewarm_failures"] += 1
            TraceEvent("ResolverEngineRewarmFailed").error(e).log()
            return
        self._probation_left = max(1, self.cfg.probation_batches)
        self._set_state(PROBATION)

    def _quarantine(self, now_v, got, want) -> None:
        """The probe caught the device disagreeing with the shadow-rebuilt
        oracle: silent corruption. SevError — a correctness event — and the
        device is never trusted again this incarnation."""
        self.stats["probe_mismatches"] += 1
        self._set_state(QUARANTINED)
        if blackbox.enabled():
            blackbox.record_flight("quarantine", now_v, self.flight.dump())
        # the flight recorder's last N dispatch records ride the SevError:
        # a post-mortem replays them (digests + journal) without having to
        # reconstruct the dispatch history from scattered logs
        TraceEvent("ResolverEngineQuarantine", severity=Severity.ERROR) \
            .detail("Version", now_v) \
            .detail("Got", [int(x) for x in got]) \
            .detail("Want", [int(x) for x in want]) \
            .detail("FlightRecorder", self.flight.dump()).log()

    # -- shadow history ------------------------------------------------------
    def _oracle_resolve(self, transactions, now_v, new_oldest):
        self.stats["oracle_batches"] += 1
        return self._failover.resolve(transactions, now_v, new_oldest)

    def _record(self, now_v, transactions, new_oldest, verdicts) -> None:
        committed = int(TransactionCommitResult.COMMITTED)
        writes = tuple(
            (r.begin, r.end)
            for t, txn in enumerate(transactions)
            if int(verdicts[t]) == committed
            for r in txn.write_conflict_ranges
            if r.begin < r.end
        )
        self._shadow.append((now_v, writes, new_oldest))
        if new_oldest > self._oldest:
            self._oldest = new_oldest
        while self._shadow and self._shadow[0][0] < self._oldest:
            self._shadow.popleft()
        if self.journal is not None:
            self.journal.append((now_v, tuple(transactions), new_oldest,
                                 tuple(int(v) for v in verdicts)))

    def _rebuild_oracle(self) -> OracleConflictEngine:
        o = self._oracle_factory()
        self._replay_shadow(o)
        return o

    def _rewarm_device(self) -> None:
        if buggify.buggify():
            # re-warm itself can fail (the device is, after all, sick)
            raise error.device_fault("buggify: device re-warm failed")
        target = self._rewarm_engine()
        try:
            self._replay_shadow(target)
            # Bucketed engines: the shadow replay rebuilds device STATE;
            # program coverage persists across clear(), so only ladder
            # buckets that actually served traffic get (re-)warmed — a
            # rebuild never front-loads compiles for shapes this stream
            # has not used.
            fn = getattr(target, "ensure_warm", None)
            if fn is not None:
                fn(used_only=True)
        except error.FDBError:
            raise
        except Exception as e:
            raise error.device_fault(f"device re-warm raised: {e}") from e

    def _replay_shadow(self, eng) -> None:
        """Rebuild an engine's observable conflict state from the shadow.

        Sufficiency: any read that passes the too-old gate has
        read_snapshot >= oldest_version, so intervals last written below
        the horizon compare <= snapshot and can never conflict — only the
        window >= oldest_version (exactly what the shadow keeps) decides
        verdicts (the same argument that makes the oracle's GC
        representation-only)."""
        # A device-loop engine's clear() drains its in-flight queue slots
        # before touching the donated table (ops/device_loop.py enforces
        # the drain-before-host-touch contract engine-side), so this
        # rebuild needs no engine-specific handling.
        eng.clear(0)
        if self._oldest:
            # pin the too-old gate first; per-entry horizons below it are
            # then no-ops and GC timing differences are representation-only
            eng.resolve([], self._oldest, self._oldest)
        for version, writes, new_oldest in self._shadow:
            if not writes:
                continue
            txn = CommitTransaction(
                read_snapshot=version,
                write_conflict_ranges=[KeyRange(b, e) for b, e in writes])
            eng.resolve([txn], version, new_oldest)
