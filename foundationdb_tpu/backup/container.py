"""Simulated blob container: the backup target.

The analog of the reference's BlobStore/backup container stack
(fdbrpc/BlobStore.actor.cpp, fdbclient/BackupContainer.actor.cpp) reduced
to a sim-process object store with put/get/list — enough surface for
range-snapshot and mutation-log objects plus a manifest, addressed by
name with prefix listing.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..sim.network import SimProcess

PUT_TOKEN = "blob.put"
GET_TOKEN = "blob.get"
LIST_TOKEN = "blob.list"
DELETE_TOKEN = "blob.delete"


@dataclass
class BlobPut:
    name: str
    data: bytes


@dataclass
class BlobGet:
    name: str


@dataclass
class BlobList:
    prefix: str = ""


@dataclass
class BlobDelete:
    name: str


class BlobContainer:
    """One backup container hosted on a sim process."""

    def __init__(self, proc: SimProcess):
        self.proc = proc
        self._objects: Dict[str, bytes] = {}
        proc.register(PUT_TOKEN, self._put)
        proc.register(GET_TOKEN, self._get)
        proc.register(LIST_TOKEN, self._list)
        proc.register(DELETE_TOKEN, self._delete)

    async def _put(self, req: BlobPut) -> None:
        self._objects[req.name] = req.data

    async def _get(self, req: BlobGet) -> Optional[bytes]:
        return self._objects.get(req.name)

    async def _list(self, req: BlobList) -> List[str]:
        return sorted(n for n in self._objects if n.startswith(req.prefix))

    async def _delete(self, req: BlobDelete) -> None:
        self._objects.pop(req.name, None)
