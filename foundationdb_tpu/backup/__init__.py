from .container import BlobContainer
from .agent import BackupAgent

__all__ = ["BlobContainer", "BackupAgent"]
