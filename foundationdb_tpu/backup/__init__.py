from .container import BlobContainer
from .agent import BackupAgent
from .dr import DRAgent, lock_database, unlock_database

__all__ = ["BlobContainer", "BackupAgent", "DRAgent",
           "lock_database", "unlock_database"]
