"""Continuous DR: cluster-to-cluster asynchronous replication + switchover.

Re-design of fdbclient/DatabaseBackupAgent.actor.cpp (:2348) reduced to its
load-bearing shape on this framework's primitives:

  * start(): activate a mutation-log tag on the SOURCE (the same proxy
    circuit the file backup uses: every committed user mutation is copied
    into the tag), then take a chunked range snapshot of the source and
    write it STRAIGHT INTO the destination cluster, recording each chunk's
    read version (the reference's range-file versions);
  * a tailing actor peeks the tag, clips each mutation per destination
    range to versions AFTER that range's chunk version (exactly-once for
    atomic ops, same rule as restore), applies it to the destination in
    transactions, pops the tag, and advances `applied_version` — the
    destination continuously trails the source by the replication lag;
  * switchover(): lockDatabase on the source (proxies reject user commits
    with database_locked from the fence version on; lock-aware management
    transactions pass), drain the tag THROUGH the fence, stop tailing,
    and unlock the destination's role as the new primary. Every commit
    the source ever acknowledged is on the destination when it returns.

The lock fence is exact: a user commit sharing the lock transaction's
batch lands at the fence version and is still tagged + drained; anything
later is rejected at the proxy, so nothing acknowledged is lost.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..client.database import Database
from ..core import error, wire
from ..core.types import Mutation, MutationType, SINGLE_KEY_MUTATIONS
from ..server import system_keys
from ..server.log_system import LogSystemClient
from ..sim.loop import TaskPriority, delay, spawn

USER_END = b"\xff"
APPLY_BATCH = 200


async def lock_database(db: Database) -> int:
    """reference: lockDatabase (ManagementAPI.actor.cpp). Returns the lock
    commit version — the write fence: every user commit at a higher
    version is rejected with database_locked."""
    async def go(tr):
        tr.set_access_system_keys()
        tr.set(system_keys.DB_LOCK_KEY, b"locked")
    await db.run(go)
    tr = db.create_transaction()
    return await tr.get_read_version()


async def unlock_database(db: Database) -> None:
    async def go(tr):
        tr.set_access_system_keys()
        tr.set(system_keys.DB_LOCK_KEY, b"")
    await db.run(go)


class DRAgent:
    """One replication relationship: src -> dest."""

    def __init__(self, sim, src: Database, dest: Database):
        self.sim = sim
        self.src = src
        self.dest = dest
        self.tag: Optional[int] = None
        self.start_version: Optional[int] = None
        #: [(begin, end, chunk_version)] of the initial range sync
        self.ranges: List[Tuple[bytes, bytes, int]] = []
        #: destination reflects every source mutation <= this version
        self.applied_version: int = 0
        self._tailer = None
        self._stopped = False

    # -- source log access ----------------------------------------------------
    async def _log_client(self) -> LogSystemClient:
        from ..server.cluster_controller import CC_OPEN_DATABASE_TOKEN, OpenDatabaseRequest
        from ..server.leader_election import tally_leader_once
        from ..sim.network import Endpoint

        while True:
            leader = await tally_leader_once(self.src.net, self.src.client_addr,
                                             self.src.coordinator_addrs)
            if leader is not None:
                try:
                    info = await self.src.net.request(
                        self.src.client_addr,
                        Endpoint(leader.address, CC_OPEN_DATABASE_TOKEN),
                        OpenDatabaseRequest(), TaskPriority.DEFAULT_ENDPOINT,
                        timeout=1.0)
                except error.FDBError:
                    info = None
                if info is not None and info.log_config is not None:
                    return LogSystemClient(self.src.net, self.src.client_addr,
                                           info.log_config)
            await delay(0.5)

    # -- start: tag + initial sync + tail -------------------------------------
    async def start(self, chunks: int = 8) -> None:
        from .agent import claim_backup_tag

        self.tag = await self.src.run(claim_backup_tag)
        tr = self.src.create_transaction()
        self.start_version = await tr.get_read_version()
        # the destination is a replica while DR runs: lock it so stray
        # writers cannot diverge it (the reference locks the DR dest; the
        # agent's own applies are lock-aware), and CLEAR it — pre-existing
        # destination keys absent from the source would otherwise survive
        # replication and surface on the promoted primary
        await lock_database(self.dest)

        async def wipe(tr2):
            tr2.set_lock_aware()
            tr2.clear_range(b"", USER_END)
        await self.dest.run(wipe)

        # initial range sync, chunked; each chunk at its own fresh version
        bounds = [b""] + [bytes([(256 * i) // chunks])
                          for i in range(1, chunks)] + [USER_END]
        for i in range(chunks):
            while True:
                vtr = self.src.create_transaction()
                vc = await vtr.get_read_version()
                try:
                    rows = await self._read_chunk(bounds[i], bounds[i + 1], vc)
                    break
                except error.FDBError as e:
                    if e.code != error.transaction_too_old("").code:
                        raise
            for j in range(0, len(rows), APPLY_BATCH):
                batch = rows[j:j + APPLY_BATCH]

                async def put(tr2):
                    tr2.set_lock_aware()
                    for k, v in batch:
                        tr2.set(k, v)
                await self.dest.run(put)
            self.ranges.append((bounds[i], bounds[i + 1], vc))
        self.ranges.sort()
        self.applied_version = min(v for (_b, _e, v) in self.ranges)

        self._tailer = spawn(self._tail(), TaskPriority.DEFAULT_ENDPOINT,
                             name="drTail")

    async def _read_chunk(self, begin: bytes, end: bytes, version: int):
        rows: List[Tuple[bytes, bytes]] = []
        tr = self.src.create_transaction()
        tr.read_version = version
        at = begin
        while at < end:
            page = await tr.get_range(at, end, limit=1000, snapshot=True)
            rows.extend(page)
            if len(page) < 1000:
                break
            at = page[-1][0] + b"\x00"
        return rows

    # -- the tail -------------------------------------------------------------
    def _clip(self, m: Mutation) -> List[Tuple[int, Mutation]]:
        """(chunk_version, clipped mutation) parts per destination range —
        a mutation already inside a chunk's snapshot never re-applies
        (exactly-once for atomic ops, the restore rule)."""
        out = []
        if m.type == MutationType.CLEAR_RANGE:
            for b, e, vc in self.ranges:
                cb, ce = max(m.param1, b), min(m.param2, e)
                if cb < ce:
                    out.append((vc, Mutation(m.type, cb, ce)))
        else:
            for b, e, vc in self.ranges:
                if b <= m.param1 < e:
                    out.append((vc, m))
                    break
        return out

    async def _apply(self, entries) -> None:
        todo: List[Mutation] = []
        for v, muts in entries:
            for m in muts:
                todo.extend(cm for (vc, cm) in self._clip(m) if v > vc)
        for i in range(0, len(todo), APPLY_BATCH):
            batch = todo[i:i + APPLY_BATCH]

            async def go(tr):
                tr.set_lock_aware()
                for m in batch:
                    if m.type == MutationType.SET_VALUE:
                        tr.set(m.param1, m.param2)
                    elif m.type == MutationType.CLEAR_RANGE:
                        tr.clear_range(m.param1, m.param2)
                    elif m.type in SINGLE_KEY_MUTATIONS:
                        tr.atomic_op(m.param1, m.param2, m.type)
            await self.dest.run(go)

    async def _tail(self) -> None:
        floor = self.start_version
        client = None
        while not self._stopped:
            if client is None:   # re-resolve only after a peek error
                client = await self._log_client()
            try:
                reply = await client.peek(self.tag, floor + 1, timeout=2.0)
            except error.FDBError:
                client = None    # generation turnover / dead replica
                await delay(0.5)
                continue
            if reply.messages:
                await self._apply(reply.messages)
                client.pop(self.tag, reply.messages[-1][0])
            if reply.end_version > floor:
                floor = reply.end_version
                self.applied_version = max(self.applied_version, floor)
            else:
                await delay(0.25)

    async def wait_for(self, version: int, timeout: float = 60.0) -> None:
        """Block until the destination reflects source version `version`
        (the replication-lag bound)."""
        from ..sim.loop import now

        deadline = now() + timeout
        while self.applied_version < version:
            if now() > deadline:
                raise error.timed_out(
                    f"DR lag: applied {self.applied_version} < {version}")
            await delay(0.2)

    # -- switchover -----------------------------------------------------------
    async def switchover(self) -> int:
        """Fence the source, drain everything acknowledged, promote the
        destination. Returns the fence version. reference:
        DatabaseBackupAgent switchover (atomic via lockDatabase)."""
        fence = await lock_database(self.src)
        await self.wait_for(fence)
        self._stopped = True
        if self._tailer is not None:
            self._tailer.cancel()

        # retire the tag on the source (nothing pins the tlog queues) and
        # clear the active flag — only if it still holds OUR tag (never
        # stomp a backup/DR started after this one ended)
        async def stop(tr):
            tr.set_access_system_keys()
            active = await tr.get(system_keys.BACKUP_ACTIVE_KEY)
            if active and system_keys.decode_backup_active(active) == self.tag:
                tr.set(system_keys.BACKUP_ACTIVE_KEY, b"")
        await self.src.run(stop)
        client = await self._log_client()
        client.pop(self.tag, -1)
        # promote the destination: it serves user traffic now
        await unlock_database(self.dest)
        return fence
