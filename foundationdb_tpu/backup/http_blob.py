"""HTTP/1.1 blob store: server + client for real-mode backup targets.

Re-design of fdbrpc/HTTP.actor.cpp + BlobStore.actor.cpp reduced to the
load-bearing surface: a persistent-connection HTTP/1.1 client speaking
PUT/GET/DELETE on objects and a prefix LIST, and a matching asyncio
server storing objects under a directory (each object a file; names
escaped). A BackupAgent pointed at `blobstore://host:port` drives its
container IO through this client (bridged from the cooperative scheduler
into asyncio) — the real-transport backup target, wire-real sibling of
the sim's in-process container (backup/container.py). End-to-end:
`python -m foundationdb_tpu.real.cluster --backup`.

Protocol (a strict, tiny subset of S3-ish semantics):

    PUT    /obj/<name>        body = object bytes      -> 200
    GET    /obj/<name>                                  -> 200 body | 404
    DELETE /obj/<name>                                  -> 200
    GET    /list?prefix=<p>                             -> 200 newline-joined names
"""
from __future__ import annotations

import asyncio
import itertools
import os
import urllib.parse
from typing import List, Optional, Set

MAX_BODY = 64 << 20
# in-flight writes live one directory down; _esc escapes '.' precisely so
# no object name ('.tmp', '.', '..') can alias this entry or escape root
_TMP_DIR = ".tmp"


def io_timeout(nbytes: int) -> float:
    """Wire-time deadline for transferring `nbytes`: a 5s floor plus
    ~4MB/s of headroom, so a near-MAX_BODY object gets ~21s instead of a
    flat cap it can never clear. Callers that don't know the response
    size ahead of time budget for MAX_BODY."""
    return 5.0 + nbytes / (4 << 20)


def _esc(name: str) -> str:
    # quote() leaves '.' alone, which would let objects named '.', '..'
    # or '.tmp' collide with the filesystem's dot entries / the temp dir
    return urllib.parse.quote(name, safe="").replace(".", "%2E")


async def _read_headers(reader: asyncio.StreamReader) -> int:
    """Consume headers through the blank line; return the content-length
    (0 when absent). Malformed or negative lengths raise ValueError —
    both sides treat that as a framing error and drop the connection."""
    length = 0
    while True:
        h = await reader.readline()
        if h == b"":
            # EOF is NOT end-of-headers: dispatching a torn request as a
            # zero-length-body one would overwrite objects with b""
            raise ValueError("EOF inside headers")
        if h in (b"\r\n", b"\n"):
            return length
        k, _, v = h.decode("latin-1").partition(":")
        if k.strip().lower() == "content-length":
            length = int(v.strip())
            if length < 0:
                raise ValueError("negative content-length")


class HTTPBlobServer:
    """Objects-on-disk blob server; address is host:port."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.root = root
        self.host = host
        self.port = port
        self._ssl = ssl_context   # mutual-TLS listener when provided
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: Set[asyncio.StreamWriter] = set()
        self._tmp_seq = itertools.count()
        tmp = os.path.join(root, _TMP_DIR)
        os.makedirs(tmp, exist_ok=True)
        # sweep temp files orphaned by a crash between write and the
        # atomic os.replace — nothing can be in flight before start()
        for leftover in os.listdir(tmp):
            os.unlink(os.path.join(tmp, leftover))

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self._ssl)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # wait_closed() waits for every handler; unblock the ones
            # parked on an idle persistent connection
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
            self._server = None

    def _path(self, name: str) -> str:
        return os.path.join(self.root, _esc(name))

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _ver = line.decode().split(" ", 2)
                    length = await _read_headers(reader)
                except ValueError:
                    return
                if length > MAX_BODY:
                    # drain and refuse — the connection stays usable and
                    # the client sees a real status instead of a reset
                    # (which its reconnect would answer by re-sending
                    # the whole oversized body)
                    remaining = length
                    while remaining:
                        chunk = await reader.read(min(1 << 20, remaining))
                        if not chunk:
                            return
                        remaining -= len(chunk)
                    status, out = 413, b""
                else:
                    body = await reader.readexactly(length) if length else b""
                    try:
                        # disk work (fsync of up-to-64MB bodies, full-file
                        # reads, listdir) off the event loop
                        status, out = await asyncio.to_thread(
                            self._dispatch, method, target, body)
                    except OSError:
                        # a SERVER-side filesystem failure (ENOSPC,
                        # permissions) is an answerable error, not a
                        # reason to reset the socket
                        status, out = 500, b""
                writer.write(
                    b"HTTP/1.1 %d X\r\ncontent-length: %d\r\n\r\n"
                    % (status, len(out)))
                writer.write(out)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                ValueError):
            # ValueError: an over-long request line overflows the
            # StreamReader limit inside readline() itself
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    def _dispatch(self, method: str, target: str, body: bytes):
        url = urllib.parse.urlsplit(target)
        if url.path == "/list" and method == "GET":
            prefix = urllib.parse.parse_qs(url.query).get("prefix", [""])[0]
            # filter + sort on RAW names (matching the sim container's
            # order); names ride the wire ESCAPED (a raw name may contain
            # the newline the framing uses) and the client unquotes
            names = sorted(
                raw for raw in (urllib.parse.unquote(n)
                                for n in os.listdir(self.root)
                                if n != _TMP_DIR)
                if raw.startswith(prefix))
            return 200, "\n".join(_esc(n) for n in names).encode()
        if not url.path.startswith("/obj/"):
            return 404, b""
        name = urllib.parse.unquote(url.path[len("/obj/"):])
        path = self._path(name)
        if method == "PUT":
            tmp = os.path.join(self.root, _TMP_DIR,
                               "%d-%s" % (next(self._tmp_seq), _esc(name)))
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)   # objects appear atomically
            # the rename itself must be durable before we ack: without a
            # directory fsync a power failure rolls it back and the
            # startup sweep then reclaims the fully-written temp file
            self._sync_root()
            return 200, b""
        if method == "GET":
            try:
                with open(path, "rb") as f:
                    return 200, f.read()
            except FileNotFoundError:
                return 404, b""
        if method == "DELETE":
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            else:
                self._sync_root()   # an acked delete must survive a crash
            return 200, b""
        return 405, b""

    def _sync_root(self) -> None:
        fd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


class BlobClientShutdown(ConnectionError):
    """Raised by a client whose shutdown() has run: PERMANENT, unlike the
    transient connection errors the retry paths are allowed to chew on."""


class BlobHTTPError(IOError):
    """A non-200 answered by the blob server; `.status` lets callers
    separate permanent refusals (4xx: oversized body, bad request) from
    server-side failures — retrying a 413 forever can never succeed."""

    def __init__(self, op: str, name: str, status: int):
        super().__init__(f"blob {op} {name!r}: HTTP {status}")
        self.status = status


class HTTPBlobClient:
    """Persistent-connection blob client (the BlobStore client's role)."""

    def __init__(self, address: str, ssl_context=None):
        self.address = address
        self._ssl = ssl_context
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._shutdown = False
        # one connection, one in-flight request: concurrent callers
        # (asyncio.gather of puts) serialize here instead of interleaving
        # reads on the shared stream and desyncing every later response
        self._lock = asyncio.Lock()

    async def _conn(self):
        if self._shutdown:
            # a final shutdown() must stick: without this, an in-flight
            # request's transparent-reconnect path would resurrect the
            # connection after teardown and leak it
            raise BlobClientShutdown("client is shut down")
        if self._writer is None or self._writer.is_closing():
            host, port = self.address.rsplit(":", 1)
            r, w = await asyncio.open_connection(host, int(port),
                                                 ssl=self._ssl)
            if self._shutdown:
                # shutdown() ran while open_connection was in flight and
                # saw nothing to close — don't adopt the new socket
                w.close()
                raise BlobClientShutdown("client is shut down")
            self._reader, self._writer = r, w
        return self._reader, self._writer

    async def _once(self, method: str, target: str, body: bytes):
        r, w = await self._conn()
        w.write(b"%s %s HTTP/1.1\r\ncontent-length: %d\r\n\r\n"
                % (method.encode(), target.encode(), len(body)))
        if body:
            w.write(body)
        await w.drain()
        status_line = await r.readline()
        status = int(status_line.split()[1])
        length = await _read_headers(r)
        out = await r.readexactly(length) if length else b""
        return status, out

    async def _request(self, method: str, target: str, body: bytes = b"",
                       timeout: Optional[float] = None):
        async with self._lock:
            for attempt in (0, 1):   # one transparent reconnect
                try:
                    # the deadline starts HERE, after the lock: queue wait
                    # behind other transfers on the shared connection must
                    # not eat a request's wire-time budget
                    coro = self._once(method, target, body)
                    if timeout is not None:
                        return await asyncio.wait_for(coro, timeout)
                    return await coro
                except BlobClientShutdown:
                    raise   # permanent by contract: retrying is pointless
                except asyncio.CancelledError:
                    # a cancelled half-read would leave the persistent
                    # connection desynced (every later response off by
                    # one) — drop it before propagating
                    self.close()
                    raise
                except (ConnectionError, OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError, IndexError, ValueError):
                    # asyncio.TimeoutError is spelled explicitly: it only
                    # became an OSError on 3.11+, and a deadline that
                    # skipped close() would leave the connection desynced
                    self.close()
                    if attempt:
                        raise
            raise ConnectionError("unreachable")

    async def put(self, name: str, data: bytes,
                  timeout: Optional[float] = None) -> None:
        status, _ = await self._request("PUT", "/obj/" + _esc(name), data,
                                        timeout=timeout)
        if status != 200:
            raise BlobHTTPError("put", name, status)

    async def get(self, name: str,
                  timeout: Optional[float] = None) -> Optional[bytes]:
        status, body = await self._request("GET", "/obj/" + _esc(name),
                                           timeout=timeout)
        if status == 404:
            return None
        if status != 200:
            raise BlobHTTPError("get", name, status)
        return body

    async def delete(self, name: str,
                     timeout: Optional[float] = None) -> None:
        status, _ = await self._request("DELETE", "/obj/" + _esc(name),
                                        timeout=timeout)
        if status != 200:
            # a swallowed 500 here would make retention loops believe
            # the object is gone while it still exists
            raise BlobHTTPError("delete", name, status)

    async def list(self, prefix: str = "",
                   timeout: Optional[float] = None) -> List[str]:
        status, body = await self._request(
            "GET", "/list?prefix=" + urllib.parse.quote(prefix),
            timeout=timeout)
        if status != 200:
            raise BlobHTTPError("list", prefix, status)
        return [urllib.parse.unquote(n) for n in body.decode().split("\n") if n]

    def close(self) -> None:
        """Drop the current connection; the next request reconnects."""
        if self._writer is not None:
            self._writer.close()
        self._reader = self._writer = None

    def shutdown(self) -> None:
        """Final close: drops the connection AND refuses reconnects, so
        an in-flight retry can't bring the socket back."""
        self._shutdown = True
        self.close()
