"""Backup/restore agent v0 (reference: fdbclient/FileBackupAgent.actor.cpp
+ design/backup.md, reduced to its load-bearing shape):

  * start_backup(): a transaction sets `\\xff/backup/active` = a fresh log
    tag; from its commit version on, every proxy copies every committed
    user mutation into that tag (the metadata-drain circuit guarantees the
    hand-over version is exact). A log-mover actor peeks the tag, writes
    `log/<version>` objects to the container and pops as it goes.
  * snapshot(): TaskBucket tasks, one per key chunk, executed by N agent
    workers — each chunk reads at its own fresh version and writes a
    `range/<n>` object carrying it (the reference's versioned range
    files). Exactly-once chunk execution comes from the task bucket's
    transactional claims.
  * finish_backup(): picks the end version, waits for the log mover to
    pass it, writes the manifest, clears the active flag and retires the
    tag. Restorable = snapshot done AND logs cover every chunk version
    through end_version (tagging started before any chunk read).
  * restore(): loads every range object, then replays log mutations in
    version order clipped per range to versions AFTER that range's chunk
    version — atomic ops replay as atomic ops exactly once, so the
    restored state equals the source state at end_version exactly.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..bindings.fdb_api import Subspace
from ..bindings.task_bucket import TaskBucket
from ..core import buggify, error, wire
from ..core.types import Mutation, MutationType, SINGLE_KEY_MUTATIONS
from ..client.database import Database
from ..server import system_keys
from ..server.log_system import LogSystemClient
from ..sim.actors import all_of
from ..sim.loop import TaskPriority, delay, spawn
from ..sim.network import Endpoint
from . import container as blob

USER_END = b"\xff"
LOG_CHUNK_VERSIONS = 200_000


async def claim_backup_tag(tr) -> int:
    """Claim the (v0 single-slot) mutation-log tag inside `tr`: refuses to
    stomp a running backup/DR, allocates the next tag, sets the active
    flag. Shared by the file backup and DR agents — their claim protocols
    must never diverge."""
    tr.set_access_system_keys()
    active = await tr.get(system_keys.BACKUP_ACTIVE_KEY)
    if active and system_keys.decode_backup_active(active) is not None:
        raise error.client_invalid_operation(
            "a backup/DR already owns the mutation-log tag")
    seq = int(await tr.get(system_keys.BACKUP_SEQ_KEY) or b"0")
    tag = system_keys.FIRST_BACKUP_TAG - seq
    tr.set(system_keys.BACKUP_SEQ_KEY, str(seq + 1).encode())
    tr.set(system_keys.BACKUP_ACTIVE_KEY, system_keys.encode_backup_active(tag))
    return tag


BLOBSTORE_SCHEME = "blobstore://"


class BackupAgent:
    def __init__(self, sim, db: Database, container_addr: str):
        self.sim = sim
        self.db = db
        self.container_addr = container_addr
        self.tag: Optional[int] = None
        self.start_version: Optional[int] = None
        self.snapshot_version: Optional[int] = None
        self.end_version: Optional[int] = None
        self._log_floor: Optional[int] = None
        self._mover = None
        self._mover_error: Optional[BaseException] = None
        # container_addr is either a process address hosting BlobContainer
        # endpoints (sim and real transport alike), or a
        # "blobstore://host:port" HTTPBlobServer (backup/http_blob.py)
        # reached over asyncio — the latter only under the RealScheduler,
        # whose run loop lives inside an asyncio event loop
        self._http = None
        self._http_tasks: set = set()
        if container_addr.startswith(BLOBSTORE_SCHEME):
            from .http_blob import HTTPBlobClient
            self._http = HTTPBlobClient(container_addr[len(BLOBSTORE_SCHEME):])

    # -- container io --------------------------------------------------------
    def _aio(self, coro):
        """Bridge an HTTP container call into a scheduler Future (lazy
        import: the sim path never touches the real runtime). Deadlines
        live INSIDE HTTPBlobClient (per attempt, after its connection
        lock) — a wrapper timeout here would count queue wait behind
        other transfers against each request's wire-time budget."""
        from ..real.runtime import aio_to_sim

        return aio_to_sim(self._classify(coro), self._http_tasks)

    async def _classify(self, coro):
        """Map blob HTTP statuses onto FDBError vocabulary BEFORE the
        bridge collapses everything else into retryable connection_failed:
        a 4xx (oversized body, bad request) can never succeed on retry —
        the mover must die loudly, not re-send the same body forever. A
        5xx is the server's own transient trouble (momentary ENOSPC, an
        fsync hiccup answered as 500) and stays retryable, same as a
        dropped connection at the same moment would be."""
        from .http_blob import BlobHTTPError
        try:
            return await coro
        except BlobHTTPError as e:
            if 400 <= e.status < 500:
                raise error.client_invalid_operation(str(e)) from e
            raise error.connection_failed(str(e)) from e

    def close(self) -> None:
        """Release the container connection (blobstore:// targets keep a
        persistent one; the RPC path holds no state)."""
        if self._http is not None:
            self._http.close()

    async def _put(self, name: str, data: bytes) -> None:
        if self._http is not None:
            from .http_blob import io_timeout

            # the deadline scales with body size — a near-MAX_BODY chunk
            # can't clear a flat 5s cap, and cancel-reconnect-resend on a
            # legitimately slow large PUT would loop forever
            await self._aio(self._http.put(name, data,
                                           timeout=io_timeout(len(data))))
            return
        await self.db.net.request(
            self.db.client_addr, Endpoint(self.container_addr, blob.PUT_TOKEN),
            blob.BlobPut(name, data), TaskPriority.DEFAULT_ENDPOINT, timeout=5.0)

    async def _get(self, name: str) -> Optional[bytes]:
        if self._http is not None:
            from .http_blob import MAX_BODY, io_timeout

            # response size is unknown up front: budget for the largest
            # object the server can hold — a restore must be able to read
            # back anything a scaled-deadline put managed to write
            return await self._aio(self._http.get(
                name, timeout=io_timeout(MAX_BODY)))
        return await self.db.net.request(
            self.db.client_addr, Endpoint(self.container_addr, blob.GET_TOKEN),
            blob.BlobGet(name), TaskPriority.DEFAULT_ENDPOINT, timeout=5.0)

    async def _list(self, prefix: str) -> List[str]:
        if self._http is not None:
            from .http_blob import MAX_BODY, io_timeout

            return await self._aio(self._http.list(
                prefix, timeout=io_timeout(MAX_BODY)))
        return await self.db.net.request(
            self.db.client_addr, Endpoint(self.container_addr, blob.LIST_TOKEN),
            blob.BlobList(prefix), TaskPriority.DEFAULT_ENDPOINT, timeout=5.0)

    # -- log access ----------------------------------------------------------
    async def _log_client(self) -> LogSystemClient:
        """The current generation's log config, fetched like any client
        learns the cluster: from the CC's ServerDBInfo."""
        from ..server.cluster_controller import CC_OPEN_DATABASE_TOKEN, OpenDatabaseRequest
        from ..server.leader_election import tally_leader_once

        while True:
            leader = await tally_leader_once(self.db.net, self.db.client_addr,
                                             self.db.coordinator_addrs)
            if leader is not None:
                try:
                    info = await self.db.net.request(
                        self.db.client_addr,
                        Endpoint(leader.address, CC_OPEN_DATABASE_TOKEN),
                        OpenDatabaseRequest(), TaskPriority.DEFAULT_ENDPOINT,
                        timeout=1.0)
                except error.FDBError:
                    info = None
                if info is not None and info.log_config is not None:
                    return LogSystemClient(self.db.net, self.db.client_addr,
                                           info.log_config)
            await delay(0.5)

    # -- backup --------------------------------------------------------------
    async def start_backup(self) -> None:
        self.tag = await self.db.run(claim_backup_tag)
        tr = self.db.create_transaction()
        self.start_version = await tr.get_read_version()
        self._log_floor = self.start_version
        self._mover_error: Optional[BaseException] = None
        self._mover = spawn(self._log_mover(), TaskPriority.DEFAULT_ENDPOINT,
                            name="backupLogMover")

    async def _log_mover(self) -> None:
        """Continuously drain the backup tag into log/<version> objects.
        A permanent failure is RECORDED, not just raised — a spawned
        task's exception is unobserved, and finish_backup's wait on
        _log_floor would otherwise wedge silently."""
        try:
            await self._log_mover_loop()
        except Exception as e:  # noqa: BLE001 — ANY unobserved death wedges
            # finish_backup; OperationCancelled (BaseException) still
            # propagates so mover.cancel() stays silent
            self._mover_error = e

    async def _log_mover_loop(self) -> None:
        floor = self._log_floor
        while True:
            client = await self._log_client()
            try:
                reply = await client.peek(self.tag, floor + 1, timeout=2.0)
            except error.FDBError:
                await delay(0.5)
                continue
            if reply.messages:
                if buggify.buggify():
                    # mover stall mid-drain: the backup tag backs up at the
                    # tlogs (spill pressure) and restorability lags
                    await delay(1.0)
                name = "log/%020d" % reply.messages[0][0]
                try:
                    await self._put(name, wire.dumps(list(reply.messages)))
                except error.FDBError as e:
                    if not e.is_retryable():
                        raise   # permanent (e.g. 4xx): recorded by the
                        #         wrapper, surfaced by finish_backup
                    # transient container loss: nothing was popped, so the
                    # next peek re-serves the same messages — retry
                    await delay(0.5)
                    continue
                if buggify.buggify():
                    # crash-shaped duplicate: object written but pop lost —
                    # the next peek re-serves; restore must dedupe by version
                    continue
                client.pop(self.tag, reply.messages[-1][0])
            if reply.end_version > floor:
                floor = reply.end_version
                self._log_floor = floor
            else:
                await delay(0.25)

    async def snapshot(self, chunks: int = 8, workers: int = 3) -> None:
        """Range snapshot via TaskBucket chunk tasks. Each chunk reads at
        its OWN fresh version (the reference's range files each carry a
        version, design/backup.md): a chunk needs only its own reads to
        fit the MVCC window, however slow task claiming is. restore()
        replays log mutations per range from that range's chunk version,
        which keeps atomic ops exactly-once."""
        bucket = TaskBucket(Subspace((b"backup-tasks",)), timeout_seconds=20.0)
        bounds = [b""] + [bytes([(256 * i) // chunks]) for i in range(1, chunks)] + [USER_END]

        async def add_tasks(tr2):
            lo, hi = bucket.avail.range()
            tr2.clear_range(lo, hi)
            lo, hi = bucket.timeouts.range()
            tr2.clear_range(lo, hi)
            for i in range(chunks):
                bucket.add(tr2, i, {b"begin": bounds[i], b"end": bounds[i + 1]})
        await self.db.run(add_tasks)
        versions: List[int] = []

        async def worker(wid: int):
            while True:
                tr2 = self.db.create_transaction()
                try:
                    task = await bucket.get_one(tr2)
                    if task is None:
                        if await bucket.is_empty(tr2):
                            return
                        # only claimed tasks remain; resurface expired
                        # claims (a maybe-committed claim whose worker
                        # moved on would otherwise strand the task and
                        # busy-wait every worker here forever)
                        await bucket.check_timeouts(tr2)
                        await tr2.commit()
                        await delay(0.5)
                        continue
                    await tr2.commit()
                except error.FDBError as e:
                    if e.is_retryable() or e.is_maybe_committed():
                        continue
                    raise
                while True:
                    if buggify.buggify():
                        # slow chunk worker: its claim may expire and another
                        # worker re-executes — exactly-once must still hold
                        await delay(1.0)
                    vtr = self.db.create_transaction()
                    vc = await vtr.get_read_version()
                    try:
                        rows = await self._read_chunk(task.params[b"begin"],
                                                      task.params[b"end"], vc)
                        break
                    except error.FDBError as e:
                        if e.code != error.transaction_too_old("").code:
                            raise
                        # chunk outlived the window: fresh version, re-read
                await self._put("range/%04d" % task.id, wire.dumps({
                    "begin": task.params[b"begin"], "end": task.params[b"end"],
                    "version": vc, "rows": rows,
                }))
                versions.append(vc)

                async def done(tr3):
                    bucket.finish(tr3, task)
                await self.db.run(done)

        await all_of([
            spawn(worker(w), TaskPriority.DEFAULT_ENDPOINT, name=f"backupSnap{w}")
            for w in range(workers)
        ])
        self.snapshot_version = min(versions) if versions else self.start_version

    async def _read_chunk(self, begin: bytes, end: bytes, version: int):
        rows: List[Tuple[bytes, bytes]] = []
        tr = self.db.create_transaction()
        tr.read_version = version
        at = begin
        while at < end:
            page = await tr.get_range(at, end, limit=1000, snapshot=True)
            rows.extend(page)
            if len(page) < 1000:
                break
            at = page[-1][0] + b"\x00"
        return rows

    async def finish_backup(self) -> None:
        """Pick the end version, wait for log coverage, write the manifest,
        stop the proxies' copying and retire the tag."""
        tr = self.db.create_transaction()
        self.end_version = await tr.get_read_version()
        while self._log_floor < self.end_version:
            if self._mover_error is not None:
                raise self._mover_error
            await delay(0.25)

        async def stop(tr2):
            tr2.set_access_system_keys()
            tr2.set(system_keys.BACKUP_ACTIVE_KEY, b"")
        await self.db.run(stop)

        await self._put("manifest", wire.dumps({
            "snapshot_version": self.snapshot_version,
            "end_version": self.end_version,
            "start_version": self.start_version,
        }))
        self._mover.cancel()
        client = await self._log_client()
        client.pop(self.tag, -1)   # retire: nothing pins the queue front

    # -- restore -------------------------------------------------------------
    async def restore(self, dest: Database) -> int:
        """Restore the backup into `dest` (an empty keyspace). Returns the
        restored end version. Log mutations replay per range from that
        range's chunk version — a mutation already reflected in a chunk's
        snapshot (v <= chunk version) is never applied twice, which is
        what keeps atomic ops exact."""
        manifest = wire.loads(await self._get("manifest"))
        vend = manifest["end_version"]

        ranges: List[Tuple[bytes, bytes, int]] = []
        for name in await self._list("range/"):
            chunk = wire.loads(await self._get(name))
            ranges.append((chunk["begin"], chunk["end"], chunk["version"]))
            rows = chunk["rows"]
            for i in range(0, len(rows), 200):
                batch = rows[i:i + 200]

                async def put_batch(tr):
                    for k, v in batch:
                        tr.set(k, v)
                await dest.run(put_batch)
        ranges.sort()

        def clip(m: Mutation) -> List[Tuple[int, Mutation]]:
            """(chunk_version, clipped mutation) parts of m per range."""
            out = []
            if m.type == MutationType.CLEAR_RANGE:
                for b, e, vc in ranges:
                    cb, ce = max(m.param1, b), min(m.param2, e)
                    if cb < ce:
                        out.append((vc, Mutation(m.type, cb, ce)))
            else:
                for b, e, vc in ranges:
                    if b <= m.param1 < e:
                        out.append((vc, m))
                        break
            return out

        for name in await self._list("log/"):
            entries = wire.loads(await self._get(name))
            for v, muts in entries:
                if v > vend:
                    continue
                todo = [cm for m in muts for (vc, cm) in clip(m) if v > vc]
                for i in range(0, len(todo), 200):
                    batch = todo[i:i + 200]

                    async def apply_batch(tr):
                        for m in batch:
                            if m.type == MutationType.SET_VALUE:
                                tr.set(m.param1, m.param2)
                            elif m.type == MutationType.CLEAR_RANGE:
                                tr.clear_range(m.param1, m.param2)
                            elif m.type in SINGLE_KEY_MUTATIONS:
                                tr.atomic_op(m.param1, m.param2, m.type)
                    await dest.run(apply_batch)
        return vend
