"""Backup/restore agent v0 (reference: fdbclient/FileBackupAgent.actor.cpp
+ design/backup.md, reduced to its load-bearing shape):

  * start_backup(): a transaction sets `\\xff/backup/active` = a fresh log
    tag; from its commit version on, every proxy copies every committed
    user mutation into that tag (the metadata-drain circuit guarantees the
    hand-over version is exact). A log-mover actor peeks the tag, writes
    `log/<first-version>` objects (split under the container object cap;
    restore dedupes by version) and pops as it goes.
  * snapshot(): TaskBucket tasks, one per key chunk, executed by N agent
    workers — each chunk reads at its own fresh version and writes a
    version-prefixed PART SET `range/<id>/<version>-<part>` capped per
    object, sealed by a `range/<id>/<version>-done` marker (the
    reference's versioned range files); restore selects only the newest
    complete set per chunk, so a re-executed expired claim can never mix
    two executions' parts. Exactly-once chunk execution comes from the
    task bucket's transactional claims.
  * finish_backup(): picks the end version, waits for the log mover to
    pass it, writes the manifest, clears the active flag and retires the
    tag. Restorable = snapshot done AND logs cover every chunk version
    through end_version (tagging started before any chunk read).
  * restore(): loads every range object, then replays log mutations in
    version order clipped per range to versions AFTER that range's chunk
    version — atomic ops replay as atomic ops exactly once, so the
    restored state equals the source state at end_version exactly.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..bindings.fdb_api import Subspace
from ..bindings.task_bucket import TaskBucket
from ..core import buggify, error, wire
from ..core.types import Mutation, MutationType, SINGLE_KEY_MUTATIONS
from ..client.database import Database
from ..server import system_keys
from ..server.log_system import LogSystemClient
from ..sim.actors import all_of_cancelling
from ..sim.loop import TaskPriority, current_scheduler, delay, spawn
from ..sim.network import Endpoint
from . import container as blob

USER_END = b"\xff"
# objects never exceed this (half the blobstore's 64MB MAX_BODY): an
# unsplit peek reply or snapshot chunk above the container's cap would
# draw a 413 — non-retryable — and permanently kill the backup
CONTAINER_OBJECT_BYTES = 32 << 20
# 60s of a container that answers nothing: the mover escalates from
# transient-retry to a recorded permanent failure so finish_backup fails
# loudly instead of wedging on a dead blobstore. A wall-clock deadline,
# not an attempt count — a black-holing host makes each attempt cost up
# to two io_timeouts, so counting attempts would stretch "a minute" into
# over an hour
MOVER_FAILURE_ESCALATION_SECONDS = 60.0


async def claim_backup_tag(tr) -> int:
    """Claim the (v0 single-slot) mutation-log tag inside `tr`: refuses to
    stomp a running backup/DR, allocates the next tag, sets the active
    flag. Shared by the file backup and DR agents — their claim protocols
    must never diverge."""
    tr.set_access_system_keys()
    active = await tr.get(system_keys.BACKUP_ACTIVE_KEY)
    if active and system_keys.decode_backup_active(active) is not None:
        raise error.client_invalid_operation(
            "a backup/DR already owns the mutation-log tag")
    seq = int(await tr.get(system_keys.BACKUP_SEQ_KEY) or b"0")
    tag = system_keys.FIRST_BACKUP_TAG - seq
    tr.set(system_keys.BACKUP_SEQ_KEY, str(seq + 1).encode())
    tr.set(system_keys.BACKUP_ACTIVE_KEY, system_keys.encode_backup_active(tag))
    return tag


def _approx_row_bytes(kv) -> int:
    return len(kv[0]) + len(kv[1]) + 32


def _approx_message_bytes(msg) -> int:
    _v, muts = msg
    return 16 + sum(len(m.param1) + len(m.param2) + 16 for m in muts)


def _byte_chunks(items: list, size_of, cap: Optional[int] = None) -> List[list]:
    """Greedy split so each group stays under `cap`, sized by a cheap
    per-item ESTIMATE (encoding every item twice just to measure it would
    double serialization CPU on the backup hot path; the cap carries 32MB
    of slack against the container's MAX_BODY, so loose is fine). A lone
    item above cap still gets its own group. The cap resolves at call
    time so the module knob stays patchable."""
    if cap is None:
        cap = CONTAINER_OBJECT_BYTES
    groups: List[list] = []
    cur: list = []
    cur_bytes = 0
    for it in items:
        sz = size_of(it)
        if cur and cur_bytes + sz > cap:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(it)
        cur_bytes += sz
    if cur:
        groups.append(cur)
    return groups


class _ContainerRetry:
    """Transient-failure escalation shared by the log mover and the
    snapshot workers: retryable container errors retry on a 0.5s cadence
    and ESCALATE to permanent only when nothing has succeeded for
    MOVER_FAILURE_ESCALATION_SECONDS — any completed put resets the
    window, because partial progress means the container is answering
    (a flaky-but-alive store must not be declared dead)."""

    def __init__(self):
        self._first_fail: Optional[float] = None

    def succeeded(self) -> None:
        self._first_fail = None

    async def failed(self, e: "error.FDBError") -> None:
        """Re-raise if permanent or escalated; otherwise sleep the retry.
        The clock can't be interleaved by other failure domains: callers
        retry the failed put IN PLACE, so a failure streak never leaves
        the put loop except by success (reset) or by raising here."""
        if not e.is_retryable():
            raise e
        now = current_scheduler().time
        if self._first_fail is None:
            self._first_fail = now
        elif now - self._first_fail >= MOVER_FAILURE_ESCALATION_SECONDS:
            raise e
        await delay(0.5)


BLOBSTORE_SCHEME = "blobstore://"


class _RPCContainer:
    """Container IO against a process hosting BlobContainer endpoints
    (sim and real transport alike)."""

    def __init__(self, db: Database, addr: str):
        self.db = db
        self.addr = addr

    async def put(self, name: str, data: bytes) -> None:
        await self.db.net.request(
            self.db.client_addr, Endpoint(self.addr, blob.PUT_TOKEN),
            blob.BlobPut(name, data), TaskPriority.DEFAULT_ENDPOINT, timeout=5.0)

    async def get(self, name: str) -> Optional[bytes]:
        return await self.db.net.request(
            self.db.client_addr, Endpoint(self.addr, blob.GET_TOKEN),
            blob.BlobGet(name), TaskPriority.DEFAULT_ENDPOINT, timeout=5.0)

    async def list(self, prefix: str) -> List[str]:
        return await self.db.net.request(
            self.db.client_addr, Endpoint(self.addr, blob.LIST_TOKEN),
            blob.BlobList(prefix), TaskPriority.DEFAULT_ENDPOINT, timeout=5.0)

    def close(self) -> None:
        return None   # the RPC path holds no connection state


class _HTTPContainer:
    """Container IO against a blobstore://host:port HTTPBlobServer
    (backup/http_blob.py), reached over asyncio — only meaningful under
    the RealScheduler, whose run loop lives inside an asyncio event
    loop. Deadlines live INSIDE HTTPBlobClient (per attempt, after its
    connection lock) — a wrapper timeout here would count queue wait
    behind other transfers against each request's wire-time budget."""

    def __init__(self, address: str):
        # construction is the lazy-import point: this class only exists
        # for blobstore:// targets, so the sim path never pays for (or
        # needs) the real runtime / HTTP modules
        from .http_blob import MAX_BODY, HTTPBlobClient, io_timeout
        from ..real.runtime import aio_to_sim
        from ..real.tls import client_context

        # the blob path inherits the process TLS policy: mutual auth via
        # the shared CA (the subject DSL stays on the RPC transport)
        self.client = HTTPBlobClient(address, ssl_context=client_context())
        self._tasks: set = set()
        self._aio_to_sim = aio_to_sim
        self._io_timeout = io_timeout
        self._max_body = MAX_BODY

    def _aio(self, coro):
        """Bridge into a scheduler Future."""
        return self._aio_to_sim(self._classify(coro), self._tasks)

    async def _classify(self, coro):
        """Map blob HTTP statuses onto FDBError vocabulary BEFORE the
        bridge collapses everything else into retryable connection_failed:
        a 4xx (oversized body, bad request) can never succeed on retry —
        the mover must die loudly, not re-send the same body forever. A
        5xx is the server's own transient trouble (momentary ENOSPC, an
        fsync hiccup answered as 500) and stays retryable, same as a
        dropped connection at the same moment would be."""
        from .http_blob import BlobClientShutdown, BlobHTTPError
        try:
            return await coro
        except BlobClientShutdown as e:
            # a shut-down client is PERMANENT — retrying would spin a
            # still-running mover forever against a dead connection
            raise error.client_invalid_operation(str(e)) from e
        except BlobHTTPError as e:
            if 400 <= e.status < 500:
                raise error.client_invalid_operation(str(e)) from e
            raise error.connection_failed(str(e)) from e

    async def put(self, name: str, data: bytes) -> None:
        # the deadline scales with body size — a near-MAX_BODY chunk
        # can't clear a flat 5s cap, and cancel-reconnect-resend on a
        # legitimately slow large PUT would loop forever
        await self._aio(self.client.put(
            name, data, timeout=self._io_timeout(len(data))))

    async def get(self, name: str) -> Optional[bytes]:
        # response size is unknown up front: budget for the largest
        # object the server can hold — a restore must be able to read
        # back anything a scaled-deadline put managed to write
        return await self._aio(self.client.get(
            name, timeout=self._io_timeout(self._max_body)))

    async def list(self, prefix: str) -> List[str]:
        return await self._aio(self.client.list(
            prefix, timeout=self._io_timeout(self._max_body)))

    def close(self) -> None:
        # shutdown (not close): an in-flight retry must not resurrect
        # the connection after teardown
        self.client.shutdown()


class BackupAgent:
    def __init__(self, sim, db: Database, container_addr: str):
        self.sim = sim
        self.db = db
        self.tag: Optional[int] = None
        self.start_version: Optional[int] = None
        self.snapshot_version: Optional[int] = None
        self.end_version: Optional[int] = None
        self._log_floor: Optional[int] = None
        self._mover = None
        self._mover_error: Optional[BaseException] = None
        self._snapshot_chunks: Optional[int] = None
        if container_addr.startswith(BLOBSTORE_SCHEME):
            self._container = _HTTPContainer(
                container_addr[len(BLOBSTORE_SCHEME):])
        else:
            self._container = _RPCContainer(db, container_addr)

    # -- container io --------------------------------------------------------
    def close(self) -> None:
        """Release container resources (blobstore:// targets keep a
        persistent connection)."""
        self._container.close()

    async def _put(self, name: str, data: bytes) -> None:
        await self._container.put(name, data)

    async def _put_retrying(self, name: str, data: bytes,
                            retry: _ContainerRetry) -> None:
        """Put with in-place transient retry under `retry`'s escalation
        window (re-puts are idempotent everywhere this is used); raises
        on permanent or escalated failure."""
        while True:
            try:
                await self._put(name, data)
            except error.FDBError as e:
                await retry.failed(e)
                continue
            retry.succeeded()
            return

    async def _get(self, name: str) -> Optional[bytes]:
        return await self._container.get(name)

    async def _list(self, prefix: str) -> List[str]:
        return await self._container.list(prefix)

    # -- log access ----------------------------------------------------------
    async def _log_client(self) -> LogSystemClient:
        """The current generation's log config, fetched like any client
        learns the cluster: from the CC's ServerDBInfo."""
        from ..server.cluster_controller import CC_OPEN_DATABASE_TOKEN, OpenDatabaseRequest
        from ..server.leader_election import tally_leader_once

        while True:
            leader = await tally_leader_once(self.db.net, self.db.client_addr,
                                             self.db.coordinator_addrs)
            if leader is not None:
                try:
                    info = await self.db.net.request(
                        self.db.client_addr,
                        Endpoint(leader.address, CC_OPEN_DATABASE_TOKEN),
                        OpenDatabaseRequest(), TaskPriority.DEFAULT_ENDPOINT,
                        timeout=1.0)
                except error.FDBError:
                    info = None
                if info is not None and info.log_config is not None:
                    return LogSystemClient(self.db.net, self.db.client_addr,
                                           info.log_config)
            await delay(0.5)

    # -- backup --------------------------------------------------------------
    async def start_backup(self) -> None:
        self.tag = await self.db.run(claim_backup_tag)
        tr = self.db.create_transaction()
        self.start_version = await tr.get_read_version()
        self._log_floor = self.start_version
        self._mover_error: Optional[BaseException] = None
        self._mover = spawn(self._log_mover(), TaskPriority.DEFAULT_ENDPOINT,
                            name="backupLogMover")

    async def _log_mover(self) -> None:
        """Continuously drain the backup tag into log/<version> objects.
        A permanent failure is RECORDED, not just raised — a spawned
        task's exception is unobserved, and finish_backup's wait on
        _log_floor would otherwise wedge silently."""
        try:
            await self._log_mover_loop()
        except Exception as e:  # noqa: BLE001 — ANY unobserved death wedges
            # finish_backup; OperationCancelled (BaseException) still
            # propagates so mover.cancel() stays silent
            self._mover_error = e

    async def _log_mover_loop(self) -> None:
        floor = self._log_floor
        retry = _ContainerRetry()
        while True:
            client = await self._log_client()
            try:
                reply = await client.peek(self.tag, floor + 1, timeout=2.0)
            except error.FDBError:
                # log-side failure: retry the peek (the container
                # escalation clock is necessarily idle here — put
                # failures retry in place and never fall back to peek)
                await delay(0.5)
                continue
            if reply.messages:
                if buggify.buggify():
                    # mover stall mid-drain: the backup tag backs up at the
                    # tlogs (spill pressure) and restorability lags
                    await delay(1.0)
                # split below the container's object cap; each group is
                # named by its first version, so a crash-shaped re-peek
                # re-puts the same (or superset) objects — restore
                # dedupes by version either way. A transient failure
                # resumes at the failed GROUP (earlier puts are durable;
                # re-uploading them would multiply bandwidth per blip);
                # permanent/escalated errors re-raise out of retry
                # (recorded by the wrapper, surfaced by finish_backup).
                for group in _byte_chunks(list(reply.messages),
                                          _approx_message_bytes):
                    await self._put_retrying("log/%020d" % group[0][0],
                                             wire.dumps(group), retry)
                if buggify.buggify():
                    # crash-shaped duplicate: object written but pop lost —
                    # the next peek re-serves; restore must dedupe by version
                    continue
                client.pop(self.tag, reply.messages[-1][0])
            if reply.end_version > floor:
                floor = reply.end_version
                self._log_floor = floor
            else:
                await delay(0.25)

    async def snapshot(self, chunks: int = 8, workers: int = 3) -> None:
        """Range snapshot via TaskBucket chunk tasks. Each chunk reads at
        its OWN fresh version (the reference's range files each carry a
        version, design/backup.md): a chunk needs only its own reads to
        fit the MVCC window, however slow task claiming is. restore()
        replays log mutations per range from that range's chunk version,
        which keeps atomic ops exactly-once."""
        bucket = TaskBucket(Subspace((b"backup-tasks",)), timeout_seconds=20.0)
        bounds = [b""] + [bytes([(256 * i) // chunks]) for i in range(1, chunks)] + [USER_END]

        async def add_tasks(tr2):
            lo, hi = bucket.avail.range()
            tr2.clear_range(lo, hi)
            lo, hi = bucket.timeouts.range()
            tr2.clear_range(lo, hi)
            for i in range(chunks):
                bucket.add(tr2, i, {b"begin": bounds[i], b"end": bounds[i + 1]})
        await self.db.run(add_tasks)
        versions: List[int] = []

        async def worker(wid: int):
            while True:
                tr2 = self.db.create_transaction()
                try:
                    task = await bucket.get_one(tr2)
                    if task is None:
                        if await bucket.is_empty(tr2):
                            return
                        # only claimed tasks remain; resurface expired
                        # claims (a maybe-committed claim whose worker
                        # moved on would otherwise strand the task and
                        # busy-wait every worker here forever)
                        await bucket.check_timeouts(tr2)
                        await tr2.commit()
                        await delay(0.5)
                        continue
                    await tr2.commit()
                except error.FDBError as e:
                    if e.is_retryable() or e.is_maybe_committed():
                        continue
                    raise
                while True:
                    if buggify.buggify():
                        # slow chunk worker: its claim may expire and another
                        # worker re-executes — exactly-once must still hold
                        await delay(1.0)
                    vtr = self.db.create_transaction()
                    vc = await vtr.get_read_version()
                    try:
                        rows = await self._read_chunk(task.params[b"begin"],
                                                      task.params[b"end"], vc)
                        break
                    except error.FDBError as e:
                        if e.code != error.transaction_too_old("").code:
                            raise
                        # chunk outlived the window: fresh version, re-read
                # a VERSION-PREFIXED part set per execution: parts stay
                # under the container's object cap, and a re-executed
                # chunk (expired claim) writes a disjoint fresh set — no
                # mixing of two executions' parts. The "-done" marker
                # makes a set visible to restore only once complete;
                # stale/partial sets are simply never selected. Transient
                # container loss retries (re-puts are idempotent: same
                # names, same rows) under the same escalation window the
                # log mover gets — one blip must not kill the backup.
                parts = _byte_chunks(rows, _approx_row_bytes) or [[]]
                retry = _ContainerRetry()
                pb = task.params[b"begin"]
                for j, part in enumerate(parts):
                    pe = (parts[j + 1][0][0] if j + 1 < len(parts)
                          else task.params[b"end"])
                    await self._put_retrying(
                        "range/%04d/%012d-%03d" % (task.id, vc, j),
                        wire.dumps({"begin": pb, "end": pe,
                                    "version": vc, "rows": part}),
                        retry)
                    pb = pe
                await self._put_retrying(
                    "range/%04d/%012d-done" % (task.id, vc),
                    wire.dumps(len(parts)), retry)
                versions.append(vc)

                async def done(tr3):
                    bucket.finish(tr3, task)
                await self.db.run(done)

        try:
            await all_of_cancelling([
                spawn(worker(w), TaskPriority.DEFAULT_ENDPOINT,
                      name=f"backupSnap{w}")
                for w in range(workers)
            ])
        except Exception:   # noqa: BLE001 — ANY worker death, not just
            # FDBError (a serialization TypeError pins the tag the same
            # way): a dead snapshot is a dead backup — release the tag
            # claim (and stop the mover) rather than wedge the slot
            await self.abort_backup()
            raise
        self._snapshot_chunks = chunks
        self.snapshot_version = min(versions) if versions else self.start_version

    async def _read_chunk(self, begin: bytes, end: bytes, version: int):
        rows: List[Tuple[bytes, bytes]] = []
        tr = self.db.create_transaction()
        tr.read_version = version
        at = begin
        while at < end:
            page = await tr.get_range(at, end, limit=1000, snapshot=True)
            rows.extend(page)
            if len(page) < 1000:
                break
            at = page[-1][0] + b"\x00"
        return rows

    async def finish_backup(self) -> None:
        """Pick the end version, wait for log coverage, write the manifest,
        stop the proxies' copying and retire the tag."""
        tr = self.db.create_transaction()
        self.end_version = await tr.get_read_version()
        while self._log_floor < self.end_version:
            if self._mover_error is not None:
                # release the tag claim before surfacing — a failed
                # backup must not pin the mutation-log slot (and the
                # tlogs' spill) forever
                await self.abort_backup()
                raise self._mover_error
            await delay(0.25)

        async def stop(tr2):
            tr2.set_access_system_keys()
            tr2.set(system_keys.BACKUP_ACTIVE_KEY, b"")
        await self.db.run(stop)

        # same transient-retry window as every other container write: a
        # single blip at manifest time must not tear down a completed
        # backup. Escalated/permanent failure aborts — without the
        # manifest the backup is unrestorable anyway, so don't leave the
        # mover alive and the tag pinned on top.
        try:
            await self._put_retrying("manifest", wire.dumps({
                "snapshot_version": self.snapshot_version,
                "end_version": self.end_version,
                "start_version": self.start_version,
                "chunks": self._snapshot_chunks,
            }), _ContainerRetry())
        except Exception:   # noqa: BLE001 — escalated OR foreign: either
            # way the backup is unrestorable without the manifest; don't
            # leave the mover alive and the tag pinned on top
            await self.abort_backup()
            raise
        self._mover.cancel()
        client = await self._log_client()
        client.pop(self.tag, -1)   # retire: nothing pins the queue front

    async def abort_backup(self) -> None:
        """Best-effort teardown after a FAILED backup (reference:
        fdbbackup abort): stop the mover, release the single mutation-log
        slot so a new backup/DR can claim it, and retire the tag so the
        tlogs stop spilling it. Callers hit this via finish_backup's
        mover-error edge, or directly after snapshot() raises."""
        if self._mover is not None:
            self._mover.cancel()

        async def clear(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.BACKUP_ACTIVE_KEY, b"")
        try:
            await self.db.run(clear)
        except error.FDBError:
            pass
        try:
            client = await self._log_client()
            client.pop(self.tag, -1)
        except error.FDBError:
            pass

    # -- restore -------------------------------------------------------------
    async def restore(self, dest: Database) -> int:
        """Restore the backup into `dest` (an empty keyspace). Returns the
        restored end version. Log mutations replay per range from that
        range's chunk version — a mutation already reflected in a chunk's
        snapshot (v <= chunk version) is never applied twice, which is
        what keeps atomic ops exact."""
        raw_manifest = await self._get("manifest")
        if raw_manifest is None:
            raise error.client_invalid_operation(
                "container has no manifest — backup not finished?")
        manifest = wire.loads(raw_manifest)
        vend = manifest["end_version"]

        # pick, per chunk id, the NEWEST complete part set: a re-executed
        # chunk leaves older (or unfinished) version-prefixed sets behind,
        # and loading two executions' parts together would mix snapshot
        # versions within one key range
        def parse_part(name: str):
            """(cid, version) from range/<cid>/<version>-<part|done>, or
            None for anything else — a foreign or legacy-named object in
            the container must be ignored, not crash the restore."""
            cid, sep, vtag = name[len("range/"):].partition("/")
            if not sep:
                return None
            try:
                return cid, int(vtag.split("-")[0])
            except ValueError:
                return None

        names = await self._list("range/")
        newest: Dict[str, int] = {}
        for name in names:
            parsed = parse_part(name)
            if parsed is None or not name.endswith("-done"):
                continue
            cid, vc = parsed
            newest[cid] = max(newest.get(cid, -1), vc)
        n_chunks = manifest.get("chunks")
        if n_chunks is not None:
            # a WHOLE chunk's set (marker included) vanishing would
            # otherwise skip silently — chunk ids are 0..chunks-1
            expected_cids = {"%04d" % i for i in range(n_chunks)}
            if set(newest) != expected_cids:
                raise error.client_invalid_operation(
                    "container chunk sets don't match the manifest: "
                    f"missing {sorted(expected_cids - set(newest))}, "
                    f"unexpected {sorted(set(newest) - expected_cids)}")
        listed = set(names)
        part_names: List[str] = []
        for cid, vc in sorted(newest.items()):
            # the marker's stored part count is the completeness check: a
            # lost/omitted part object must fail the restore loudly, not
            # silently drop its key subrange from snapshot AND log replay
            n_parts = wire.loads(await self._get(
                "range/%s/%012d-done" % (cid, vc)))
            expect = ["range/%s/%012d-%03d" % (cid, vc, j)
                      for j in range(n_parts)]
            missing = [n for n in expect if n not in listed]
            if missing:
                raise error.client_invalid_operation(
                    f"chunk {cid}: sealed part set at version {vc} is "
                    f"missing {len(missing)} of {n_parts} parts")
            part_names.extend(expect)
        if names and not part_names:
            # range objects exist but none parse to a complete set:
            # restoring "successfully" with zero rows would be
            # data-loss-shaped — refuse loudly instead
            raise error.client_invalid_operation(
                "container holds range objects but no complete part set "
                "was recognized (foreign or corrupt format)")

        ranges: List[Tuple[bytes, bytes, int]] = []
        for name in part_names:
            chunk = wire.loads(await self._get(name))
            ranges.append((chunk["begin"], chunk["end"], chunk["version"]))
            rows = chunk["rows"]
            for i in range(0, len(rows), 200):
                batch = rows[i:i + 200]

                async def put_batch(tr):
                    for k, v in batch:
                        tr.set(k, v)
                await dest.run(put_batch)
        ranges.sort()
        begins = [b for b, _e, _vc in ranges]

        def clip(m: Mutation) -> List[Tuple[int, Mutation]]:
            """(chunk_version, clipped mutation) parts of m per range.
            Bisect over the sorted disjoint ranges — part-splitting put
            the list at ~object-count, and a linear scan per mutation
            would make log replay O(mutations x parts)."""
            out = []
            if m.type == MutationType.CLEAR_RANGE:
                i = max(bisect.bisect_right(begins, m.param1) - 1, 0)
                for b, e, vc in ranges[i:]:
                    if b >= m.param2:
                        break
                    cb, ce = max(m.param1, b), min(m.param2, e)
                    if cb < ce:
                        out.append((vc, Mutation(m.type, cb, ce)))
            else:
                i = bisect.bisect_right(begins, m.param1) - 1
                if i >= 0:
                    b, e, vc = ranges[i]
                    if b <= m.param1 < e:
                        out.append((vc, m))
            return out

        seen_versions: set = set()
        for name in await self._list("log/"):
            entries = wire.loads(await self._get(name))
            for v, muts in entries:
                if v > vend or v in seen_versions:
                    # dedupe: a crash-shaped re-put after a shifted group
                    # split can repeat a version across log objects, and
                    # replaying it twice would double-apply atomic ops
                    continue
                seen_versions.add(v)
                todo = [cm for m in muts for (vc, cm) in clip(m) if v > vc]
                for i in range(0, len(todo), 200):
                    batch = todo[i:i + 200]

                    async def apply_batch(tr):
                        for m in batch:
                            if m.type == MutationType.SET_VALUE:
                                tr.set(m.param1, m.param2)
                            elif m.type == MutationType.CLEAR_RANGE:
                                tr.clear_range(m.param1, m.param2)
                            elif m.type in SINGLE_KEY_MUTATIONS:
                                tr.atomic_op(m.param1, m.param2, m.type)
                    await dest.run(apply_batch)
        return vend
