"""Multi-shard conflict detection over a device mesh.

Design (TPU-first re-think of the reference's multi-Resolver scheme,
fdbserver/Resolver.actor.cpp + MasterProxyServer.actor.cpp:263-316):

  * The keyspace is statically partitioned by split keys into S spans,
    one per device ("shard" mesh axis) — the analog of the proxy's
    `keyResolvers` range map.
  * Each device holds the boundary table restricted to its span; the host
    routes and *clips* every read/write conflict range to the shards it
    intersects (ResolutionRequestBuilder::addTransaction's splitting) — all
    shared with the single-chip engine via RoutedConflictEngineBase.
  * One jitted shard_map step: each shard runs phases 1-2 locally and
    keeps its bit-packed overlap edges + per-key group ids shard-local;
    only [T] txn-space vectors cross the ICI — one psum of history-hit
    bitmaps, then one 8KB psum of blocked-txn counts per fixpoint
    iteration.
    Every shard computes the identical earlier-in-batch-wins fixpoint
    from the reduced values (lockstep while_loop) and applies its own
    clipped committed writes. A handful of tiny collective rounds per
    batch — the reference needs a full RPC round-trip per resolver plus
    a proxy-side min-combine (MasterProxyServer.actor.cpp:489-500).

Clipping is exact: shard spans are disjoint and cover the keyspace, so a
read overlaps history (or a write) globally iff some shard observes the
overlap on clipped ranges, and per-span tables together represent exactly
the global version-interval map.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax >= 0.4.35 exposes shard_map at top level; 0.4.3x still keeps it in
# experimental — accept either so the pinned container jax keeps working.
# check_rep=False: the replication checker has no rule for while_loop (the
# commit fixpoint) on these jax versions; the step's own psum discipline is
# what guarantees replicated verdicts, so the static check is advisory here.
_raw_shard_map = getattr(jax, "shard_map", None)
if _raw_shard_map is None:
    from jax.experimental.shard_map import shard_map as _raw_shard_map


def _shard_map(f, **kw):
    try:
        return _raw_shard_map(f, check_rep=False, **kw)
    except TypeError:   # newer jax dropped/renamed check_rep
        return _raw_shard_map(f, **kw)

from ..core.types import Version
from ..ops import conflict_kernel as ck
from ..ops.conflict_kernel import KernelConfig
from ..core.keyshard import KeyShardMap
from ..ops.host_engine import RoutedConflictEngineBase, donate_state_kwargs

__all__ = ["KeyShardMap", "ShardedConflictEngine", "make_sharded_step",
           "make_mesh_scan_step", "make_mesh_exchange_step"]


def make_sharded_step(cfg: KernelConfig, mesh: Mesh, axis: str = "shard"):
    """Jitted shard_map step over `mesh[axis]`.

    Inputs are stacked along a leading device axis of size S:
      state leaves  [S, ...]   per-shard boundary tables
      batch leaves  [S, ...]   per-shard clipped batches (t_ok/t_too_old/
                               now/gc replicated: identical rows)
    Returns (state', out) with the same stacking; out["status"] rows are
    identical across shards (verdicts are a pure function of the psum'd
    bitmaps)."""

    def step(state, batch):
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        hist_hits, ovp, wpos = ck.local_phases(cfg, state, batch)
        # The ICI allreduces of the north star: one [T] psum of per-shard
        # history-hit bitmaps up front, then one [T] psum of blocked-txn
        # counts per fixpoint iteration (8KB each; the bit-packed overlap
        # edges never cross the ICI). Counts are additive across disjoint
        # key shards, and every shard sees identical reduced values, so the
        # while_loop runs in lockstep.
        hist_hits = lax.psum(hist_hits, axis)
        committed = ck.commit_fixpoint(
            cfg, batch["t_ok"], hist_hits, ovp, batch,
            allreduce=lambda x: lax.psum(x, axis),
        )
        new_state, overflow, reclaimed = ck.apply_writes_and_gc(
            cfg, state, batch, committed, wpos)
        out = {
            "status": ck.status_of(batch["t_too_old"], committed),
            "overflow": overflow,
            "n": new_state["n"],
        }
        if cfg.heat_buckets > 0:
            # per-shard aggregate (each shard's own table delimits its
            # buckets); stays shard-local — the host merges by boundary key
            out["heat"] = ck.heat_of(cfg, new_state, batch, committed, ovp,
                                     reclaimed)
        return jax.tree.map(lambda x: jnp.asarray(x)[None], (new_state, out))

    mapped = _shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(axis))
    return jax.jit(mapped, **donate_state_kwargs())


def make_sharded_scan_step(cfg: KernelConfig, mesh: Mesh, n_chunks: int,
                           axis: str = "shard"):
    """Fused multi-chunk variant of make_sharded_step: batch leaves are
    stacked [S, n_chunks, ...] (shard axis leading for the P(axis) specs)
    and ONE shard_map program lax.scans the per-chunk step, threading each
    shard's boundary table across chunks — one collective-bearing dispatch
    per batch instead of one per chunk. Scan order == per-chunk dispatch
    order, so status/overflow stacks are bit-identical."""

    def step(state, batches):
        state = jax.tree.map(lambda x: x[0], state)
        batches = jax.tree.map(lambda x: x[0], batches)   # leaves [C, ...]

        def body(st, b):
            hist_hits, ovp, wpos = ck.local_phases(cfg, st, b)
            hist_hits = lax.psum(hist_hits, axis)
            committed = ck.commit_fixpoint(
                cfg, b["t_ok"], hist_hits, ovp, b,
                allreduce=lambda x: lax.psum(x, axis),
            )
            new_state, overflow, reclaimed = ck.apply_writes_and_gc(
                cfg, st, b, committed, wpos)
            heat = (ck.heat_of(cfg, new_state, b, committed, ovp, reclaimed)
                    if cfg.heat_buckets > 0 else {})
            return new_state, (ck.status_of(b["t_too_old"], committed),
                               overflow, heat)

        state, (status, overflow, heat) = lax.scan(body, state, batches)
        out = {"status": status, "overflow": overflow}
        if cfg.heat_buckets > 0:
            out["heat"] = heat          # leaves [C, ...], shard-local
        return jax.tree.map(lambda x: jnp.asarray(x)[None], (state, out))

    mapped = _shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(axis))
    return jax.jit(mapped, **donate_state_kwargs())


def make_mesh_scan_step(cfg: KernelConfig, mesh: Mesh, axis: str = "shard"):
    """Phase-1 half of the mesh engine's split dispatch unit: shard-LOCAL
    scans only — history probes, overlap edges, write positions — with NO
    collective anywhere in the program. Returns the un-jitted shard_map
    (the mesh engine AOT-lowers it per bucket so the progcache can serve
    it); outputs keep the [S, ...] stacking and stay device-resident,
    feeding make_mesh_exchange_step without a host round-trip. Because
    this program touches no other shard's data, the NEXT batch's scan can
    run while the PREVIOUS batch's exchange collectives drain — the
    overlap the mesh engine's double-buffered ring exploits."""

    def scan(state, batch):
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        hist_hits, ovp, wpos = ck.local_phases(cfg, state, batch)
        return jax.tree.map(lambda x: jnp.asarray(x)[None],
                            (hist_hits, ovp, wpos))

    return _shard_map(scan, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=P(axis))


def make_mesh_exchange_step(cfg: KernelConfig, mesh: Mesh, axis: str = "shard"):
    """Exchange + commit half of the mesh engine's split dispatch unit:
    ALL the cross-shard traffic of one batch — one [T] psum of the
    per-shard history-hit planes, one [T] psum of blocked-txn counts per
    fixpoint iteration (counts are additive across disjoint key shards,
    so every shard runs the identical lockstep while_loop) — then the
    shard-local apply of globally-committed writes. Same stacking
    conventions as make_sharded_step; status rows are replicated across
    shards. Un-jitted shard_map (AOT-lowered by the engine)."""

    def exchange(state, batch, hist_local, ovp, wpos):
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        hist_local = hist_local[0]
        ovp = jax.tree.map(lambda x: x[0], ovp)
        wpos = jax.tree.map(lambda x: x[0], wpos)
        hist = lax.psum(hist_local, axis)
        committed = ck.commit_fixpoint(
            cfg, batch["t_ok"], hist, ovp, batch,
            allreduce=lambda x: lax.psum(x, axis),
        )
        new_state, overflow, reclaimed = ck.apply_writes_and_gc(
            cfg, state, batch, committed, wpos)
        out = {
            "status": ck.status_of(batch["t_too_old"], committed),
            "overflow": overflow,
        }
        if cfg.heat_buckets > 0:
            out["heat"] = ck.heat_of(cfg, new_state, batch, committed, ovp,
                                     reclaimed)
        return jax.tree.map(lambda x: jnp.asarray(x)[None], (new_state, out))

    return _shard_map(exchange, mesh=mesh,
                      in_specs=(P(axis),) * 5, out_specs=P(axis))


def make_sharded_split_steps(cfg: KernelConfig, mesh: Mesh, axis: str = "shard"):
    """Detect / fix / apply as separate shard_map programs, for the host
    long-key tier: the outer host fixpoint needs global verdicts BEFORE any
    tier (device shards included) applies writes. Same stacking conventions
    as make_sharded_step; committed is replicated across shards."""

    def detect(state, batch):
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        hist_hits, ovp, wpos = ck.local_phases(cfg, state, batch)
        return jax.tree.map(lambda x: jnp.asarray(x)[None], (hist_hits, ovp, wpos))

    def fix(t_ok, hist_local, ovp, batch):
        t_ok = t_ok[0]
        hist_local = hist_local[0]
        ovp = jax.tree.map(lambda x: x[0], ovp)
        batch = jax.tree.map(lambda x: x[0], batch)
        hist = lax.psum(hist_local, axis)
        committed = ck.commit_fixpoint(
            cfg, t_ok, hist, ovp, batch,
            allreduce=lambda x: lax.psum(x, axis),
        )
        return committed[None]

    def apply(state, batch, committed, wpos):
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        committed = committed[0]
        wpos = jax.tree.map(lambda x: x[0], wpos)
        new_state, overflow, _ = ck.apply_writes_and_gc(
            cfg, state, batch, committed, wpos)
        return jax.tree.map(lambda x: jnp.asarray(x)[None], (new_state, overflow))

    detect_m = jax.jit(_shard_map(
        detect, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(axis)))
    fix_m = jax.jit(_shard_map(
        fix, mesh=mesh, in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis)))
    apply_m = jax.jit(_shard_map(
        apply, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)), out_specs=P(axis)),
        **donate_state_kwargs())
    return detect_m, fix_m, apply_m


class ShardedConflictEngine(RoutedConflictEngineBase):
    """Multi-device ConflictSet engine: same resolve() contract as
    OracleConflictEngine/JaxConflictEngine, state sharded over a Mesh."""

    name = "sharded"

    def __init__(
        self,
        cfg: KernelConfig = KernelConfig(),
        shards: KeyShardMap | None = None,
        mesh: Mesh | None = None,
        initial_version: Version = 0,
        ladder=None,
        scan_sizes=(2, 4, 8),
        arena: bool = True,
        history_search=None,
        heat_buckets=None,
        device_time_sample_rate=None,
        history_structure=None,
    ):
        if mesh is None:
            devs = jax.devices()
            n = len(devs) if shards is None else shards.n_shards
            mesh = jax.make_mesh((n,), ("shard",), devices=devs[:n])
        (n_devices,) = mesh.devices.shape
        super().__init__(cfg, shards or KeyShardMap.uniform(n_devices),
                         ladder=ladder, scan_sizes=scan_sizes, arena=arena,
                         history_search=history_search,
                         heat_buckets=heat_buckets,
                         device_time_sample_rate=device_time_sample_rate,
                         history_structure=history_structure)
        cfg = self.cfg   # base resolved the history-search mode into it
        assert self.n_shards == n_devices
        self.mesh = mesh
        self._sharding = NamedSharding(mesh, P("shard"))
        self._detect_m, self._fix_m, self._apply_m = make_sharded_split_steps(cfg, mesh)
        self._reset_device_state(self._rel(initial_version))
        from ..ops.oracle import VersionIntervalMap

        self.tier_map = VersionIntervalMap(initial_version)

    def _stack_shards(self, per_shard: List[Dict]):
        stacked = jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]), *per_shard)
        return jax.tree.map(lambda x: jax.device_put(x, self._sharding), stacked)

    def _reset_device_state(self, version_rel: int) -> None:
        per = [
            ck.initial_state(self.cfg, version_rel=version_rel, first_key=self.shards.begins[s])
            for s in range(self.n_shards)
        ]
        self.state = self._stack_shards(per)

    def _device_states_for_snapshot(self):
        return [jax.tree.map(lambda x, s=s: np.asarray(x)[s], self.state)
                for s in range(self.n_shards)]

    # -- bucketed program cache (RoutedConflictEngineBase) -------------------
    def _progcache_fingerprint(self) -> str:
        # programs bake the mesh topology (shard_map over self.mesh): the
        # cache key must separate an S-shard layout from any other
        return f"mesh:{self.n_shards}/{len(jax.devices())}"

    def _make_program(self, bucket: KernelConfig, n_chunks: int):
        # jit-based (not AOT): pinning input shardings through an AOT
        # .lower() of a shard_map is version-fragile on the pinned jax;
        # _warm_program executes a state-preserving no-op batch instead, so
        # warmup still front-loads the compile and steady state runs from
        # the jit cache.
        if n_chunks == 1:
            return make_sharded_step(bucket, self.mesh)
        return make_sharded_scan_step(bucket, self.mesh, n_chunks)

    def _warm_program(self, bucket: KernelConfig, n_chunks: int, prog) -> None:
        S = self.n_shards
        stack = (S,) if n_chunks == 1 else (S, n_chunks)
        struct = ck.batch_struct(bucket, stack=stack)
        # All-invalid rows, t_ok all-false, now == gc == 0: proven a bit-
        # exact no-op on the interval table (no union rows, no GC branch).
        noop = jax.tree.map(
            lambda x: jax.device_put(np.zeros(x.shape, x.dtype), self._sharding),
            struct)
        self.state, out = prog(self.state, noop)
        np.asarray(out["overflow"])   # block: compile + first run complete

    def _dispatch_unit(self, bucket: KernelConfig,
                       per_chunks: List[List[Dict[str, np.ndarray]]]):
        C = len(per_chunks)
        prog = self._program(bucket, C)
        if C == 1:
            batch = self._stack_shards(per_chunks[0])
        else:
            # [S, C, ...]: shard axis leading for the P("shard") specs
            stacked = {
                k: np.stack([
                    np.stack([np.asarray(pc[s][k]) for pc in per_chunks])
                    for s in range(self.n_shards)
                ])
                for k in per_chunks[0][0]
            }
            batch = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding), stacked)
        self.state, out = prog(self.state, batch)
        status_dev, overflow_dev = out["status"], out["overflow"]
        heat_dev = out.get("heat")   # shard-local, [S, ...] or [S, C, ...]
        heat_layout = "s" if C == 1 else "sc"
        heat_base, heat_version = self.base, self._heat_version
        keep = batch

        def force() -> Tuple[np.ndarray, bool]:
            status = np.asarray(status_dev)[0]   # identical across shards
            overflow = bool(np.any(np.asarray(overflow_dev)))
            if heat_dev is not None:
                self._merge_heat(heat_dev, version=heat_version,
                                 base=heat_base, layout=heat_layout)
            _ = keep
            return (status[None] if C == 1 else status), overflow

        return force

    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        status, overflow = self._dispatch_unit(self.cfg, [per_shard])()
        return status[0], overflow

    # -- split-step path (host long-key tier) --------------------------------
    def _run_detect(self, per_shard):
        batch = self._stack_shards(per_shard)
        hist, ovp, wpos = self._detect_m(self.state, batch)
        return {"batch": batch, "hist": hist, "ovp": ovp, "wpos": wpos}

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        t_ok_stacked = jax.device_put(
            np.broadcast_to(t_ok, (self.n_shards,) + t_ok.shape).copy(),
            self._sharding,
        )
        committed = self._fix_m(t_ok_stacked, ctx["hist"], ctx["ovp"], ctx["batch"])
        return np.asarray(committed)[0]

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        cm = jax.device_put(
            np.broadcast_to(committed, (self.n_shards,) + committed.shape).copy(),
            self._sharding,
        )
        self.state, overflow = self._apply_m(self.state, ctx["batch"], cm, ctx["wpos"])
        t_too_old = np.asarray(ctx["batch"]["t_too_old"])[0]
        status = np.asarray(ck.status_of(t_too_old, committed))
        return status, bool(np.any(np.asarray(overflow)))
