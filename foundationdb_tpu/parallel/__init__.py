"""Device-mesh parallelism for the TPU conflict-detection engine.

The reference scales conflict detection by key-range sharding across Resolver
processes (SURVEY.md §2.6.2; fdbserver/MasterProxyServer.actor.cpp:263-316,
masterserver.actor.cpp:919-977). Here the same partitioning maps onto a
jax.sharding.Mesh: one key-range shard per TPU core, per-shard interval
tables resident in that core's HBM, and the commit verdict combined by
allreducing conflict bitmaps over ICI (psum inside shard_map).
"""
from .sharding import KeyShardMap, ShardedConflictEngine, make_sharded_step

__all__ = ["ShardedConflictEngine", "KeyShardMap", "make_sharded_step"]
