"""Measured multi-device mesh resolution with overlapped exchange.

`ShardedConflictEngine` (parallel/sharding.py) proved the shard_map math:
one fused program per batch, verdicts a pure function of psum'd [T]
planes. But it is jit-served (compiles can stall steady state on a
restarted resolver) and its force() blocks the host on every batch, so
the cross-shard collective cost had to be ESTIMATED in the bench
(BENCH_r05's 0.15 ms). This module is the mesh path grown to the full
single-chip treatment, so the protocol costs become measured:

  * SPLIT dispatch unit: phase-1 scans are one shard-LOCAL program
    (make_mesh_scan_step — no collective anywhere), the cross-shard
    abort-set/witness exchange plus commit fixpoint plus apply is a
    second program (make_mesh_exchange_step). Both are AOT
    `.lower().compile()`d per ladder bucket against NamedSharding-placed
    ShapeDtypeStructs and served through the on-disk progcache
    (core/progcache.py) under distinct `variant=` keys, so a restarted
    mesh resolver warms by loading — and the cache key's mesh
    fingerprint + device count guarantee an artifact compiled for one
    topology is never served to another.
  * OVERLAPPED exchange: everything is JAX async dispatch and nothing is
    forced inline — the host enqueues scan(i), exchange(i), then packs
    and enqueues scan(i+1) while exchange(i)'s collectives are still
    draining on the mesh (scan(i+1) only data-depends on exchange(i)'s
    table update, not on its status readback). Results retire through
    the same non-blocking result-ring discipline as the device loop
    (ops/device_loop.py): `poll()` decodes exactly the ready prefix via
    `jax.Array.is_ready()`, `loop_stats` files every drain as
    drained_nonblocking / forced_waits / blocking_syncs, and
    blocking_syncs == 0 is the acceptance bar (`make mesh-smoke`).
    `overlap=False` (knob `resolver_mesh_overlap=serial`) forces every
    unit at dispatch — the serialized A/B baseline tools/mesh_bench.py
    records; overlapped must beat it.
  * MEASURED exchange interval: the ticket keeps a handle on the scan
    program's history-hit plane; the drain stamps when the scan outputs
    landed vs when the exchange outputs landed, so `mesh_stats` carries
    a host-observed scan-ready -> exchange-ready interval per drained
    batch (`last_collective_ms`). tools/mesh_bench.py additionally times
    a dedicated compiled psum-chain program for the clean
    collective-only number that replaces the BENCH_r05 estimate.
  * A shard is a DEVICE, not a host engine: the shard map is adopted
    from the heat aggregator's measured equal-load split keys
    (`measured_shard_map`), and under `ElasticResolverGroup` a mesh
    engine slots in behind the epoched shard map exactly like the
    single-chip engines (same resolve()/journal/handoff contract), so
    `ReshardController` split/merge moves device-resident table slices
    through fault/handoff.py's replay protocol unchanged.

Exactness: the split pair composes the SAME phases as make_sharded_step
— local_phases, psum'd commit_fixpoint, apply_writes_and_gc — so abort
sets are bit-identical to the fused mesh step, the single-chip engines
and the CPU oracle at every shard count (tests/test_mesh_parity.py
drives N in {1, 2, 4, 8} across bucket boundaries and a live epoch
flip).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

from ..core import telemetry
from ..core.keyshard import KeyShardMap
from ..core.knobs import SERVER_KNOBS
from ..core.types import Version
from ..ops import conflict_kernel as ck
from ..ops.conflict_kernel import KernelConfig
from ..ops.host_engine import RoutedConflictEngineBase, donate_state_kwargs
from .sharding import (make_mesh_exchange_step, make_mesh_scan_step,
                       make_sharded_scan_step, make_sharded_split_steps)

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["MeshShardedConflictEngine", "measured_shard_map",
           "mesh_overlap_requested"]

#: legal values of the `resolver_mesh_overlap` knob
MESH_OVERLAP_MODES = ("", "on", "serial")


def mesh_overlap_requested() -> bool:
    """False iff the `resolver_mesh_overlap` knob selects the serialized
    A/B baseline (force every dispatch unit before the next enqueue)."""
    raw = str(getattr(SERVER_KNOBS, "resolver_mesh_overlap", "on") or "").strip()
    if raw not in MESH_OVERLAP_MODES:
        raise ValueError(
            f"unknown resolver_mesh_overlap mode {raw!r}; expected one of "
            f"{MESH_OVERLAP_MODES}")
    return raw != "serial"


def mesh_device_count() -> int:
    """Devices the mesh engine spans by default: the
    `resolver_mesh_devices` knob, 0 meaning every visible XLA device."""
    n = int(getattr(SERVER_KNOBS, "resolver_mesh_devices", 0) or 0)
    return n if n > 0 else len(jax.devices())


def measured_shard_map(heat, n_shards: int) -> KeyShardMap:
    """The shard map a mesh (re)build adopts: the heat aggregator's
    MEASURED equal-load split keys when the histogram can supply a full
    set, byte-uniform otherwise (KeyShardMap.from_split_points
    sanitizes). This is the split-key adoption half of ROADMAP item 1:
    the same `split_points()` the ReshardController plans host-engine
    splits from now shapes the device mesh partition."""
    splits: List[bytes] = []
    if heat is not None:
        try:
            splits = list(heat.split_points(shards=n_shards) or [])
        except Exception:
            splits = []
    return KeyShardMap.from_split_points(splits, n_shards)


class _MeshTicket:
    """One dispatched mesh unit's place in the result ring."""

    __slots__ = ("status_dev", "ov_dev", "heat_dev", "heat_base",
                 "heat_version", "heat_layout", "n_chunks", "scan_probe",
                 "enq_t", "scan_ready_t", "keep", "status", "overflow",
                 "done", "sample")

    def __init__(self, status_dev, ov_dev, n_chunks: int, keep,
                 scan_probe=None, heat_dev=None, heat_base: int = 0,
                 heat_version=None, heat_layout: str = "s"):
        self.status_dev = status_dev
        self.ov_dev = ov_dev
        self.heat_dev = heat_dev
        self.heat_base = heat_base
        self.heat_version = heat_version
        self.heat_layout = heat_layout
        self.n_chunks = n_chunks
        #: the scan program's [S, T] history-hit plane (split units only):
        #: probed non-blockingly so the drain can stamp scan-ready vs
        #: exchange-ready — the measured exchange interval
        self.scan_probe = scan_probe
        self.enq_t = time.perf_counter()
        self.scan_ready_t: Optional[float] = None
        #: zero-copy keepalive: everything the dispatched programs may
        #: still read (host_engine._dispatch_unit contract)
        self.keep = keep
        self.status: Optional[np.ndarray] = None
        self.overflow = False
        self.done = False
        #: sampled device timing (t0_wall, t0_span, version) or None
        self.sample = None

    def probe_scan(self) -> None:
        """Stamp the moment the scan outputs were first OBSERVED ready
        (non-blocking; exchange-interval measurement only)."""
        if (self.scan_ready_t is None and self.scan_probe is not None
                and self.scan_probe.is_ready()):
            self.scan_ready_t = time.perf_counter()

    def ready(self) -> bool:
        """Non-blocking: have this unit's verdict planes (and heat, when
        on) landed?"""
        self.probe_scan()
        r = self.status_dev.is_ready() and self.ov_dev.is_ready()
        if r and self.heat_dev is not None:
            r = all(v.is_ready() for v in self.heat_dev.values())
        return r


class MeshShardedConflictEngine(RoutedConflictEngineBase):
    """N-device mesh ConflictSet engine: AOT split scan/exchange
    programs, overlapped cross-shard exchange, progcache-served warmup.
    Same resolve() contract as every other engine family."""

    name = "mesh"
    dispatch_mode = "mesh"

    def __init__(
        self,
        cfg: KernelConfig = KernelConfig(),
        shards: Optional[KeyShardMap] = None,
        mesh: Optional[Mesh] = None,
        initial_version: Version = 0,
        ladder=None,
        scan_sizes: Sequence[int] = (2, 4, 8),
        arena: bool = True,
        history_search: Optional[str] = None,
        heat_buckets: Optional[int] = None,
        device_time_sample_rate: Optional[float] = None,
        queue_depth: Optional[int] = None,
        overlap: Optional[bool] = None,
        drain_deadline_s: float = 5.0,
        history_structure: Optional[str] = None,
    ):
        if mesh is None:
            devs = jax.devices()
            n = shards.n_shards if shards is not None else mesh_device_count()
            if n > len(devs):
                raise ValueError(
                    f"mesh engine needs {n} devices, only {len(devs)} visible")
            mesh = jax.make_mesh((n,), ("shard",), devices=devs[:n])
        (n_devices,) = mesh.devices.shape
        #: dispatched-but-undrained tickets — the result ring; its bound
        #: is the double buffer (knob resolver_mesh_queue_depth)
        self._ring: deque = deque()
        self.queue_depth = max(1, int(
            queue_depth if queue_depth is not None
            else int(getattr(SERVER_KNOBS, "resolver_mesh_queue_depth", 2))))
        self.overlap = bool(overlap if overlap is not None
                            else mesh_overlap_requested())
        self.drain_deadline_s = drain_deadline_s
        #: same sync-accounting keys as ops/device_loop.py loop_stats, so
        #: ElasticResolverGroup.loop_stats aggregation and the
        #: blocking_syncs == 0 acceptance read mesh slots unchanged
        self.loop_stats = {"enqueued_chunks": 0, "units": 0,
                           "drained_nonblocking": 0, "forced_waits": 0,
                           "blocking_syncs": 0, "wait_ms": 0.0,
                           "enqueue_ms": 0.0, "decode_ms": 0.0}
        #: mesh-topology + measured-exchange gauges (fdbtpu_mesh family)
        self.mesh_stats: Dict[str, float] = {
            "n_devices": int(n_devices), "n_shards": int(n_devices),
            "exchanges": 0, "timed_exchanges": 0,
            "table_bytes_per_shard": 0,
            "last_collective_ms": 0.0, "exchange_ms_total": 0.0,
            "scan_ms_total": 0.0,
        }
        self._sample_pending = None
        super().__init__(cfg, shards or KeyShardMap.uniform(n_devices),
                         ladder=ladder, scan_sizes=scan_sizes, arena=arena,
                         history_search=history_search,
                         heat_buckets=heat_buckets,
                         device_time_sample_rate=device_time_sample_rate,
                         history_structure=history_structure)
        cfg = self.cfg   # base resolved history-search + heat into it
        assert self.n_shards == n_devices
        self.mesh = mesh
        self._sharding = NamedSharding(mesh, P("shard"))
        # split-step programs for the host long-key tier (jit, compiled
        # lazily — short-key-only workloads never pay for them)
        self._detect_m, self._fix_m, self._apply_m = \
            make_sharded_split_steps(cfg, mesh)
        self._reset_device_state(self._rel(initial_version))
        from ..ops.oracle import VersionIntervalMap

        self.tier_map = VersionIntervalMap(initial_version)
        self.mesh_stats["table_bytes_per_shard"] = \
            self._table_bytes_per_shard()
        self._mesh_telemetry_label = telemetry.hub().register_mesh(
            self, name=self.name)

    # -- telemetry ------------------------------------------------------------
    def ring_depth(self) -> int:
        """Dispatched-but-undrained tickets in the result ring."""
        return len(self._ring)

    def _table_bytes_per_shard(self) -> int:
        total = sum(int(np.prod(x.shape)) * x.dtype.itemsize
                    for x in jax.tree.leaves(self.state))
        return total // max(self.n_shards, 1)

    def mesh_stats_snapshot(self) -> Dict[str, float]:
        """One batch-attachable snapshot of the topology + measured
        exchange gauges plus the sync accounting — what `cli shards`
        renders as the per-shard device view and what rides the
        fdbtpu_mesh exposition."""
        snap = {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self.mesh_stats.items()}
        snap.update({k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in self.loop_stats.items()})
        snap["ring_depth"] = self.ring_depth()
        snap["overlap"] = self.overlap
        return snap

    def loop_stats_snapshot(self) -> Dict[str, float]:
        return self.mesh_stats_snapshot()

    def device_view(self) -> List[dict]:
        """Per-shard device placement — shard id, owning device, table
        residency, last measured exchange interval — the `cli shards`
        device-view rows (live and via campaign-report JSON)."""
        from ..core.keyshard import _fmt_key

        devs = list(self.mesh.devices.reshape(-1))
        tb = self._table_bytes_per_shard()
        last = round(float(self.mesh_stats["last_collective_ms"]), 4)
        out = []
        for s in range(self.n_shards):
            d = devs[s]
            out.append({
                "shard": s,
                "device": int(getattr(d, "id", s)),
                "platform": str(getattr(d, "platform", "")),
                "span_begin": _fmt_key(self.shards.begins[s]),
                "table_bytes": tb,
                "last_collective_ms": last,
            })
        return out

    # -- device state ---------------------------------------------------------
    def _stack_shards(self, per_shard: List[Dict]):
        stacked = jax.tree.map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *per_shard)
        return jax.tree.map(
            lambda x: jax.device_put(x, self._sharding), stacked)

    def _reset_device_state(self, version_rel: int) -> None:
        self.drain_ring()
        per = [
            ck.initial_state(self.cfg, version_rel=version_rel,
                             first_key=self.shards.begins[s])
            for s in range(self.n_shards)
        ]
        self.state = self._stack_shards(per)

    def _device_states_for_snapshot(self):
        # quiesce the ring first: an async unit may still own the table
        self.drain_ring()
        return [jax.tree.map(lambda x, s=s: np.asarray(x)[s], self.state)
                for s in range(self.n_shards)]

    # -- AOT program pairs ----------------------------------------------------
    def _progcache_fingerprint(self) -> str:
        # programs bake the mesh topology: never share entries across
        # shard counts or visible-device sets (the satellite-1 bugfix)
        return f"mesh:{self.n_shards}/{len(jax.devices())}"

    def _struct(self, tree):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=self._sharding), tree)

    def _program(self, bucket: KernelConfig, n_chunks: int):
        key = (bucket.max_txns, n_chunks)
        prog = self._programs.get(key)
        if prog is None:
            if n_chunks == 1:
                # the split pair: each half builds (or progcache-loads)
                # under its own variant key
                scan = self._build_and_record(
                    bucket, 1, variant="scan",
                    make=self._make_scan_program)
                exch = self._build_and_record(
                    bucket, 1, variant="exchange",
                    make=self._make_exchange_program)
                prog = (scan, exch)
            else:
                prog = self._build_and_record(bucket, n_chunks)
            self._programs[key] = prog
        return prog

    def _structs_for(self, bucket: KernelConfig, n_chunks: int):
        S = self.n_shards
        st = self._struct(ck.state_struct(self.cfg, stack=(S,)))
        stack = (S,) if n_chunks == 1 else (S, n_chunks)
        bt = self._struct(ck.batch_struct(bucket, stack=stack))
        return st, bt

    def _make_scan_program(self, bucket: KernelConfig, n_chunks: int):
        st, bt = self._structs_for(bucket, 1)
        mapped = make_mesh_scan_step(bucket, self.mesh)
        # AOT: compiled eagerly against the sharded structs — can never
        # re-trace, and serialize_executable round-trips it (progcache)
        return jax.jit(mapped).lower(st, bt).compile()

    def _make_exchange_program(self, bucket: KernelConfig, n_chunks: int):
        st, bt = self._structs_for(bucket, 1)
        scan_mapped = make_mesh_scan_step(bucket, self.mesh)
        outs = jax.eval_shape(scan_mapped, st, bt)
        hist_s, ovp_s, wpos_s = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=self._sharding), outs)
        mapped = make_mesh_exchange_step(bucket, self.mesh)
        return jax.jit(mapped, **donate_state_kwargs()).lower(
            st, bt, hist_s, ovp_s, wpos_s).compile()

    def _make_program(self, bucket: KernelConfig, n_chunks: int):
        # fused multi-chunk unit (C > 1): the split pair cannot span
        # chunks — chunk c+1's scan reads the table chunk c's apply
        # wrote — so the scan-size ladder keeps the one-program shape,
        # AOT-lowered (make_sharded_scan_step returns the jit)
        st, bt = self._structs_for(bucket, n_chunks)
        return make_sharded_scan_step(bucket, self.mesh,
                                      n_chunks).lower(st, bt).compile()

    # -- dispatch / result ring ----------------------------------------------
    def _dispatch_unit(self, bucket: KernelConfig,
                       per_chunks: List[List[Dict[str, np.ndarray]]]):
        C = len(per_chunks)
        prog = self._program(bucket, C)
        t_enq = time.perf_counter()
        scan_probe = None
        if C == 1:
            scan_p, exch_p = prog
            batch = self._stack_shards(per_chunks[0])
            hist, ovp, wpos = scan_p(self.state, batch)
            self.state, out = exch_p(self.state, batch, hist, ovp, wpos)
            scan_probe = hist
            self.mesh_stats["exchanges"] += 1
            heat_layout = "s"
        else:
            stacked = {
                k: np.stack([
                    np.stack([np.asarray(pc[s][k]) for pc in per_chunks])
                    for s in range(self.n_shards)
                ])
                for k in per_chunks[0][0]
            }
            batch = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding), stacked)
            self.state, out = prog(self.state, batch)
            heat_layout = "sc"
        self.loop_stats["enqueue_ms"] += (time.perf_counter() - t_enq) * 1e3
        ticket = _MeshTicket(out["status"], out["overflow"], C, batch,
                             scan_probe=scan_probe,
                             heat_dev=out.get("heat"), heat_base=self.base,
                             heat_version=self._heat_version,
                             heat_layout=heat_layout)
        if self._sample_pending is not None:
            ticket.sample = (bucket.max_txns, C) + self._sample_pending
            self._sample_pending = None
        self._ring.append(ticket)
        self.loop_stats["units"] += 1
        self.loop_stats["enqueued_chunks"] += C
        if not self.overlap:
            # serialized A/B baseline: retire the unit before the host
            # packs anything else — what mesh_bench compares against
            self._drain_through(ticket)
        else:
            # bound the in-flight depth to the double buffer, then drain
            # whatever already landed — the non-blocking steady state
            while len(self._ring) > self.queue_depth:
                self._drain_through(self._ring[0])
            self.poll()

        def force() -> Tuple[np.ndarray, bool]:
            self._drain_through(ticket)
            return ticket.status, ticket.overflow

        return force

    def _dispatch_sampled(self, bucket: KernelConfig, per_chunks):
        """Mesh sampled device timing rides the TICKET (recorded when the
        drain sees the results — ops/device_loop.py's discipline), not
        force(), which in overlapped steady state runs long after the
        results landed."""
        from ..core.trace import g_spans, span_now

        self._sample_pending = (time.perf_counter(),
                                span_now() if g_spans.enabled else 0.0,
                                self._heat_version)
        try:
            return self._dispatch_unit(bucket, per_chunks)
        finally:
            self._sample_pending = None

    def poll(self) -> int:
        """Drain the READY prefix of the result ring — the non-blocking
        steady-state path. Returns the number of tickets completed."""
        n = 0
        for t in self._ring:
            # stamp every in-flight scan, not just the head's: under
            # overlap, batch i+1's scan lands while batch i's exchange
            # is still draining — that stamp IS the overlap evidence
            t.probe_scan()
        while self._ring and self._ring[0].ready():
            self._finish(self._ring.popleft())
            self.loop_stats["drained_nonblocking"] += 1
            n += 1
        return n

    def drain_ring(self) -> None:
        """Block until every in-flight unit drained — the explicit
        barrier before host code touches the table state (clear, the
        split-step long-key path, shadow rebuild)."""
        if getattr(self, "_ring", None):
            self._drain_through(self._ring[-1])

    def _drain_through(self, ticket: _MeshTicket) -> None:
        while not ticket.done:
            head = self._ring[0]
            if not head.ready():
                # poll-wait for readiness (the host is never inside a
                # device sync call); only the deadline fallback is a
                # true blocking sync
                self.loop_stats["forced_waits"] += 1
                t0 = time.perf_counter()
                deadline = t0 + self.drain_deadline_s
                while not head.ready() and time.perf_counter() < deadline:
                    time.sleep(2e-5)
                self.loop_stats["wait_ms"] += (time.perf_counter() - t0) * 1e3
                if not head.ready():
                    self.loop_stats["blocking_syncs"] += 1
            self._finish(self._ring.popleft())

    # fdbtpu-lint: drain-point — only reached once ticket.ready() (or the
    # deadline fallback, which loop_stats charges as a blocking sync): the
    # asarray below copies a COMPLETED buffer, it never parks in the device
    def _finish(self, ticket: _MeshTicket) -> None:
        t_dec = time.perf_counter()
        status = np.asarray(ticket.status_dev)[0]  # identical across shards
        ticket.status = status[None] if ticket.n_chunks == 1 else status
        ticket.overflow = bool(np.any(np.asarray(ticket.ov_dev)))
        if ticket.heat_dev is not None:
            self._merge_heat(ticket.heat_dev, version=ticket.heat_version,
                             base=ticket.heat_base,
                             layout=ticket.heat_layout)
        self.loop_stats["decode_ms"] += (time.perf_counter() - t_dec) * 1e3
        if ticket.scan_ready_t is not None:
            # host-observed scan-ready -> exchange-ready interval: the
            # measured cost of the psum exchange + lockstep fixpoint +
            # apply on the real mesh (an upper bound in overlapped mode
            # — the drain may observe late; mesh_bench's dedicated psum
            # timing is the clean collective-only figure)
            ex_ms = (t_dec - ticket.scan_ready_t) * 1e3
            self.mesh_stats["last_collective_ms"] = ex_ms
            self.mesh_stats["exchange_ms_total"] += ex_ms
            self.mesh_stats["scan_ms_total"] += \
                (ticket.scan_ready_t - ticket.enq_t) * 1e3
            self.mesh_stats["timed_exchanges"] += 1
        if ticket.sample is not None:
            self._record_device_sample(*ticket.sample)
            ticket.sample = None
        ticket.done = True
        ticket.status_dev = ticket.ov_dev = None
        ticket.heat_dev = None
        ticket.scan_probe = None
        ticket.keep = None

    # -- resolve paths --------------------------------------------------------
    def _run_step(self, per_shard: List[Dict[str, np.ndarray]]) -> Tuple[np.ndarray, bool]:
        status, overflow = self._dispatch_unit(self.cfg, [per_shard])()
        return status[0], overflow

    # -- split-step path (host long-key tier) --------------------------------
    def _run_detect(self, per_shard):
        # the split-step jits read/write self.state directly: quiesce the
        # ring first so no async unit still owns the table
        self.drain_ring()
        batch = self._stack_shards(per_shard)
        hist, ovp, wpos = self._detect_m(self.state, batch)
        return {"batch": batch, "hist": hist, "ovp": ovp, "wpos": wpos}

    def _run_fix(self, ctx, per_shard, t_ok: np.ndarray) -> np.ndarray:
        t_ok_stacked = jax.device_put(
            np.broadcast_to(t_ok, (self.n_shards,) + t_ok.shape).copy(),
            self._sharding,
        )
        committed = self._fix_m(t_ok_stacked, ctx["hist"], ctx["ovp"],
                                ctx["batch"])
        return np.asarray(committed)[0]

    def _run_apply(self, ctx, per_shard, committed: np.ndarray) -> Tuple[np.ndarray, bool]:
        cm = jax.device_put(
            np.broadcast_to(committed,
                            (self.n_shards,) + committed.shape).copy(),
            self._sharding,
        )
        self.state, overflow = self._apply_m(self.state, ctx["batch"], cm,
                                             ctx["wpos"])
        t_too_old = np.asarray(ctx["batch"]["t_too_old"])[0]
        status = np.asarray(ck.status_of(t_too_old, committed))
        return status, bool(np.any(np.asarray(overflow)))
