"""The tuple layer: order-preserving encoding of typed tuples into keys.

Implements the reference's public tuple encoding specification
(design/tuple.md; bindings/python/fdb/tuple.py is the C-binding-backed
analog): each element is a type code byte followed by a self-delimiting
payload, chosen so that unsigned byte comparison of packed tuples equals
elementwise typed comparison of the tuples — the property every layer
built on range reads depends on.

Supported element types (the common subset every binding provides):
None, bytes, str (UTF-8), int (arbitrary precision), float (as IEEE
double), bool, uuid.UUID, and nested tuples/lists.
"""
from __future__ import annotations

import struct
import uuid
from typing import Any, List, Sequence, Tuple

NULL_CODE = 0x00
BYTES_CODE = 0x01
STRING_CODE = 0x02
NESTED_CODE = 0x05
NEG_INT_START = 0x0B      # arbitrary-precision negative: length byte is complemented
INT_ZERO_CODE = 0x14      # ints: 0x14 - 8 .. 0x14 + 8 by byte length
POS_INT_END = 0x1D        # arbitrary-precision positive: explicit length byte
DOUBLE_CODE = 0x21
FALSE_CODE = 0x26
TRUE_CODE = 0x27
UUID_CODE = 0x30

_ESCAPE = b"\x00\xff"


def _encode_bytes_body(b: bytes) -> bytes:
    """NUL-terminated with embedded NULs escaped as 00 FF — preserves order
    because FF cannot follow a real terminator."""
    return b.replace(b"\x00", _ESCAPE) + b"\x00"


def _decode_bytes_body(data: bytes, pos: int) -> Tuple[bytes, int]:
    out = bytearray()
    while True:
        i = data.index(b"\x00", pos)
        if i + 1 < len(data) and data[i + 1] == 0xFF:
            out += data[pos:i] + b"\x00"
            pos = i + 2
        else:
            out += data[pos:i]
            return bytes(out), i + 1


def _encode_int(v: int) -> bytes:
    if v == 0:
        return bytes([INT_ZERO_CODE])
    if v > 0:
        n = (v.bit_length() + 7) // 8
        if n > 8:
            # arbitrary precision: explicit length byte keeps longer ints sorting later
            if n > 255:
                raise ValueError("tuple layer big ints are limited to 255 bytes")
            return bytes([POS_INT_END, n]) + v.to_bytes(n, "big")
        return bytes([INT_ZERO_CODE + n]) + v.to_bytes(n, "big")
    n = ((-v).bit_length() + 7) // 8
    if n > 8:
        # negative big int: complemented length byte so longer (more negative) sorts first
        if n > 255:
            raise ValueError("tuple layer big ints are limited to 255 bytes")
        return bytes([NEG_INT_START, n ^ 0xFF]) + ((1 << (8 * n)) - 1 + v).to_bytes(n, "big")
    # negative: offset by the max so bigger magnitudes sort first
    return bytes([INT_ZERO_CODE - n]) + ((1 << (8 * n)) - 1 + v).to_bytes(n, "big")


def _encode_double(v: float) -> bytes:
    raw = bytearray(struct.pack(">d", v))
    # IEEE total-order transform: flip all bits of negatives, sign of positives
    if raw[0] & 0x80:
        raw = bytearray(b ^ 0xFF for b in raw)
    else:
        raw[0] ^= 0x80
    return bytes([DOUBLE_CODE]) + bytes(raw)


def _decode_double(data: bytes, pos: int) -> Tuple[float, int]:
    raw = bytearray(data[pos:pos + 8])
    if raw[0] & 0x80:
        raw[0] ^= 0x80
    else:
        raw = bytearray(b ^ 0xFF for b in raw)
    return struct.unpack(">d", bytes(raw))[0], pos + 8


def _encode_one(v: Any, nested: bool) -> bytes:
    if v is None:
        # inside nested tuples, None is 00 FF so it can't terminate the nest
        return b"\x00\xff" if nested else bytes([NULL_CODE])
    if isinstance(v, bool):   # before int: bool is an int subclass
        return bytes([TRUE_CODE if v else FALSE_CODE])
    if isinstance(v, bytes):
        return bytes([BYTES_CODE]) + _encode_bytes_body(v)
    if isinstance(v, str):
        return bytes([STRING_CODE]) + _encode_bytes_body(v.encode("utf-8"))
    if isinstance(v, int):
        return _encode_int(v)
    if isinstance(v, float):
        return _encode_double(v)
    if isinstance(v, uuid.UUID):
        return bytes([UUID_CODE]) + v.bytes
    if isinstance(v, (tuple, list)):
        body = b"".join(_encode_one(x, nested=True) for x in v)
        return bytes([NESTED_CODE]) + body + b"\x00"
    raise TypeError(f"tuple layer cannot encode {type(v).__name__}")


def pack(t: Sequence[Any], prefix: bytes = b"") -> bytes:
    """Encode a tuple to a key; byte order == typed tuple order."""
    return prefix + b"".join(_encode_one(v, nested=False) for v in t)


def _decode_one(data: bytes, pos: int, nested: bool) -> Tuple[Any, int]:
    code = data[pos]
    pos += 1
    if code == NULL_CODE:
        if nested and pos < len(data) and data[pos] == 0xFF:
            return None, pos + 1
        return None, pos
    if code == BYTES_CODE:
        return _decode_bytes_body(data, pos)
    if code == STRING_CODE:
        raw, pos = _decode_bytes_body(data, pos)
        return raw.decode("utf-8"), pos
    if code == NESTED_CODE:
        out: List[Any] = []
        while True:
            if data[pos] == 0x00 and not (pos + 1 < len(data) and data[pos + 1] == 0xFF):
                return tuple(out), pos + 1
            v, pos = _decode_one(data, pos, nested=True)
            out.append(v)
    if code == DOUBLE_CODE:
        return _decode_double(data, pos)
    if code == FALSE_CODE:
        return False, pos
    if code == TRUE_CODE:
        return True, pos
    if code == UUID_CODE:
        return uuid.UUID(bytes=data[pos:pos + 16]), pos + 16
    if code == POS_INT_END:
        n = data[pos]
        return int.from_bytes(data[pos + 1:pos + 1 + n], "big"), pos + 1 + n
    if code == NEG_INT_START:
        n = data[pos] ^ 0xFF
        return (int.from_bytes(data[pos + 1:pos + 1 + n], "big")
                - ((1 << (8 * n)) - 1)), pos + 1 + n
    if INT_ZERO_CODE - 8 <= code <= INT_ZERO_CODE + 8:
        n = code - INT_ZERO_CODE
        if n == 0:
            return 0, pos
        if n > 0:
            return int.from_bytes(data[pos:pos + n], "big"), pos + n
        n = -n
        return int.from_bytes(data[pos:pos + n], "big") - ((1 << (8 * n)) - 1), pos + n
    raise ValueError(f"unknown tuple type code 0x{code:02x} at {pos - 1}")


def unpack(key: bytes, prefix: bytes = b"") -> Tuple[Any, ...]:
    assert key.startswith(prefix), "key does not carry the expected prefix"
    out: List[Any] = []
    pos = len(prefix)
    while pos < len(key):
        v, pos = _decode_one(key, pos, nested=False)
        out.append(v)
    return tuple(out)


def range_of(t: Sequence[Any], prefix: bytes = b"") -> Tuple[bytes, bytes]:
    """[begin, end) covering every tuple that extends `t` (fdb.tuple.range)."""
    p = pack(t, prefix)
    return p + b"\x00", p + b"\xff"
