"""TaskBucket: a transactional distributed task queue in the keyspace.

Re-design of fdbclient/TaskBucket.actor.cpp round-2 scope: tasks are rows
under a subspace; executors CLAIM a task transactionally (move it from the
available space to the timeout space stamped with a reclaim deadline), so
exactly one executor works each task; finishing clears it; a claimer that
dies resurfaces its task after the deadline (check_timeouts). This is the
substrate the reference's backup/restore agents schedule themselves on —
conflict detection provides the exactly-once-claim guarantee for free.

Keys:
  <prefix>/avail/<task id>            -> packed params
  <prefix>/timeout/<deadline>/<id>    -> packed params
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..sim.loop import now
from . import fdb_tuple
from .fdb_api import Subspace


class Task:
    def __init__(self, id: int, params: Dict[str, Any], timeout_key: Optional[bytes] = None):
        self.id = id
        self.params = params
        self.timeout_key = timeout_key


class TaskBucket:
    def __init__(self, subspace: Subspace, timeout_seconds: float = 10.0):
        self.avail = subspace["avail"]
        self.timeouts = subspace["timeout"]
        self.timeout_seconds = timeout_seconds

    # -- producer -------------------------------------------------------------
    def add(self, tr, task_id: int, params: Dict[str, Any]) -> None:
        """reference: TaskBucket::addTask."""
        payload = fdb_tuple.pack(tuple(sorted(params.items())))
        tr.set(self.avail.pack((task_id,)), payload)

    # -- executor -------------------------------------------------------------
    async def get_one(self, tr) -> Optional[Task]:
        """Claim one available task (TaskBucket::getOne): moves it into the
        timeout space under a reclaim deadline. The read of the available
        row is a conflict range, so two racing claimers cannot both win."""
        lo, hi = self.avail.range()
        rows = await tr.get_range(lo, hi, limit=1)
        if not rows:
            return None
        key, payload = rows[0]
        (task_id,) = self.avail.unpack(key)
        deadline = int((now() + self.timeout_seconds) * 1000)
        tkey = self.timeouts.pack((deadline, task_id))
        tr.clear(key)
        tr.set(tkey, payload)
        params = dict(fdb_tuple.unpack(payload))
        return Task(task_id, params, timeout_key=tkey)

    def finish(self, tr, task: Task) -> None:
        """reference: TaskBucket::finish — the claim row disappears."""
        if task.timeout_key is not None:
            tr.clear(task.timeout_key)

    async def check_timeouts(self, tr) -> int:
        """Requeue expired claims (TaskBucket::checkTimeouts); returns how
        many moved back to available."""
        deadline_now = int(now() * 1000)
        lo = self.timeouts.pack(())
        hi = self.timeouts.pack((deadline_now,))
        rows = await tr.get_range(lo, hi)
        for key, payload in rows:
            _deadline, task_id = self.timeouts.unpack(key)
            tr.clear(key)
            tr.set(self.avail.pack((task_id,)), payload)
        return len(rows)

    async def is_empty(self, tr) -> bool:
        for space in (self.avail, self.timeouts):
            lo, hi = space.range()
            if await tr.get_range(lo, hi, limit=1):
                return False
        return True
