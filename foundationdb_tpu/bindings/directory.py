"""The directory layer: hierarchical named namespaces over short prefixes.

Re-design of the reference python binding's DirectoryLayer
(bindings/python/fdb/directory_impl.py): paths like ("app", "users") map
to short, unique byte prefixes allocated by a high-contention allocator,
with the path->prefix metadata stored in a node subspace so renames never
move data. Layers (a per-directory type tag) must match on open.

Storage model (mirroring the reference):
  node(prefix)                 = node_subspace[prefix]
  node[SUBDIRS][name]          -> child prefix       (directory tree edges)
  node[b"layer"]               -> layer tag
The root node's "prefix" is the node subspace's own raw prefix.

HighContentionAllocator (directory_impl.py _HighContentionAllocator):
windowed counters + candidate probing. Atomic ADDs keep counter bumps
conflict-free; candidate claims rely on the resolver for uniqueness —
two racing allocators cannot both commit the same candidate because the
claim write conflicts with the other's snapshot read.
"""
from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

from ..core import error
from ..core.types import MutationType
from . import fdb_tuple
from .fdb_api import Subspace

SUBDIRS = 0


class DirectoryError(Exception):
    pass


class HighContentionAllocator:
    def __init__(self, subspace: Subspace):
        self.counters = subspace[0]
        self.recent = subspace[1]

    async def allocate(self, tr) -> bytes:
        """A short byte string never allocated before and never a prefix
        of another allocation (tuple-packed ints have that property
        within a window scheme)."""
        while True:
            # current window start = highest counter key
            rows = await tr.get_range(*self.counters.range(), limit=1, reverse=True,
                                      snapshot=True)
            start = self.counters.unpack(rows[0][0])[0] if rows else 0
            count = struct.unpack("<q", rows[0][1])[0] if rows else 0
            window = self._window_size(start)
            if count * 2 >= window:
                # window exhausted: advance it, clearing superseded state
                start += window
                tr.clear_range(self.counters.pack(()), self.counters.pack((start,)))
                tr.clear_range(self.recent.pack(()), self.recent.pack((start,)))
                window = self._window_size(start)
            tr.atomic_op(self.counters.pack((start,)),
                         struct.pack("<q", 1), MutationType.ADD_VALUE)
            # probe candidates inside the window
            for _ in range(64):
                from ..sim.loop import current_scheduler

                candidate = start + current_scheduler().rng.random_int(0, window)
                key = self.recent.pack((candidate,))
                taken = await tr.get(key)   # conflict range: the claim race
                if taken is None:
                    tr.set(key, b"")
                    return fdb_tuple.pack((candidate,))

    @staticmethod
    def _window_size(start: int) -> int:
        if start < 255:
            return 64
        if start < 65535:
            return 1024
        return 8192


class DirectorySubspace(Subspace):
    """A directory's content subspace plus its identity."""

    def __init__(self, path: Tuple[str, ...], prefix: bytes, layer: bytes,
                 directory_layer: "DirectoryLayer"):
        super().__init__((), prefix)
        self.path = path
        self.layer = layer
        self._dl = directory_layer

    def __repr__(self) -> str:
        return f"DirectorySubspace(path={self.path!r}, prefix={self.raw_prefix!r})"


class DirectoryLayer:
    def __init__(self, node_prefix: bytes = b"\xfe",
                 content_subspace: Optional[Subspace] = None):
        self._node_ss = Subspace((), node_prefix)
        self._content = content_subspace or Subspace((), b"")
        self._alloc = HighContentionAllocator(self._node_ss[b"hca"])
        #: the root directory's node
        self._root_node = self._node_ss.subspace((self._node_ss.raw_prefix,))

    # -- node helpers --------------------------------------------------------
    def _node(self, prefix: bytes) -> Subspace:
        return self._node_ss.subspace((prefix,))

    async def _find(self, tr, path: Sequence[str]):
        """Walk the tree; returns (node, prefix) or (None, None)."""
        node, prefix = self._root_node, self._node_ss.raw_prefix
        for name in path:
            child = await tr.get(node.pack((SUBDIRS, name)))
            if child is None:
                return None, None
            prefix = child
            node = self._node(prefix)
        return node, prefix

    async def _layer_of(self, tr, node: Subspace) -> bytes:
        return (await tr.get(node.pack((b"layer",)))) or b""

    # -- public api ----------------------------------------------------------
    async def create_or_open(self, tr, path: Sequence[str], layer: bytes = b"") -> DirectorySubspace:
        return await self._create_or_open(tr, tuple(path), layer,
                                          allow_create=True, allow_open=True)

    async def create(self, tr, path: Sequence[str], layer: bytes = b"") -> DirectorySubspace:
        return await self._create_or_open(tr, tuple(path), layer,
                                          allow_create=True, allow_open=False)

    async def open(self, tr, path: Sequence[str], layer: bytes = b"") -> DirectorySubspace:
        return await self._create_or_open(tr, tuple(path), layer,
                                          allow_create=False, allow_open=True)

    async def _create_or_open(self, tr, path, layer, allow_create, allow_open):
        if not path:
            raise DirectoryError("the root directory cannot be opened")
        node, prefix = await self._find(tr, path)
        if node is not None:
            if not allow_open:
                raise DirectoryError(f"directory {path!r} already exists")
            existing = await self._layer_of(tr, node)
            if layer and existing != layer:
                raise DirectoryError(
                    f"layer mismatch at {path!r}: {existing!r} != {layer!r}")
            return DirectorySubspace(path, prefix, existing, self)
        if not allow_create:
            raise DirectoryError(f"directory {path!r} does not exist")
        # create parents, then allocate this directory's prefix
        if len(path) > 1:
            parent = await self._create_or_open(tr, path[:-1], b"",
                                               allow_create=True, allow_open=True)
            parent_node = self._node(parent.raw_prefix)
        else:
            parent_node = self._root_node
        prefix = self._content.raw_prefix + await self._alloc.allocate(tr)
        node = self._node(prefix)
        tr.set(parent_node.pack((SUBDIRS, path[-1])), prefix)
        tr.set(node.pack((b"layer",)), layer)
        return DirectorySubspace(tuple(path), prefix, layer, self)

    async def list(self, tr, path: Sequence[str] = ()) -> List[str]:
        if path:
            node, _prefix = await self._find(tr, path)
            if node is None:
                raise DirectoryError(f"directory {tuple(path)!r} does not exist")
        else:
            node = self._root_node
        lo, hi = node.range((SUBDIRS,))
        return [node.unpack(k)[1] for k, _v in await tr.get_range(lo, hi)]

    async def exists(self, tr, path: Sequence[str]) -> bool:
        node, _ = await self._find(tr, path)
        return node is not None

    async def move(self, tr, old_path: Sequence[str], new_path: Sequence[str]) -> DirectorySubspace:
        """Re-link the node under a new parent/name; data never moves
        (the whole point of the prefix indirection)."""
        old_path, new_path = tuple(old_path), tuple(new_path)
        if new_path[:len(old_path)] == old_path:
            raise DirectoryError("cannot move a directory into itself")
        node, prefix = await self._find(tr, old_path)
        if node is None:
            raise DirectoryError(f"directory {old_path!r} does not exist")
        if await self._find(tr, new_path) != (None, None):
            raise DirectoryError(f"directory {new_path!r} already exists")
        if len(new_path) > 1:
            parent_node, _p = await self._find(tr, new_path[:-1])
            if parent_node is None:
                raise DirectoryError(f"parent {new_path[:-1]!r} does not exist")
        else:
            parent_node = self._root_node
        if len(old_path) > 1:
            old_parent, _p = await self._find(tr, old_path[:-1])
        else:
            old_parent = self._root_node
        tr.clear(old_parent.pack((SUBDIRS, old_path[-1])))
        tr.set(parent_node.pack((SUBDIRS, new_path[-1])), prefix)
        return DirectorySubspace(new_path, prefix,
                                 await self._layer_of(tr, node), self)

    async def remove(self, tr, path: Sequence[str]) -> bool:
        """Remove the directory, its subtree and ALL its contents."""
        path = tuple(path)
        node, prefix = await self._find(tr, path)
        if node is None:
            return False
        await self._remove_recursive(tr, node, prefix)
        if len(path) > 1:
            parent, _p = await self._find(tr, path[:-1])
        else:
            parent = self._root_node
        tr.clear(parent.pack((SUBDIRS, path[-1])))
        return True

    async def _remove_recursive(self, tr, node: Subspace, prefix: bytes) -> None:
        from ..core.types import strinc

        lo, hi = node.range((SUBDIRS,))
        for _k, child_prefix in await tr.get_range(lo, hi):
            await self._remove_recursive(tr, self._node(child_prefix), child_prefix)
        # contents + metadata (strinc: EVERY key under the prefix, including
        # ones whose next byte is 0xff)
        tr.clear_range(prefix, strinc(prefix))
        nlo, nhi = node.range()
        tr.clear_range(nlo, nhi)
