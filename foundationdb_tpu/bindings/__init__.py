"""Client binding surface (the analog of bindings/python).

The reference's Python binding wraps libfdb_c with the `fdb` package API:
fdb.open, @fdb.transactional, fdb.tuple, fdb.Subspace. This package offers
the same surface over the native client (client/database.py), so a user of
the reference's Python binding finds the API shapes they expect — async,
because the framework's cooperative runtime is async end to end.
"""
from . import fdb_tuple
from .fdb_api import Database, Subspace, transactional

__all__ = ["Database", "Subspace", "transactional", "fdb_tuple"]
