"""Stack-machine conformance tester (the bindings/bindingtester/ role).

The reference's bindingtester drives two language bindings through an
identical randomized instruction stream and diffs the resulting database
state + logged stack results (bindingtester.py + spec/). Here the two
"bindings" are two full STACKS OF THE FRAMEWORK differing in their
conflict engine (oracle vs TPU kernel vs sharded mesh) — every op goes
through the real client (RYW, selectors, atomics, tuple layer) into a
real simulated cluster, so a diff catches divergence anywhere from tuple
encoding to resolver verdicts.

Instruction set (the load-bearing subset of the reference's spec/):
    PUSH x | DUP | SWAP | POP | CONCAT | TUPLE_PACK n | TUPLE_UNPACK
    NEW_TRANSACTION | COMMIT | RESET
    SET | GET | CLEAR | CLEAR_RANGE | GET_RANGE | ATOMIC_ADD
    LOG_STACK  (append the popped stack to the result journal)

Execution semantics mirror the reference: GET pushes the value (or
b'RESULT_NOT_PRESENT'); COMMIT pushes b'COMMITTED' or the error name;
every engine must produce an IDENTICAL journal + final keyspace.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..core import error
from ..core.rng import DeterministicRandom
from . import fdb_tuple

NOT_PRESENT = b"RESULT_NOT_PRESENT"

OPS = (
    "PUSH", "DUP", "SWAP", "POP", "CONCAT", "TUPLE_PACK", "TUPLE_UNPACK",
    "NEW_TRANSACTION", "COMMIT", "RESET",
    "SET", "GET", "CLEAR", "CLEAR_RANGE", "GET_RANGE", "ATOMIC_ADD",
    "LOG_STACK",
)


def generate_stream(seed: int, n: int = 120) -> List[Tuple]:
    """A deterministic instruction stream: weighted toward data ops, with
    enough stack shuffling to exercise encode/decode paths."""
    rng = DeterministicRandom(seed)

    def rkey() -> bytes:
        return b"st/%03d" % rng.random_int(0, 40)

    def rval() -> bytes:
        return b"v%06d" % rng.random_int(0, 10**6)

    out: List[Tuple] = [("NEW_TRANSACTION",)]
    for _ in range(n):
        r = rng.random01()
        if r < 0.22:
            out.append(("PUSH", rkey()))
            out.append(("PUSH", rval()))
            out.append(("SET",))
        elif r < 0.38:
            out.append(("PUSH", rkey()))
            out.append(("GET",))
        elif r < 0.46:
            out.append(("PUSH", rkey()))
            out.append(("CLEAR",))
        elif r < 0.52:
            a, b = sorted([rkey(), rkey()])
            out.append(("PUSH", a))
            out.append(("PUSH", b + b"\x00"))
            out.append(("CLEAR_RANGE",))
        elif r < 0.60:
            a, b = sorted([rkey(), rkey()])
            out.append(("PUSH", a))
            out.append(("PUSH", b + b"\x00"))
            out.append(("GET_RANGE",))
        elif r < 0.66:
            out.append(("PUSH", rkey()))
            out.append(("PUSH", rng.random_int(0, 1000).to_bytes(8, "little")))
            out.append(("ATOMIC_ADD",))
        elif r < 0.72:
            out.append(("PUSH", (rkey(), rng.random_int(0, 99), "s")))
            out.append(("TUPLE_PACK",))
        elif r < 0.76 and rng.random01() < 0.5:
            out.append(("TUPLE_UNPACK",))
        elif r < 0.82:
            out.append(("DUP",))
        elif r < 0.86:
            out.append(("SWAP",))
        elif r < 0.90:
            out.append(("POP",))
        elif r < 0.94:
            out.append(("LOG_STACK",))
        elif r < 0.97:
            out.append(("COMMIT",))
            out.append(("NEW_TRANSACTION",))
        else:
            out.append(("RESET",))
    out.append(("COMMIT",))
    out.append(("LOG_STACK",))
    return out


async def run_stream(db, stream: List[Tuple]) -> List[bytes]:
    """Execute the stream against a Database; returns the journal every
    conforming stack must reproduce byte-for-byte."""
    stack: List[Any] = []
    journal: List[bytes] = []
    tr = db.create_transaction()

    def pop(n: int = 1):
        nonlocal stack
        got, stack = stack[-n:], stack[:-n]
        return got[::-1]

    def as_bytes(x: Any) -> bytes:
        if isinstance(x, bytes):
            return x
        if x is None:
            return NOT_PRESENT
        return repr(x).encode()

    for ins in stream:
        op = ins[0]
        try:
            if op == "PUSH":
                stack.append(ins[1])
            elif op == "DUP":
                if stack:
                    stack.append(stack[-1])
            elif op == "SWAP":
                if len(stack) >= 2:
                    stack[-1], stack[-2] = stack[-2], stack[-1]
            elif op == "POP":
                if stack:
                    stack.pop()
            elif op == "CONCAT":
                if len(stack) >= 2:
                    a, b = pop(2)
                    stack.append(as_bytes(a) + as_bytes(b))
            elif op == "TUPLE_PACK":
                if stack:
                    (t,) = pop(1)
                    stack.append(fdb_tuple.pack(t if isinstance(t, tuple) else (t,)))
            elif op == "TUPLE_UNPACK":
                if stack and isinstance(stack[-1], bytes):
                    (raw,) = pop(1)
                    try:
                        stack.append(repr(fdb_tuple.unpack(raw)).encode())
                    except Exception:       # noqa: BLE001 — not a tuple key
                        stack.append(b"ERROR: NOT_A_TUPLE")
            elif op == "NEW_TRANSACTION":
                tr = db.create_transaction()
            elif op == "RESET":
                tr.reset()
            elif op == "COMMIT":
                try:
                    await tr.commit()
                    stack.append(b"COMMITTED")
                except error.FDBError as e:
                    stack.append(b"ERROR: " + e.name.encode())
                tr = db.create_transaction()
            elif op == "SET":
                if len(stack) >= 2:
                    v, k = pop(2)
                    tr.set(as_bytes(k), as_bytes(v))
            elif op == "GET":
                if stack:
                    (k,) = pop(1)
                    stack.append(as_bytes(await tr.get(as_bytes(k))))
            elif op == "CLEAR":
                if stack:
                    (k,) = pop(1)
                    tr.clear(as_bytes(k))
            elif op == "CLEAR_RANGE":
                if len(stack) >= 2:
                    e_, b_ = pop(2)
                    tr.clear_range(as_bytes(b_), as_bytes(e_))
            elif op == "GET_RANGE":
                if len(stack) >= 2:
                    e_, b_ = pop(2)
                    rows = await tr.get_range(as_bytes(b_), as_bytes(e_), limit=50)
                    stack.append(fdb_tuple.pack(
                        tuple(x for kv in rows for x in kv)))
            elif op == "ATOMIC_ADD":
                if len(stack) >= 2:
                    from ..core.types import MutationType

                    v, k = pop(2)
                    tr.atomic_op(as_bytes(k), as_bytes(v), MutationType.ADD_VALUE)
            elif op == "LOG_STACK":
                journal.append(fdb_tuple.pack(tuple(as_bytes(x) for x in stack)))
                stack = []
        except error.FDBError as e:
            stack.append(b"ERROR: " + e.name.encode())
            tr = db.create_transaction()
    return journal


async def final_state(db) -> List[Tuple[bytes, bytes]]:
    async def rd(tr):
        return await tr.get_range(b"st/", b"st/\xff", limit=10_000)
    return await db.run(rd)
