"""The `fdb`-shaped binding API: open / @transactional / Subspace.

The analog of bindings/python/fdb: the reference's Python binding wraps the
C ABI; here the native client is already in-process, so the binding is the
API-compatibility veneer — the names and calling shapes a reference user
expects (`@fdb.transactional` functions that take `tr` as the first
argument and retry transparently; subspaces that pack typed tuples under a
prefix), adapted to the framework's async runtime.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

from ..client.database import Database, Transaction
from . import fdb_tuple


def transactional(fn):
    """reference: @fdb.transactional (bindings/python/fdb/impl.py). Wraps
    an async function whose first argument may be a Database or a
    Transaction: given a Database, runs the function in a retry loop and
    commits; given a Transaction, composes into the caller's transaction."""

    @functools.wraps(fn)
    async def wrapper(db_or_tr, *args, **kwargs):
        if isinstance(db_or_tr, Transaction):
            return await fn(db_or_tr, *args, **kwargs)
        db: Database = db_or_tr
        tr = db.create_transaction()
        from ..core import error

        while True:
            try:
                result = await fn(tr, *args, **kwargs)
                await tr.commit()
                return result
            except error.FDBError as e:
                await tr.on_error(e)

    return wrapper


class Subspace:
    """Tuple-packed keys under a byte prefix (bindings' Subspace class)."""

    def __init__(self, prefix_tuple: Sequence[Any] = (), raw_prefix: bytes = b""):
        self.raw_prefix = fdb_tuple.pack(tuple(prefix_tuple), raw_prefix)

    def key(self) -> bytes:
        return self.raw_prefix

    def pack(self, t: Sequence[Any] = ()) -> bytes:
        return fdb_tuple.pack(tuple(t), self.raw_prefix)

    def unpack(self, key: bytes) -> Tuple[Any, ...]:
        return fdb_tuple.unpack(key, self.raw_prefix)

    def range(self, t: Sequence[Any] = ()) -> Tuple[bytes, bytes]:
        return fdb_tuple.range_of(tuple(t), self.raw_prefix)

    def contains(self, key: bytes) -> bool:
        return key.startswith(self.raw_prefix)

    def subspace(self, t: Sequence[Any]) -> "Subspace":
        return Subspace((), self.pack(t))

    def __getitem__(self, item: Any) -> "Subspace":
        return self.subspace((item,))
