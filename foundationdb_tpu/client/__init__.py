"""Client library.

The analog of fdbclient/NativeAPI + the ReadYourWrites layer, exposing the
reference's transaction API shape: get / get_range / set / clear /
atomic_op / commit / on_error with automatic retry via `Database.run`.
"""
from .database import Database, Transaction

__all__ = ["Database", "Transaction"]
