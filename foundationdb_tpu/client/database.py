"""Database + Transaction: the client of the transaction system.

Round-1 scope of fdbclient/NativeAPI.actor.cpp + ReadYourWrites.actor.cpp:

  * GRV from the proxy (readVersionBatcher batches on the proxy side here)
  * key -> storage-server location cache filled from the proxy
    (getKeyLocation_internal:1028) with wrong_shard_server invalidation
  * reads at the read version from storage replicas (getValue:1165,
    getRange:1604), recording read conflict ranges (unless snapshot)
  * a read-your-writes overlay: uncommitted sets/clears/atomic-ops are
    visible to this transaction's own reads (WriteMap semantics)
  * commit via the proxy; on_error implements the reference's retry loop
    with randomized exponential backoff (Transaction::onError:2630)
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core import error
from ..core.knobs import CLIENT_KNOBS
from ..core.types import (
    CommitTransaction,
    Key,
    KeyRange,
    Mutation,
    MutationType,
    SINGLE_KEY_MUTATIONS,
    VERSIONSTAMP_MUTATIONS,
    Value,
    Version,
    apply_atomic_op,
    key_after,
    place_versionstamp,
    single_key_range,
    validate_versionstamp_param,
)
from ..sim.loop import TaskPriority, current_scheduler, delay
from ..sim.network import Endpoint
from ..server import proxy as proxy_mod
from ..server import storage as storage_mod
from ..server.messages import (
    CommitTransactionRequest,
    GetKeyValuesRequest,
    GetKeyServerLocationsRequest,
    GetReadVersionRequest,
    GetValueRequest,
    WatchValueRequest,
)


class KeySelector:
    """reference: KeySelectorRef (fdbclient/FDBTypes.h) — resolves to the
    key at `offset` relative to the anchor position defined by (key,
    or_equal): with i0 = the index of the first database key > key (if
    or_equal) or >= key (if not), the selector resolves to the key at
    index i0 + offset - 1, clamped to b"" / the end of the keyspace."""

    __slots__ = ("key", "or_equal", "offset")

    def __init__(self, key: Key, or_equal: bool, offset: int):
        self.key = key
        self.or_equal = or_equal
        self.offset = offset

    @classmethod
    def first_greater_or_equal(cls, key: Key) -> "KeySelector":
        return cls(key, False, 1)

    @classmethod
    def first_greater_than(cls, key: Key) -> "KeySelector":
        return cls(key, True, 1)

    @classmethod
    def last_less_than(cls, key: Key) -> "KeySelector":
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key: Key) -> "KeySelector":
        return cls(key, True, 0)

MAX_BACKOFF = 1.0
INITIAL_BACKOFF = 0.01
USER_KEYSPACE_END = b"\xff"
#: per-request reply timeout (virtual seconds). A partition between client
#: and a role must surface as a retryable error, never a hung future
#: (reference: the failure monitor + connection break give the same bound).
REQUEST_TIMEOUT = 5.0

_WRONG_SHARD = error.wrong_shard_server("").code
_MAYBE_DELIVERED = error.request_maybe_delivered("").code
_CONNECTION_FAILED = error.connection_failed("").code


def _map_read_error(e: error.FDBError) -> error.FDBError:
    """Reads are idempotent: a maybe-delivered request is safely retryable,
    so surface it as connection_failed (which on_error retries). The
    reference gets this for free from loadBalance re-issuing to replicas."""
    if e.code == _MAYBE_DELIVERED:
        return error.connection_failed("retrying idempotent read")
    return e


class Database:
    def __init__(self, net, client_addr: str, proxy_addrs: Optional[List[str]] = None,
                 coordinator_addrs: Optional[List[str]] = None):
        """Static mode (fixed proxy_addrs) or dynamic mode: given
        coordinator addresses — the cluster file — the client elects its
        view of the cluster controller by majority and fetches the proxy
        list from it, re-fetching whenever proxies fail (reference:
        MonitorLeader + openDatabase, NativeAPI/MonitorLeader.actor.cpp)."""
        self.net = net
        self.client_addr = client_addr
        from ..core.keyrangemap import KeyRangeMap as _KRM

        self.proxy_addrs = list(proxy_addrs or [])
        self.coordinator_addrs = list(coordinator_addrs or [])
        # location cache: a coalescing KeyRangeMap (the reference's
        # locationCache KeyRangeMap, NativeAPI.actor.cpp:1028); value =
        # tuple of storage addrs, None = unknown
        self._locations = _KRM(default=None)
        # rotates reads across a shard's replica team (loadBalance)
        self._lb_counter: int = 0
        # QueueModel (fdbrpc/QueueModel.cpp): per-replica latency EWMA +
        # failure penalty; the preferred replica is the model's best, with
        # periodic exploration so a recovered replica re-earns traffic
        self._queue_model: Dict[str, float] = {}

    def _proxy(self) -> str:
        rng = current_scheduler().rng
        return self.proxy_addrs[rng.random_int(0, len(self.proxy_addrs))]

    async def _get_proxy(self) -> str:
        while not self.proxy_addrs:
            if not self.coordinator_addrs:
                raise error.connection_failed("no proxies and no coordinators")
            await self._refresh_proxies()
        return self._proxy()

    def note_proxy_failure(self) -> None:
        """A proxy request failed at the transport level: in dynamic mode,
        drop the cached proxy list so the next request re-discovers (the
        generation may have turned over)."""
        if self.coordinator_addrs:
            self.proxy_addrs = []

    async def _refresh_proxies(self) -> None:
        from ..server.cluster_controller import (
            CC_OPEN_DATABASE_TOKEN,
            OpenDatabaseRequest,
        )
        from ..server.leader_election import tally_leader_once

        leader = await tally_leader_once(self.net, self.client_addr,
                                         self.coordinator_addrs)
        if leader is not None:
            try:
                info = await self.net.request(
                    self.client_addr, Endpoint(leader.address, CC_OPEN_DATABASE_TOKEN),
                    OpenDatabaseRequest(), TaskPriority.DEFAULT_ENDPOINT, timeout=1.0,
                )
            except error.FDBError:
                info = None
            if info is not None and info.recovery_state == "fully_recovered" and info.proxy_addrs:
                self.proxy_addrs = list(info.proxy_addrs)
                return
        await delay(0.25)

    async def get_status(self) -> Optional[dict]:
        """Fetch the cluster status document from the CC (StatusClient)."""
        from ..server.cluster_controller import CC_STATUS_TOKEN
        from ..server.leader_election import tally_leader_once

        leader = await tally_leader_once(self.net, self.client_addr,
                                         self.coordinator_addrs)
        if leader is None:
            return None
        try:
            return await self.net.request(
                self.client_addr, Endpoint(leader.address, CC_STATUS_TOKEN),
                None, TaskPriority.DEFAULT_ENDPOINT, timeout=2.0,
            )
        except error.FDBError:
            return None

    def create_transaction(self) -> "Transaction":
        return Transaction(self)

    async def run(self, fn, *args):
        """Retry loop (the @fdb.transactional decorator of the bindings):
        fn(tr, *args) is retried until commit succeeds."""
        tr = self.create_transaction()
        while True:
            try:
                result = await fn(tr, *args)
                await tr.commit()
                return result
            except error.FDBError as e:
                await tr.on_error(e)

    # -- location cache ------------------------------------------------------
    def invalidate_cache(self) -> None:
        self._locations.clear(default=None)

    async def get_locations(self, begin: Key, end: Key) -> List[Tuple[KeyRange, List[str]]]:
        from ..core import buggify

        if buggify.buggify():
            self.invalidate_cache()   # spontaneous cache loss (sim only)
        covered = self._cached_locations(begin, end)
        if covered is not None:
            return covered
        try:
            reply = await self.net.request(
                self.client_addr,
                Endpoint(await self._get_proxy(), proxy_mod.LOCATIONS_TOKEN),
                GetKeyServerLocationsRequest(begin=begin, end=end),
                TaskPriority.DEFAULT_ENDPOINT,
                timeout=REQUEST_TIMEOUT,
            )
        except error.FDBError as e:
            if e.code in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                self.note_proxy_failure()
            raise _map_read_error(e)
        for rng, addrs in reply.results:
            self._insert_location(rng, addrs)
        return reply.results

    def _cached_locations(self, begin: Key, end: Key) -> Optional[List[Tuple[KeyRange, List[str]]]]:
        out = []
        for cb, ce, addrs in self._locations.intersecting(begin, end):
            if addrs is None or ce is None:
                return None   # a gap: the whole span must re-resolve
            out.append((KeyRange(cb, ce), list(addrs)))
        return out or None

    def _insert_location(self, rng: KeyRange, addrs: List[str]) -> None:
        self._locations.insert(rng.begin, rng.end, tuple(addrs))

    # -- replica load balancing ---------------------------------------------
    async def storage_request(self, addrs: List[str], token: str, req,
                              priority: int = TaskPriority.DEFAULT_ENDPOINT,
                              timeout: float = 0.0, hedge: bool = True):
        """loadBalance (fdbrpc/LoadBalance.actor.h:158): reads spread
        across a shard's replica team, fail over on transport loss, and
        HEDGE — when the preferred replica is slow (read_hedge_delay), a
        second request races it on the next replica and the first answer
        wins (the reference's second-request machinery, :413). Reads are
        idempotent, so duplicates are safe. Non-transport errors
        (wrong_shard, future_version, ...) surface immediately — they come
        from a live replica and would repeat."""
        from ..core import buggify

        self._lb_counter += 1
        # QueueModel ordering: lowest expected latency first; every 8th
        # request explores round-robin so a slow-marked replica that
        # recovered re-earns traffic (the reference decays its penalties)
        if self._lb_counter % 8 == 0 or all(
            a not in self._queue_model for a in addrs
        ):
            start = self._lb_counter % len(addrs)
            order = [addrs[(start + i) % len(addrs)] for i in range(len(addrs))]
        else:
            order = sorted(addrs, key=lambda a: self._queue_model.get(a, 0.0))
        if buggify.buggify():
            # sticky replica preference: all reads pile onto one replica,
            # exercising hedging and server-side shedding instead of the
            # rotation hiding them
            order = sorted(addrs)
        to = timeout or REQUEST_TIMEOUT
        from ..sim.loop import now as _now

        def _observe(addr: str, dt: float) -> None:
            old_v = self._queue_model.get(addr, dt)
            self._queue_model[addr] = 0.75 * old_v + 0.25 * dt

        def send(i: int):
            addr = order[i % len(order)]
            t0 = _now()
            f = self.net.request(
                self.client_addr, Endpoint(addr, token), req,
                priority, timeout=to,
            )

            def done(fut) -> None:
                if fut.is_error:
                    try:
                        fut.get()
                    except error.FDBError as e:
                        if e.code in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                            # transport loss: heavy penalty pushes the
                            # replica back until it recovers
                            _observe(addr, to)
                        else:
                            # wrong_shard/future_version etc. came from a
                            # LIVE replica answering promptly: its latency
                            # is the reply time, not a penalty
                            _observe(addr, _now() - t0)
                    except BaseException:
                        pass
                else:
                    _observe(addr, _now() - t0)

            f.on_ready(done)
            return f

        if hedge and len(addrs) > 1:
            from ..sim.actors import any_of, ready_or_error

            first = send(0)
            which, _ = await any_of(
                [ready_or_error(first), delay(CLIENT_KNOBS.read_hedge_delay, priority)]
            )
            if which == 0 and not first.is_error:
                return first.get()
            if which == 0:
                # fast failure: fall through to plain failover on the rest
                try:
                    first.get()
                except error.FDBError as e:
                    if e.code not in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                        raise
                order = order[1:] + order[:1]
            else:
                # slow replica: race a hedge on the next one
                second = send(1)
                got = await any_of([ready_or_error(first), ready_or_error(second)])
                winner = (first, second)[got[0]]
                other = (second, first)[got[0]]
                if not winner.is_error:
                    return winner.get()
                try:
                    winner.get()
                except error.FDBError as e:
                    if e.code not in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                        raise
                await ready_or_error(other)
                if not other.is_error:
                    return other.get()
                try:
                    other.get()
                except error.FDBError as e:
                    if e.code not in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                        raise
                order = order[2:] + order[:2]

        last: Optional[error.FDBError] = None
        for i in range(len(addrs)):
            try:
                return await send(i)
            except error.FDBError as e:
                if e.code in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                    last = e
                    continue
                raise
        raise last if last is not None else error.connection_failed()


class Transaction:
    def __init__(self, db: Database):
        self.db = db
        self.read_version: Optional[Version] = None
        self.mutations: List[Mutation] = []
        self.read_conflict_ranges: List[KeyRange] = []
        self.write_conflict_ranges: List[KeyRange] = []
        self.committed_version: Optional[Version] = None
        self.committed_batch_index: int = 0
        self._backoff = INITIAL_BACKOFF
        self._committing = False
        self._access_system_keys = False
        self._lock_aware = False

    # -- versions ------------------------------------------------------------
    async def get_read_version(self) -> Version:
        if self.read_version is None:
            try:
                reply = await self.db.net.request(
                    self.db.client_addr,
                    Endpoint(await self.db._get_proxy(), proxy_mod.GRV_TOKEN),
                    GetReadVersionRequest(),
                    TaskPriority.GET_CONSISTENT_READ_VERSION,
                    timeout=REQUEST_TIMEOUT,
                )
            except error.FDBError as e:
                if e.code in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                    self.db.note_proxy_failure()
                raise _map_read_error(e)
            self.read_version = reply.version
        return self.read_version

    # -- the RYW overlay -----------------------------------------------------
    def _overlay_value(self, key: Key, base: Optional[Value]) -> Optional[Value]:
        """Apply this transaction's own buffered mutations for `key` on top
        of the storage value (WriteMap semantics, fdbclient/WriteMap.h)."""
        v = base
        for m in self.mutations:
            if m.type in VERSIONSTAMP_MUTATIONS:
                # The stamped bytes are unknown until commit; reading a key
                # this transaction versionstamped is an error (reference:
                # RYW marks these ranges unreadable, error 1036).
                if m.param1 == key:
                    raise error.accessed_unreadable()
                continue
            if m.type == MutationType.SET_VALUE and m.param1 == key:
                v = m.param2
            elif m.type == MutationType.CLEAR_RANGE and m.param1 <= key < m.param2:
                v = None
            elif m.type in SINGLE_KEY_MUTATIONS and m.param1 == key:
                v = apply_atomic_op(m.type, v, m.param2)
        return v

    def _needs_base_read(self, key: Key) -> bool:
        """False when buffered mutations fully determine the value: any SET
        or covering CLEAR makes the storage base irrelevant (atomic ops after
        it apply to a known value)."""
        for m in self.mutations:
            if m.type == MutationType.SET_VALUE and m.param1 == key:
                return False
            if m.type == MutationType.CLEAR_RANGE and m.param1 <= key < m.param2:
                return False
        return True

    # -- reads ---------------------------------------------------------------
    async def get(self, key: Key, snapshot: bool = False) -> Optional[Value]:
        version = await self.get_read_version()
        if not snapshot:
            self.read_conflict_ranges.append(single_key_range(key))
        base: Optional[Value] = None
        if self._needs_base_read(key):
            base = await self._storage_get(key, version)
        return self._overlay_value(key, base)

    async def get_range(
        self, begin: Key, end: Key, limit: int = 10_000, snapshot: bool = False, reverse: bool = False
    ) -> List[Tuple[Key, Value]]:
        if begin >= end:
            return []
        version = await self.get_read_version()
        # With buffered mutations the overlay may add/remove rows, so the
        # storage limit cannot be trusted; fetch the whole range (paged).
        fetch_limit = limit if not self.mutations else None
        data, server_truncated = await self._storage_get_range(
            begin, end, version, fetch_limit, reverse
        )
        merged = self._overlay_range(begin, end, data)
        if reverse:
            merged = sorted(merged, key=lambda kv: kv[0], reverse=True)
        result = merged[:limit]
        if not snapshot:
            # When the limit truncates the read, narrow the conflict range to
            # the keys actually observed (reference: ReadYourWrites narrows
            # to the returned ranges) — a write past the last returned key
            # was never read and must not abort us. Truncation happens either
            # in the overlay (len(merged) > limit) or at the storage server
            # (server_truncated, via GetKeyValuesReply.more).
            if (len(merged) > limit or server_truncated) and result:
                if reverse:
                    self.read_conflict_ranges.append(KeyRange(result[-1][0], end))
                else:
                    self.read_conflict_ranges.append(KeyRange(begin, key_after(result[-1][0])))
            else:
                self.read_conflict_ranges.append(KeyRange(begin, end))
        return result

    def _overlay_range(
        self, begin: Key, end: Key, data: List[Tuple[Key, Value]]
    ) -> List[Tuple[Key, Value]]:
        if not self.mutations:
            return list(data)
        result: Dict[Key, Optional[Value]] = dict(data)
        for m in self.mutations:
            if m.type in VERSIONSTAMP_MUTATIONS:
                if begin <= m.param1 < end:
                    raise error.accessed_unreadable()
                continue
            if m.type == MutationType.SET_VALUE:
                if begin <= m.param1 < end:
                    result[m.param1] = m.param2
            elif m.type == MutationType.CLEAR_RANGE:
                for k in [k for k in result if m.param1 <= k < m.param2]:
                    result[k] = None
            elif m.type in SINGLE_KEY_MUTATIONS:
                if begin <= m.param1 < end:
                    result[m.param1] = apply_atomic_op(m.type, result.get(m.param1), m.param2)
        return sorted(
            [(k, v) for k, v in result.items() if v is not None], key=lambda kv: kv[0]
        )

    # -- storage rpc with location cache + retry -----------------------------
    async def _storage_get(self, key: Key, version: Version) -> Optional[Value]:
        fresh_tries = 0
        while True:
            locs = await self.db.get_locations(key, key_after(key))
            try:
                reply = await self.db.storage_request(
                    locs[0][1], storage_mod.GET_VALUE_TOKEN,
                    GetValueRequest(key=key, version=version),
                )
                return reply.value
            except error.FDBError as e:
                if e.code == _WRONG_SHARD:
                    self.db.invalidate_cache()
                    continue
                if e.code in (_CONNECTION_FAILED, _MAYBE_DELIVERED) and fresh_tries < 2:
                    # The whole cached team is unreachable — it may have
                    # been moved away (MoveKeys retired the old replicas).
                    # Re-resolve locations before giving up (loadBalance's
                    # allAlternativesFailed -> re-fetch).
                    fresh_tries += 1
                    self.db.invalidate_cache()
                    await delay(0.1)
                    continue
                raise _map_read_error(e)

    async def _storage_get_range(
        self, begin: Key, end: Key, version: Version, limit: Optional[int], reverse: bool
    ) -> Tuple[List[Tuple[Key, Value]], bool]:
        """limit=None fetches the whole range, paging per shard until each
        shard is exhausted. Returns (data, truncated): truncated means the
        servers may hold more rows in [begin, end) past the returned ones."""
        out: List[Tuple[Key, Value]] = []
        fresh_tries = 0
        while True:
            locs = await self.db.get_locations(begin, end)
            if reverse:
                locs = list(reversed(locs))
            try:
                for i, (rng, addrs) in enumerate(locs):
                    cb, ce = max(begin, rng.begin), min(end, rng.end)
                    while cb < ce:
                        want = 10_000 if limit is None else min(limit - len(out), 10_000)
                        reply = await self.db.storage_request(
                            addrs, storage_mod.GET_KEY_VALUES_TOKEN,
                            GetKeyValuesRequest(begin=cb, end=ce, version=version, limit=want, reverse=reverse),
                        )
                        out.extend(reply.data)
                        if limit is not None and len(out) >= limit:
                            truncated = bool(reply.more) or i + 1 < len(locs)
                            return out, truncated
                        if not reply.more or not reply.data:
                            break
                        if reverse:
                            ce = reply.data[-1][0]
                        else:
                            cb = key_after(reply.data[-1][0])
                return out, False
            except error.FDBError as e:
                if e.code == _WRONG_SHARD:
                    self.db.invalidate_cache()
                    out = []
                    continue
                if e.code in (_CONNECTION_FAILED, _MAYBE_DELIVERED) and fresh_tries < 2:
                    # dead cached team: the shard may have moved (MoveKeys)
                    fresh_tries += 1
                    self.db.invalidate_cache()
                    out = []
                    await delay(0.1)
                    continue
                raise _map_read_error(e)

    # -- writes ----------------------------------------------------------------
    def set(self, key: Key, value: Value) -> None:
        self._check_writable(key)
        self.mutations.append(Mutation(MutationType.SET_VALUE, key, value))
        self.write_conflict_ranges.append(single_key_range(key))

    def clear(self, key: Key) -> None:
        self.clear_range(key, key_after(key))

    def clear_range(self, begin: Key, end: Key) -> None:
        self._check_writable(begin)
        if end > USER_KEYSPACE_END:
            # The end bound is exclusive, so end == \xff is legal.
            raise error.key_outside_legal_range()
        if begin >= end:
            return
        self.mutations.append(Mutation(MutationType.CLEAR_RANGE, begin, end))
        self.write_conflict_ranges.append(KeyRange(begin, end))

    def atomic_op(self, key: Key, param: Value, op: MutationType) -> None:
        self._check_writable(key)
        if op in VERSIONSTAMP_MUTATIONS:
            stamped = key if op == MutationType.SET_VERSIONSTAMPED_KEY else param
            if not validate_versionstamp_param(stamped):
                raise error.client_invalid_operation(
                    "versionstamp offset out of range or param too short"
                )
        self.mutations.append(Mutation(op, key, param))
        self.write_conflict_ranges.append(single_key_range(key))

    def get_versionstamp(self) -> bytes:
        """The 10-byte versionstamp assigned at commit (reference:
        Transaction::getVersionstamp, NativeAPI.actor.cpp:2785-2792; value
        layout per fdb.options set_versionstamped_key). Only valid after a
        successful commit."""
        if self.committed_version is None:
            raise error.client_invalid_operation("get_versionstamp before commit")
        return place_versionstamp(self.committed_version, self.committed_batch_index)

    async def get_key(self, selector: KeySelector, snapshot: bool = False) -> Key:
        """Resolve a key selector (reference: Transaction::getKey,
        NativeAPI.actor.cpp:1234). Resolution scans through get_range, so
        the scanned span lands in the read conflict set exactly like the
        reference's selector reads (unless snapshot)."""
        k, or_equal, offset = selector.key, selector.or_equal, selector.offset
        if offset >= 1:
            start = key_after(k) if or_equal else k
            rows = await self.get_range(start, USER_KEYSPACE_END,
                                        limit=offset, snapshot=snapshot)
            if len(rows) >= offset:
                return rows[offset - 1][0]
            return USER_KEYSPACE_END
        n = 1 - offset
        end = key_after(k) if or_equal else k
        rows = await self.get_range(b"", end, limit=n, reverse=True,
                                    snapshot=snapshot)
        if len(rows) >= n:
            return rows[n - 1][0]
        return b""

    async def get_range_selector(self, begin: KeySelector, end: KeySelector,
                                 limit: Optional[int] = None,
                                 reverse: bool = False,
                                 snapshot: bool = False):
        """Range read with selector endpoints (getRange with selectors)."""
        b = await self.get_key(begin, snapshot=snapshot)
        e = await self.get_key(end, snapshot=snapshot)
        if b >= e:
            return []
        return await self.get_range(b, e, limit=limit if limit is not None else 10_000,
                                    reverse=reverse, snapshot=snapshot)

    def watch(self, key: Key, expected: object = ...,
              expected_version: Optional[Version] = None):
        """Future firing when `key`'s value changes from `expected`
        (reference: Transaction::watch, NativeAPI.actor.cpp:1302). With no
        `expected`, the watch snapshot-reads the current value first; pass
        the value your transaction already read (plus its read version) to
        close the read-then-watch race — the reference gets that atomicity
        from registering the watch inside the reading transaction. Survives
        storage failures by re-registering; cancel the returned task to
        stop watching."""
        from ..sim.loop import spawn

        _UNSET = object()

        async def read_current():
            """Snapshot-read key with full retry (storage may be mid-reboot
            or mid-recovery when the watch re-registers)."""
            tr = self.db.create_transaction()
            while True:
                try:
                    value = await tr.get(key, snapshot=True)
                    return value, tr.read_version
                except error.FDBError as e:
                    await tr.on_error(e)

        async def watch_actor():
            if expected is ...:
                exp, version = await read_current()
            else:
                exp = expected
                version = expected_version or self.read_version or 0
            while True:
                try:
                    locs = await self.db.get_locations(key, key_after(key))
                    # One rotated replica, NO failover: a watch is a long
                    # poll, and chaining 30s parks across the team would
                    # multiply the re-check interval by the team size.
                    addrs = locs[0][1]
                    self.db._lb_counter += 1
                    addr = addrs[self.db._lb_counter % len(addrs)]
                    return await self.db.net.request(
                        self.db.client_addr,
                        Endpoint(addr, storage_mod.WATCH_VALUE_TOKEN),
                        WatchValueRequest(key=key, value=exp, version=version),
                        TaskPriority.DEFAULT_ENDPOINT,
                        timeout=30.0,
                    )
                except error.FDBError as e:
                    if e.code == _WRONG_SHARD:
                        self.db.invalidate_cache()
                    elif not e.is_retryable() and e.code != _MAYBE_DELIVERED:
                        raise
                    # Transport loss or parked-too-long: re-read; if the
                    # value moved while we were not watching, fire now.
                    await delay(0.25)
                    current, version = await read_current()
                    if current != exp:
                        return current

        return spawn(watch_actor(), TaskPriority.DEFAULT_ENDPOINT, name=f"watch:{key!r}")

    def add_read_conflict_range(self, begin: Key, end: Key) -> None:
        self.read_conflict_ranges.append(KeyRange(begin, end))

    def add_write_conflict_range(self, begin: Key, end: Key) -> None:
        self.write_conflict_ranges.append(KeyRange(begin, end))

    def set_access_system_keys(self) -> None:
        """Allow writes to the `\\xff` system keyspace (the reference's
        ACCESS_SYSTEM_KEYS transaction option; used by ManagementAPI-class
        callers like the master's DD-lite)."""
        self._access_system_keys = True

    def set_lock_aware(self) -> None:
        """Commit through a database lock (the reference's LOCK_AWARE
        transaction option; DR's apply transactions use it against the
        locked destination)."""
        self._lock_aware = True

    def _check_writable(self, key: Key) -> None:
        if self._committing:
            raise error.used_during_commit()
        if key >= USER_KEYSPACE_END and not self._access_system_keys:
            raise error.key_outside_legal_range()

    # -- commit / retry --------------------------------------------------------
    async def commit(self) -> Version:
        if not self.mutations and not self.write_conflict_ranges:
            # Read-only transactions commit trivially (reference:
            # Transaction::commit fast path).
            self.committed_version = self.read_version or 0
            return self.committed_version
        self._committing = True
        txn = CommitTransaction(
            read_conflict_ranges=list(self.read_conflict_ranges),
            write_conflict_ranges=list(self.write_conflict_ranges),
            mutations=list(self.mutations),
            read_snapshot=await self.get_read_version(),
            # management/DR transactions commit through a database lock
            # (system-keys access implies LOCK_AWARE, like the reference's
            # ManagementAPI callers; DR applies set it explicitly)
            lock_aware=self._access_system_keys or self._lock_aware,
        )
        try:
            reply = await self.db.net.request(
                self.db.client_addr,
                Endpoint(await self.db._get_proxy(), proxy_mod.COMMIT_TOKEN),
                CommitTransactionRequest(transaction=txn),
                TaskPriority.PROXY_COMMIT,
                timeout=2 * REQUEST_TIMEOUT,
            )
        except error.FDBError as e:
            if e.code in (_MAYBE_DELIVERED, _CONNECTION_FAILED):
                self.db.note_proxy_failure()
                # The commit may or may not have happened (reference:
                # tryCommit maps transport loss to commit_unknown_result).
                raise error.commit_unknown_result(e.name)
            raise
        finally:
            self._committing = False
        self.committed_version = reply.version
        self.committed_batch_index = reply.txn_batch_index
        return reply.version

    async def on_error(self, e: error.FDBError) -> None:
        """reference: Transaction::onError (NativeAPI.actor.cpp:2630):
        retryable errors reset the transaction after randomized backoff;
        everything else re-raises."""
        if not isinstance(e, error.FDBError) or not e.is_retryable():
            raise e
        if e.code == error.transaction_too_old("").code:
            # Defense in depth for generation turnover: a deposed proxy can
            # keep answering GRV with pre-jump versions that storage has
            # already forgotten; re-resolve the proxy list so the retry
            # reaches the live generation.
            self.db.note_proxy_failure()
        from ..core import buggify

        rng = current_scheduler().rng
        backoff = self._backoff
        if buggify.buggify():
            # impatient client: minimal backoff floods the retry path and
            # stresses idempotent-commit / replay-window handling
            backoff = 0.001
        await delay(backoff * rng.random01())
        self._backoff = min(self._backoff * CLIENT_KNOBS.backoff_growth_rate,
                            CLIENT_KNOBS.max_backoff)
        self.reset()

    def reset(self) -> None:
        self.read_version = None
        self.mutations = []
        self.read_conflict_ranges = []
        self.write_conflict_ranges = []
        self._committing = False
