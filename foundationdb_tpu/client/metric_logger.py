"""MetricLogger: persist TDMetrics into the database itself.

Re-design of fdbclient/MetricLogger.actor.cpp: an actor drains a process's
TDMetricCollection on an interval and writes each metric's change blocks
into the `\\xff/metrics/` keyspace, keyed so a time-range read is one
range read:

    \\xff/metrics/<process>/<metric>/<time-be-bytes> = wire([(t, v), ...])

Blocks are transactional writes through the normal commit path (ordered
with user traffic, replicated, recovered); queries reconstruct a level
metric at any time from its change history."""
from __future__ import annotations

import struct
from typing import List, Tuple

from ..core import error, wire
from ..core.tdmetric import TDMetricCollection
from ..sim.loop import delay

METRICS_PREFIX = b"\xff/metrics/"


def _block_key(process: str, metric: str, t: float, seq: int = 0) -> bytes:
    # millisecond-resolution big-endian time + a per-logger sequence:
    # lexicographic == chronological, and two blocks whose first entries
    # share a millisecond can never overwrite each other
    ms = int(t * 1000)
    return (METRICS_PREFIX + process.encode() + b"/" + metric.encode()
            + b"/" + struct.pack(">QI", ms, seq))


async def run_metric_logger(db, collection: TDMetricCollection,
                            process: str, interval: float = 2.0,
                            sync=None) -> None:
    """Drain `collection` into the database forever (spawn as an actor).
    `sync` is an optional pre-drain hook — pass
    `core.telemetry.hub().sync` so the unified registry pulls engine perf /
    batcher / health values into the collection right before each drain."""
    from ..core import buggify

    seq = 0
    while True:
        await delay(interval)
        if buggify.buggify():
            # laggy telemetry drain: metrics recorded meanwhile must buffer
            # (never drop) and land in a later block — the drain-vs-record
            # interleaving the tdmetric tests pin
            await delay(interval * 4)
        if sync is not None:
            sync()
        drained = collection.drain_all()
        if not drained:
            continue
        seq += 1
        try:
            async def put(tr, seq=seq):
                tr.set_access_system_keys()
                for name, entries in drained.items():
                    tr.set(_block_key(process, name, entries[0][0], seq),
                           wire.dumps(entries))
            await db.run(put)
        except error.FDBError:
            # telemetry is best-effort: re-buffer nothing, drop the block
            # (the reference tolerates metric loss the same way)
            continue


async def read_metric(db, process: str, metric: str,
                      t0: float = 0.0, t1: float = 2**40
                      ) -> List[Tuple[float, int]]:
    """Every persisted (time, value) entry of `metric` in [t0, t1].
    Blocks are keyed by their FIRST entry's time, so the scan starts at
    the metric's beginning (a block straddling t0 would otherwise be
    missed) and the per-entry filter clips exactly."""
    lo = _block_key(process, metric, 0.0)
    hi = _block_key(process, metric, t1, 2**32 - 1) + b"\xff"

    async def rd(tr):
        tr.set_access_system_keys()
        return await tr.get_range(lo, hi, limit=10_000, snapshot=True)

    rows = await db.run(rd)
    out: List[Tuple[float, int]] = []
    for _k, v in rows:
        out.extend((t, val) for t, val in wire.loads(v) if t0 <= t <= t1)
    return out
