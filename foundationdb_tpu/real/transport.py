"""Real transport: token-addressed RPC over TCP (asyncio).

The FlowTransport analog (fdbrpc/FlowTransport.actor.cpp) for clusters of
actual OS processes: the same Endpoint/request/one_way surface the sim
network exposes, so code written against that seam can run over real
sockets. Frames are length-prefixed and carry the repo's versioned flat
wire format (core/wire.py) — the on-disk encoding and the on-wire
encoding are the same bytes, like flow/serialize.h serving both.

    frame := [u32 len][wire payload]
    payload := {"kind": "req"|"reply"|"err"|"oneway",
                "id": int, "token": str, "body": any}

Every dataclass in server/messages.py is wire-registered at import, so
role interfaces serialize without pickle. Connections are per-peer,
created on demand, reconnected on failure; replies match requests by id.
A request to an address with no listener (or a handler raising) surfaces
as the same FDBError codes the sim transport uses, keeping failure
handling uniform across both worlds.
"""
from __future__ import annotations

import asyncio
import dataclasses
import struct
from typing import Any, Callable, Dict, Optional, Tuple

from ..core import buggify, error, wire
from ..sim.network import Endpoint


def _register_messages() -> None:
    """Wire-register every role-interface dataclass, so the full dynamic
    cluster's RPC surface (recruitment, coordination, recovery, DD,
    ratekeeper) serializes — the real-mode analog of the reference's
    serializable interface structs (fdbclient/*Interface.h)."""
    from ..core import types as t
    from ..server import cluster_controller as cc
    from ..server import coordinated_state as cst
    from ..server import coordination as coord
    from ..server import log_system as ls
    from ..server import master as master_mod
    from ..server import masterserver as ms
    from ..server import messages as msgs
    from ..server import proxy as proxy_mod
    from ..server import ratekeeper as rk
    from ..server import storage as storage_mod
    from ..server import worker as worker_mod
    from ..sim import network as simnet

    for mod in (msgs, t, coord, cst, ls, worker_mod, cc, ms, storage_mod,
                rk, master_mod, proxy_mod, simnet):
        for name in dir(mod):
            obj = getattr(mod, name)
            if dataclasses.is_dataclass(obj) and isinstance(obj, type):
                if obj not in wire._RECORD_NAMES and obj not in wire._ADAPTERS:
                    wire.register_record(obj)


_register_messages()

_LEN = struct.Struct("<I")
MAX_FRAME = 64 << 20

#: wire protocol version, exchanged in the connection handshake (the
#: FlowTransport ConnectPacket's protocolVersion, FlowTransport.actor.cpp):
#: both sides must agree before any request crosses the link — a version
#: skew surfaces as an immediate typed error, never a mis-decoded frame
PROTOCOL_VERSION = 1


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    hdr = await reader.readexactly(4)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise error.connection_failed("oversized frame")
    return wire.loads(await reader.readexactly(n))


def _write_frame(writer: asyncio.StreamWriter, payload: Any) -> None:
    raw = wire.dumps(payload)
    writer.write(_LEN.pack(len(raw)) + raw)


class _Peer:
    """One outgoing connection + its in-flight request table."""

    def __init__(self, addr: str):
        self.addr = addr
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: Dict[int, asyncio.Future] = {}
        self.lock = asyncio.Lock()
        self._pump: Optional[asyncio.Task] = None

    async def connect(self) -> None:
        from . import tls

        # ONE snapshot for the whole connection: a concurrent set_tls()
        # can't desync the handshake context from the subject rules
        snap = tls.current()
        host, port = self.addr.rsplit(":", 1)
        self.reader, self.writer = await asyncio.open_connection(
            host, int(port), ssl=snap.client_ctx if snap else None)
        if snap is not None and not tls.verify_peer(self.writer, snap):
            self.writer.close()
            self.reader = self.writer = None
            raise error.connection_failed("peer failed TLS subject check")
        # protocol-version handshake BEFORE the reply pump owns the reader:
        # hello out, hello back, versions must match
        _write_frame(self.writer, {"kind": "hello", "id": 0,
                                   "token": "", "body": PROTOCOL_VERSION})
        await self.writer.drain()
        try:
            reply = await asyncio.wait_for(_read_frame(self.reader), timeout=5.0)
        except asyncio.TimeoutError:
            self.writer.close()
            self.reader = self.writer = None
            raise error.connection_failed("handshake timeout")
        except asyncio.IncompleteReadError:
            # no timeout happened: the peer CLOSED mid-handshake — the
            # classic symptom of a plaintext/TLS listener mismatch
            self.writer.close()
            self.reader = self.writer = None
            raise error.connection_failed(
                "connection closed during handshake (TLS mismatch?)")
        if reply.get("kind") == "err":
            self.writer.close()
            self.reader = self.writer = None
            raise error.connection_failed(
                f"peer refused connection: {reply.get('body')}")
        if reply.get("kind") != "hello" or reply.get("body") != PROTOCOL_VERSION:
            self.writer.close()
            self.reader = self.writer = None
            raise error.connection_failed(
                f"protocol version mismatch: ours {PROTOCOL_VERSION}, "
                f"theirs {reply.get('body')}")
        self._pump = asyncio.create_task(self._pump_replies())

    async def _pump_replies(self) -> None:
        try:
            while True:
                msg = await _read_frame(self.reader)
                fut = self.pending.pop(msg.get("id"), None)
                if fut is None or fut.done():
                    continue
                if msg["kind"] == "err":
                    code, name = msg["body"]
                    fut.set_exception(error.FDBError(code, name))
                else:
                    fut.set_result(msg["body"])
        except asyncio.CancelledError:
            raise
        except Exception:
            # ANY pump death (decode error, oversized frame, socket loss)
            # must fail the in-flight table and drop the connection, or the
            # peer wedges: requests keep writing to a socket nobody reads
            self._fail_all()

    def _fail_all(self) -> None:
        """Tear down the connection: fail waiters, close the socket, stop
        the pump (unless we ARE the pump, which is exiting anyway)."""
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(error.connection_failed("peer connection lost"))
        self.pending.clear()
        if self.writer is not None:
            self.writer.close()
        self.reader = self.writer = None
        pump = self._pump
        if pump is not None and pump is not asyncio.current_task():
            pump.cancel()
        self._pump = None

    def close(self) -> None:
        self._fail_all()


class RealProcess:
    """The listener half: a handler registry bound to a TCP port
    (workerServer's mailbox). `address` is "host:port"."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._tls = None   # TLS snapshot, captured at start()
        #: strong refs — the loop keeps only weak ones, and a collected
        #: handler task means a silently dropped reply
        self._tasks: set = set()
        #: how handler coroutines are driven: None = plain asyncio await
        #: (handlers are asyncio coroutines); the real-cluster runtime
        #: installs a dispatcher that runs them on the node's cooperative
        #: scheduler instead (handlers there await scheduler Futures,
        #: which asyncio cannot drive)
        self.dispatcher: Optional[Callable] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, token: str, handler: Callable) -> None:
        self.handlers[token] = handler

    def unregister(self, token: str) -> None:
        self.handlers.pop(token, None)

    async def start(self) -> None:
        from . import tls

        # snapshot at listen time; _serve checks peers against the SAME
        # policy the listener's handshake context came from
        self._tls = tls.current()
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port,
            ssl=self._tls.server_ctx if self._tls else None)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # drop live connections too: wait_closed() blocks on them
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
            self._server = None

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        from . import tls

        self._conns.add(writer)
        shaken = False
        try:
            if self._tls is not None and not tls.verify_peer(writer,
                                                             self._tls):
                # consume the client's in-flight hello first — closing
                # with unread bytes degenerates to an RST that destroys
                # the diagnostic frame below
                try:
                    await asyncio.wait_for(_read_frame(reader), 5.0)
                except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                        ConnectionError, OSError):
                    pass
                # tell the peer WHY before dropping — a silent close
                # reads as a spurious transport failure and sends the
                # operator chasing the network instead of the certs
                _write_frame(writer, {
                    "kind": "err", "id": 0,
                    "body": (error.connection_failed("").code,
                             "tls_subject_rejected")})
                await writer.drain()
                return
            while True:
                msg = await _read_frame(reader)
                if msg["kind"] == "hello":
                    if msg.get("body") != PROTOCOL_VERSION:
                        _write_frame(writer, {"kind": "err", "id": 0,
                                              "body": (error.connection_failed("").code,
                                                       "protocol_mismatch")})
                        await writer.drain()
                        return
                    _write_frame(writer, {"kind": "hello", "id": 0,
                                          "token": "", "body": PROTOCOL_VERSION})
                    await writer.drain()
                    shaken = True
                    continue
                if not shaken:
                    # no frame is serviced before the version handshake: a
                    # peer speaking a pre-handshake protocol must fail HERE,
                    # not be decoded under skew
                    _write_frame(writer, {"kind": "err", "id": msg.get("id", 0),
                                          "body": (error.connection_failed("").code,
                                                   "handshake_required")})
                    await writer.drain()
                    return
                if msg["kind"] == "oneway":
                    handler = self.handlers.get(msg["token"])
                    if handler is not None:
                        self._track(asyncio.create_task(
                            self._run_oneway(handler, msg["body"])))
                    continue
                self._track(asyncio.create_task(self._answer(writer, msg)))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    def _track(self, task: asyncio.Task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_oneway(self, handler, body) -> None:
        try:
            if self.dispatcher is not None:
                await self.dispatcher(handler, body)
            else:
                await handler(body)
        except Exception:
            pass

    async def _answer(self, writer: asyncio.StreamWriter, msg) -> None:
        if buggify.buggify():
            await asyncio.sleep(0.05)   # slow service: client timeouts race
        handler = self.handlers.get(msg["token"])
        try:
            if handler is None:
                raise error.FDBError(error.request_maybe_delivered("").code,
                                     "request_maybe_delivered")
            if self.dispatcher is not None:
                body = await self.dispatcher(handler, msg["body"])
            else:
                body = await handler(msg["body"])
            reply = {"kind": "reply", "id": msg["id"], "body": body}
        except error.FDBError as e:
            reply = {"kind": "err", "id": msg["id"], "body": (e.code, e.name)}
        except Exception:
            reply = {"kind": "err", "id": msg["id"],
                     "body": (error.internal_error("").code, "internal_error")}
        try:
            _write_frame(writer, reply)
            await writer.drain()
        except (ConnectionError, OSError):
            pass


class RealNetwork:
    """The sender half: the sim network's request/one_way surface over
    real sockets. One instance per OS process; peers cached per address."""

    def __init__(self):
        self._peers: Dict[str, _Peer] = {}
        self._next_id = 0

    async def _peer(self, addr: str) -> _Peer:
        p = self._peers.get(addr)
        if p is None:
            p = self._peers[addr] = _Peer(addr)
        async with p.lock:
            if p.writer is None:
                try:
                    await p.connect()
                except (ConnectionError, OSError) as e:
                    raise error.connection_failed(str(e))
        return p

    async def request(self, src: str, ep: Endpoint, payload: Any,
                      priority: int = 0, timeout: float = 5.0) -> Any:
        p = await self._peer(ep.address)
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        p.pending[rid] = fut
        try:
            frame = {"kind": "req", "id": rid, "token": ep.token, "body": payload}
            _write_frame(p.writer, frame)
            if buggify.buggify():
                # duplicate delivery (the transport's redelivery semantics):
                # the server answers twice; handlers must be idempotent and
                # the pump drops the orphan reply
                _write_frame(p.writer, frame)
            await p.writer.drain()
        except (ConnectionError, OSError) as e:
            p.pending.pop(rid, None)
            p._fail_all()
            raise error.connection_failed(str(e))
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            p.pending.pop(rid, None)
            raise error.request_maybe_delivered("request timed out")

    async def one_way(self, src: str, ep: Endpoint, payload: Any,
                      priority: int = 0) -> None:
        if buggify.buggify():
            return   # unreliable by contract: drop outright
        try:
            p = await self._peer(ep.address)
            _write_frame(p.writer, {"kind": "oneway", "id": 0,
                                    "token": ep.token, "body": payload})
            await p.writer.drain()
        except (error.FDBError, ConnectionError, OSError):
            pass   # unreliable by contract

    def close(self) -> None:
        for p in self._peers.values():
            p.close()
        self._peers.clear()
