"""Real transport: token-addressed RPC over TCP (asyncio).

The FlowTransport analog (fdbrpc/FlowTransport.actor.cpp) for clusters of
actual OS processes: the same Endpoint/request/one_way surface the sim
network exposes, so code written against that seam can run over real
sockets. Frames are length-prefixed and carry the repo's versioned flat
wire format (core/wire.py) — the on-disk encoding and the on-wire
encoding are the same bytes, like flow/serialize.h serving both.

    frame := [u32 len][wire payload]
    payload := {"kind": "req"|"reply"|"err"|"oneway",
                "id": int, "token": str, "body": any,
                "ttl": float?,        # propagated deadline budget
                "tc": TraceContext?}  # propagated trace context
                                      # (core/trace.py; spans enabled only)

Every dataclass in server/messages.py is wire-registered at import, so
role interfaces serialize without pickle. Connections are per-peer,
created on demand, reconnected on failure; replies match requests by id.
A request to an address with no listener (or a handler raising) surfaces
as the same FDBError codes the sim transport uses, keeping failure
handling uniform across both worlds.
"""
from __future__ import annotations

import asyncio
import dataclasses
import socket as _socket
import struct
from typing import Any, Callable, Dict, Optional, Tuple

from ..core import buggify, error, wire
from ..core.knobs import FLOW_KNOBS
from ..core.trace import (
    current_trace_context,
    g_spans,
    pop_trace_context,
    push_trace_context,
)
from ..sim.network import Endpoint


def _register_messages() -> None:
    """Wire-register every role-interface dataclass, so the full dynamic
    cluster's RPC surface (recruitment, coordination, recovery, DD,
    ratekeeper) serializes — the real-mode analog of the reference's
    serializable interface structs (fdbclient/*Interface.h)."""
    from ..core import types as t
    from ..server import cluster_controller as cc
    from ..server import coordinated_state as cst
    from ..server import coordination as coord
    from ..server import log_system as ls
    from ..server import master as master_mod
    from ..server import masterserver as ms
    from ..server import messages as msgs
    from ..server import proxy as proxy_mod
    from ..server import ratekeeper as rk
    from ..server import storage as storage_mod
    from ..server import worker as worker_mod
    from ..sim import network as simnet

    for mod in (msgs, t, coord, cst, ls, worker_mod, cc, ms, storage_mod,
                rk, master_mod, proxy_mod, simnet):
        for name in dir(mod):
            obj = getattr(mod, name)
            if dataclasses.is_dataclass(obj) and isinstance(obj, type):
                if obj not in wire._RECORD_NAMES and obj not in wire._ADAPTERS:
                    wire.register_record(obj)


_register_messages()

_LEN = struct.Struct("<I")
MAX_FRAME = 64 << 20

#: wire protocol version, exchanged in the connection handshake (the
#: FlowTransport ConnectPacket's protocolVersion, FlowTransport.actor.cpp):
#: both sides must agree before any request crosses the link — a version
#: skew surfaces as an immediate typed error, never a mis-decoded frame
PROTOCOL_VERSION = 1


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    hdr = await reader.readexactly(4)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise error.connection_failed("oversized frame")
    if buggify.buggify():
        # straddled frame: the body arrives a beat after the header —
        # readers must tolerate a frame split across socket reads
        await asyncio.sleep(0)
    return wire.loads(await reader.readexactly(n))


def _nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on an RPC connection: with several small frames in
    flight per connection, Nagle + delayed ACK serializes successive
    writes into ~40 ms stalls — the classic small-RPC latency cliff. Every
    serious RPC transport (the reference's FlowTransport included) runs
    NODELAY; measured here as a 30-60 ms p99 tail under concurrency."""
    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except (OSError, ValueError):
            pass   # non-TCP transport (tests may stub); nothing to tune


def _write_frame(writer: asyncio.StreamWriter, payload: Any) -> None:
    raw = wire.dumps(payload)
    if buggify.buggify():
        # torn write: header and body leave in separate writes, so the
        # peer's reader sees a partial frame on the wire mid-request
        writer.write(_LEN.pack(len(raw)))
        writer.write(raw)
        return
    writer.write(_LEN.pack(len(raw)) + raw)


class _Peer:
    """One outgoing connection + its in-flight request table, with
    jittered-exponential reconnect backoff: consecutive connect failures
    widen `retry_at`, and requests landing inside the window fail fast
    (connection_failed) instead of hammering a dead peer with SYNs."""

    def __init__(self, addr: str, src: str = "", chaos=None):
        self.addr = addr
        #: owning network's process name (chaos targets faults by name)
        self.src = src
        #: optional NetworkNemesis hook (real/chaos.py): consulted at
        #: connect time for injected handshake stalls
        self.chaos = chaos
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: Dict[int, asyncio.Future] = {}
        self.lock = asyncio.Lock()
        self._pump: Optional[asyncio.Task] = None
        #: consecutive failed connects; 0 after any successful handshake
        self.fail_streak = 0
        #: loop time before which reconnect attempts fail fast
        self.retry_at = 0.0

    def note_connect_failure(self, rng01=None) -> float:
        """Advance the backoff window after a failed connect; returns the
        backoff applied. Jitter draws from `rng01` when given (a seeded
        campaign), else the peer spreads itself with hash-derived jitter."""
        self.fail_streak += 1
        base = float(FLOW_KNOBS.real_reconnect_backoff_initial_s)
        cap = float(FLOW_KNOBS.real_reconnect_backoff_max_s)
        jit = float(FLOW_KNOBS.real_reconnect_backoff_jitter)
        backoff = min(base * (2 ** (self.fail_streak - 1)), cap)
        if jit > 0:
            u = rng01() if rng01 is not None else (
                (hash((self.addr, self.fail_streak)) & 0xFFFF) / 0xFFFF)
            backoff *= (1 - jit) + 2 * jit * u
        self.retry_at = asyncio.get_running_loop().time() + backoff
        return backoff

    async def connect(self) -> None:
        from . import tls

        # ONE snapshot for the whole connection: a concurrent set_tls()
        # can't desync the handshake context from the subject rules.
        # The half-open connection lives in LOCALS until the handshake
        # completes: a concurrent _fail_all() (reset fault, pump death of
        # the previous incarnation) must not be able to null out the
        # writer mid-handshake — it simply never sees this one until it
        # is published whole.
        snap = tls.current()
        host, port = self.addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(
            host, int(port), ssl=snap.client_ctx if snap else None)
        _nodelay(writer)
        try:
            if snap is not None and not tls.verify_peer(writer, snap):
                raise error.connection_failed("peer failed TLS subject check")

            async def _handshake():
                # EVERYTHING between accept and a validated hello counts
                # against the handshake bound — including an injected
                # chaos stall, so a stall longer than the knob surfaces
                # as connection_failed, never as an unbounded hang
                if self.chaos is not None:
                    await self.chaos.on_connect(self.src, self.addr)
                # protocol-version handshake BEFORE the reply pump owns
                # the reader: hello out, hello back, versions must match
                _write_frame(writer, {"kind": "hello", "id": 0,
                                      "token": "", "body": PROTOCOL_VERSION})
                await writer.drain()
                return await _read_frame(reader)

            try:
                reply = await asyncio.wait_for(
                    _handshake(),
                    timeout=float(FLOW_KNOBS.real_handshake_timeout_s))
            except asyncio.TimeoutError:
                raise error.connection_failed("handshake timeout")
            except asyncio.IncompleteReadError:
                # no timeout happened: the peer CLOSED mid-handshake — the
                # classic symptom of a plaintext/TLS listener mismatch
                raise error.connection_failed(
                    "connection closed during handshake (TLS mismatch?)")
            if reply.get("kind") == "err":
                raise error.connection_failed(
                    f"peer refused connection: {reply.get('body')}")
            if reply.get("kind") != "hello" or reply.get("body") != PROTOCOL_VERSION:
                raise error.connection_failed(
                    f"protocol version mismatch: ours {PROTOCOL_VERSION}, "
                    f"theirs {reply.get('body')}")
        except BaseException:
            writer.close()
            raise
        self.reader, self.writer = reader, writer
        self._pump = asyncio.create_task(self._pump_replies())
        self.fail_streak = 0
        self.retry_at = 0.0

    async def _pump_replies(self) -> None:
        try:
            while True:
                msg = await _read_frame(self.reader)
                fut = self.pending.pop(msg.get("id"), None)
                if fut is None or fut.done():
                    continue
                if msg["kind"] == "err":
                    code, name = msg["body"]
                    fut.set_exception(error.FDBError(code, name))
                else:
                    fut.set_result(msg["body"])
        except asyncio.CancelledError:
            raise
        except Exception:
            # ANY pump death (decode error, oversized frame, socket loss)
            # must fail the in-flight table and drop the connection, or the
            # peer wedges: requests keep writing to a socket nobody reads
            self._fail_all()

    def _fail_all(self) -> None:
        """Tear down the connection: fail waiters, close the socket, stop
        the pump (unless we ARE the pump, which is exiting anyway)."""
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(error.connection_failed("peer connection lost"))
        self.pending.clear()
        if self.writer is not None:
            self.writer.close()
        self.reader = self.writer = None
        pump = self._pump
        if pump is not None and pump is not asyncio.current_task():
            pump.cancel()
        self._pump = None

    def close(self) -> None:
        self._fail_all()


class RealProcess:
    """The listener half: a handler registry bound to a TCP port
    (workerServer's mailbox). `address` is "host:port"."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._tls = None   # TLS snapshot, captured at start()
        #: strong refs — the loop keeps only weak ones, and a collected
        #: handler task means a silently dropped reply
        self._tasks: set = set()
        #: how handler coroutines are driven: None = plain asyncio await
        #: (handlers are asyncio coroutines); the real-cluster runtime
        #: installs a dispatcher that runs them on the node's cooperative
        #: scheduler instead (handlers there await scheduler Futures,
        #: which asyncio cannot drive)
        self.dispatcher: Optional[Callable] = None
        #: requests shed because their propagated deadline (frame ttl)
        #: expired before the handler finished — work nobody was waiting
        #: for anymore (docs/real_cluster.md, deadline propagation)
        self.shed_expired = 0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, token: str, handler: Callable) -> None:
        self.handlers[token] = handler

    def unregister(self, token: str) -> None:
        self.handlers.pop(token, None)

    async def start(self) -> None:
        from . import tls

        # snapshot at listen time; _serve checks peers against the SAME
        # policy the listener's handshake context came from
        self._tls = tls.current()
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port,
            ssl=self._tls.server_ctx if self._tls else None)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # drop live connections too: wait_closed() blocks on them
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
            self._server = None

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        from . import tls

        self._conns.add(writer)
        _nodelay(writer)
        shaken = False
        try:
            if self._tls is not None and not tls.verify_peer(writer,
                                                             self._tls):
                # consume the client's in-flight hello first — closing
                # with unread bytes degenerates to an RST that destroys
                # the diagnostic frame below
                try:
                    await asyncio.wait_for(
                        _read_frame(reader),
                        float(FLOW_KNOBS.real_handshake_timeout_s))
                except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                        ConnectionError, OSError):
                    pass
                # tell the peer WHY before dropping — a silent close
                # reads as a spurious transport failure and sends the
                # operator chasing the network instead of the certs
                _write_frame(writer, {
                    "kind": "err", "id": 0,
                    "body": (error.connection_failed("").code,
                             "tls_subject_rejected")})
                await writer.drain()
                return
            while True:
                msg = await _read_frame(reader)
                if msg["kind"] == "hello":
                    if msg.get("body") != PROTOCOL_VERSION:
                        _write_frame(writer, {"kind": "err", "id": 0,
                                              "body": (error.connection_failed("").code,
                                                       "protocol_mismatch")})
                        await writer.drain()
                        return
                    _write_frame(writer, {"kind": "hello", "id": 0,
                                          "token": "", "body": PROTOCOL_VERSION})
                    await writer.drain()
                    shaken = True
                    continue
                if not shaken:
                    # no frame is serviced before the version handshake: a
                    # peer speaking a pre-handshake protocol must fail HERE,
                    # not be decoded under skew
                    _write_frame(writer, {"kind": "err", "id": msg.get("id", 0),
                                          "body": (error.connection_failed("").code,
                                                   "handshake_required")})
                    await writer.drain()
                    return
                if msg["kind"] == "oneway":
                    handler = self.handlers.get(msg["token"])
                    if handler is not None:
                        self._track(asyncio.create_task(
                            self._run_oneway(handler, msg["body"],
                                             msg.get("tc"))))
                    continue
                self._track(asyncio.create_task(self._answer(writer, msg)))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    def _track(self, task: asyncio.Task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_oneway(self, handler, body, tc=None) -> None:
        # inbound trace context installed task-locally (this coroutine IS
        # its own asyncio task, so the set never leaks to other requests)
        tok = push_trace_context(tc) if tc is not None else None
        try:
            if self.dispatcher is not None:
                await self.dispatcher(handler, body)
            else:
                await handler(body)
        except Exception:
            pass
        finally:
            if tok is not None:
                pop_trace_context(tok)

    async def _answer(self, writer: asyncio.StreamWriter, msg) -> None:
        if buggify.buggify():
            # slow service: client timeouts race (knob-derived, was 0.05)
            await asyncio.sleep(float(FLOW_KNOBS.max_buggified_delay) / 4)
        # inbound trace context: installed for the whole handler await —
        # task-local (each _answer is its own asyncio task), and handed
        # across the cooperative-scheduler boundary by the dispatcher
        # (real/runtime.make_dispatcher wraps the handler coroutine)
        tc = msg.get("tc")
        tok = push_trace_context(tc) if tc is not None else None
        try:
            await self._answer_inner(writer, msg)
        finally:
            if tok is not None:
                pop_trace_context(tok)

    async def _answer_inner(self, writer: asyncio.StreamWriter, msg) -> None:
        handler = self.handlers.get(msg["token"])
        #: propagated client deadline (seconds of budget left at send time):
        #: handler work is bounded by it — a reply the client stopped
        #: waiting for is shed as request_maybe_delivered instead of
        #: occupying the service path (deadline propagation,
        #: docs/real_cluster.md)
        ttl = msg.get("ttl")
        try:
            if handler is None:
                raise error.FDBError(error.request_maybe_delivered("").code,
                                     "request_maybe_delivered")
            if self.dispatcher is not None:
                work = self.dispatcher(handler, msg["body"])
            else:
                work = handler(msg["body"])
            if ttl is not None:
                try:
                    body = await asyncio.wait_for(work, float(ttl))
                except asyncio.TimeoutError:
                    # cancel the HANDLER too (scheduler-dispatched work
                    # carries its Task as sim_task): shedding must stop
                    # the work, not just abandon its reply. Work a
                    # handler already handed to a role-internal batcher
                    # still completes — the cancel bounds everything
                    # upstream of that handoff.
                    task = getattr(work, "sim_task", None)
                    if task is not None:
                        task.cancel()
                    self.shed_expired += 1
                    raise error.FDBError(
                        error.request_maybe_delivered("").code,
                        "request_maybe_delivered")
            else:
                body = await work
            reply = {"kind": "reply", "id": msg["id"], "body": body}
        except error.FDBError as e:
            reply = {"kind": "err", "id": msg["id"], "body": (e.code, e.name)}
        except Exception:
            reply = {"kind": "err", "id": msg["id"],
                     "body": (error.internal_error("").code, "internal_error")}
        try:
            _write_frame(writer, reply)
            await writer.drain()
        except (ConnectionError, OSError):
            pass


class RealNetwork:
    """The sender half: the sim network's request/one_way surface over
    real sockets. One instance per OS process; peers cached per address.

    `name` is this process's identity for fault targeting (real/chaos.py
    partitions between NAMED processes); `chaos` is an optional
    NetworkNemesis handed down to peers for connect-time injection."""

    def __init__(self, name: str = "", chaos=None):
        self.name = name
        self.chaos = chaos
        self._peers: Dict[str, _Peer] = {}
        self._next_id = 0
        #: degradation counters (docs/real_cluster.md): reconnect attempts
        #: gated by backoff fail fast here instead of SYN-flooding the peer
        self.backoff_failfasts = 0
        self.reconnects = 0

    def transport_degraded(self) -> bool:
        """True while any peer is inside a reconnect-backoff window — the
        transport-level analog of ResilientEngine.degraded, consumed by
        depth-collapse (pipeline/resolver_pipeline.py) and admission."""
        return any(p.fail_streak > 0 for p in self._peers.values())

    async def _peer(self, addr: str, deadline: Optional[float] = None) -> _Peer:
        p = self._peers.get(addr)
        if p is None:
            p = self._peers[addr] = _Peer(addr, src=self.name,
                                          chaos=self.chaos)

        async def ensure_connected() -> None:
            async with p.lock:
                if p.writer is not None:
                    return
                loop_now = asyncio.get_running_loop().time()
                if loop_now < p.retry_at:
                    # inside the backoff window: fail fast — the caller's
                    # retry policy owns pacing, not a per-request SYN storm
                    self.backoff_failfasts += 1
                    raise error.connection_failed(
                        f"reconnect backoff ({p.retry_at - loop_now:.3f}s left)")
                try:
                    if p.fail_streak:
                        self.reconnects += 1
                    await p.connect()
                except error.FDBError:
                    p.note_connect_failure()
                    raise
                except (ConnectionError, OSError) as e:
                    p.note_connect_failure()
                    raise error.connection_failed(str(e))

        if p.writer is not None or deadline is None:
            # hot path: an already-connected peer skips the wait_for
            # task/timer allocation entirely (every request carries a
            # deadline, so this is the per-RPC steady state)
            await ensure_connected()
            return p
        # the request budget is end to end: the connect phase — including
        # TCP to a SYN-blackholed host and waiting out another request's
        # in-flight connect on the peer lock — must not outlive it
        remaining = deadline - asyncio.get_running_loop().time()
        if remaining <= 0:
            raise error.connection_failed("deadline exceeded before connect")
        try:
            await asyncio.wait_for(ensure_connected(), remaining)
        except asyncio.TimeoutError:
            raise error.connection_failed("connect deadline exceeded")
        return p

    async def request(self, src: str, ep: Endpoint, payload: Any,
                      priority: int = 0,
                      timeout: Optional[float] = None) -> Any:
        if timeout is None:
            timeout = float(FLOW_KNOBS.real_rpc_timeout_s)
        # distributed tracing: capture the ambient context NOW, in the
        # caller's synchronous prefix — on a cooperative-scheduler node
        # the shared ambient var is only guaranteed before the first
        # suspension (core/trace.py's discipline), and the connect below
        # suspends. The captured value is re-attached on every send, so a
        # retry after a reset/backoff/failover re-joins the same trace.
        tc = current_trace_context() if g_spans.enabled else None
        # deadline propagation: the budget is END TO END — connect (incl.
        # handshake) and the reply wait share it, and the remaining budget
        # rides the frame as `ttl` so the server can shed work whose
        # client already gave up (a healed partition flushes a backlog of
        # frames nobody is waiting on; resolving them only adds queue)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        p = await self._peer(ep.address, deadline)
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = loop.create_future()
        p.pending[rid] = fut
        try:
            ttl = max(0.001, deadline - loop.time())
            frame = {"kind": "req", "id": rid, "token": ep.token,
                     "body": payload, "ttl": round(ttl, 4)}
            if tc is not None:
                frame["tc"] = tc
            _write_frame(p.writer, frame)
            if buggify.buggify():
                # duplicate delivery (the transport's redelivery semantics):
                # the server answers twice; handlers must be idempotent and
                # the pump drops the orphan reply
                _write_frame(p.writer, frame)
            await p.writer.drain()
        except (ConnectionError, OSError) as e:
            p.pending.pop(rid, None)
            p._fail_all()
            raise error.connection_failed(str(e))
        try:
            return await asyncio.wait_for(
                fut, max(0.001, deadline - loop.time()))
        except asyncio.TimeoutError:
            p.pending.pop(rid, None)
            raise error.request_maybe_delivered("request timed out")

    async def one_way(self, src: str, ep: Endpoint, payload: Any,
                      priority: int = 0) -> None:
        if buggify.buggify():
            return   # unreliable by contract: drop outright
        # context captured before the first suspension (see request())
        tc = current_trace_context() if g_spans.enabled else None
        try:
            p = await self._peer(ep.address)
            frame = {"kind": "oneway", "id": 0,
                     "token": ep.token, "body": payload}
            if tc is not None:
                frame["tc"] = tc
            _write_frame(p.writer, frame)
            await p.writer.drain()
        except (error.FDBError, ConnectionError, OSError):
            pass   # unreliable by contract

    def close(self) -> None:
        for p in self._peers.values():
            p.close()
        self._peers.clear()
