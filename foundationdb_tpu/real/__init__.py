from .transport import RealNetwork, RealProcess

__all__ = ["RealNetwork", "RealProcess"]
