"""The scenario atlas: machine-asserted production workload recipes.

"Millions of users" was one knob — a Zipf skew s ∈ {0, 0.9, 1.2} — while
the observability stack (traces, heat, watchdog incidents, the black-box
journal, the trend gate) only ever watched that one shape. This module is
ROADMAP item 6's library of NAMED production scenarios: each a
declarative `ScenarioSpec` (tenant mix, txn shape, drift, nemesis
profile, SLO budget rows) instantiated through the SAME `run_campaign`
machinery every chaos campaign uses, so nothing about a scenario run is
bespoke — the p99-outside-windows math, the journal replay parity, the
watchdog incident correlation and the black-box journal all apply
unchanged (docs/scenarios.md).

The six recipes cover the ordered-store access shapes the SmartNIC
ordered-KV paper catalogs, stressing the concurrency structures Proust's
design-space analysis frames (PAPERS.md):

  * **flash_sale** — a heat spike on a tiny pool: reshard + admission
    interplay under concentrated contention;
  * **payment_ledger** — read-modify-write chains over balance rows:
    the conflict-heavy shape the conflict scheduler earns its keep on;
  * **secondary_index** — every base-row update fans out to index
    entries under disjoint prefixes: multi-range transactions;
  * **task_queue** — append at the tail, claim at the head: the future
    commutative-lane showcase (appends commute, claims contend);
  * **timeseries_ingest** — monotone tail keys: the adversarial case
    for key-range splits (the tail outruns any split chosen from past
    heat);
  * **session_cache** — read-mostly with cadenced TTL RANGE deletes.

Every run produces a **scorecard**: per-scenario SLO verdicts (p99
outside injected windows, abort fraction, throttle share, reshard
blackout budgets, parity, incidents-all-explained) plus a heat/abort
**signature** (concentration, top-range shares, witness mix) stamped
into the report, the `scenario.<name>.*` telemetry gauges
(`fdbtpu_scenario` family) and the black-box journal's `scenario`
event. `cli atlas` renders scorecards live or cluster-less;
`run_scenario_atlas` is bench.py's `scenario_atlas` section, whose
per-scenario headline metrics tools/bench_history.py gates.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..core import telemetry
from ..core.knobs import SERVER_KNOBS
from .workload import TenantSpec
from .nemesis import CampaignReport, NemesisConfig, run_campaign, assert_slos

#: budget multiplier for the atlas serving point, the
#: ELASTIC_BUDGET_FACTOR precedent one notch further: every scenario
#: serves through the elastic resolver group (host-side routing, dedup
#: cache, group-heat accounting) WITH spans, watchdog and the black-box
#: journal all on, and the shaped streams (range deletes, fan-out
#: multi-range txns) pack heavier conflict sets than the classic point
#: stream — on a shared CI box that stacks tens of ms of co-resident
#: scheduler noise onto the 60 ms knob product. The atlas measures
#: SHAPE DISCRIMINATION (does each recipe hold its own contract), not
#: the capacity knee `run_served_under_chaos` prices.
ATLAS_BUDGET_FACTOR = 4.0


@dataclass(frozen=True)
class ScenarioSpec:
    """One named production recipe: how to build its fleet, which
    nemesis profile it runs under, and the SLO budget rows its scorecard
    is judged by."""

    name: str
    title: str
    blurb: str
    #: (scale, duration_s) -> tenant mix. `scale` follows the
    #: NemesisConfig.default_tenants convention (1.0 oracle, 0.4 for
    #: CPU-emulated device modes).
    make_tenants: Callable[[float, float], List[TenantSpec]]
    #: NemesisConfig field overrides applied on top of the atlas
    #: defaults (elastic group, one partition, watchdog + spans on)
    profile: Dict = field(default_factory=dict)
    #: scorecard budget rows
    max_abort_frac: float = 0.30
    max_throttle_frac: float = 0.45
    min_commits: int = 40

    def tenants(self, scale: float, duration_s: float) -> List[TenantSpec]:
        return self.make_tenants(scale, duration_s)


def _flash_sale(scale: float, duration_s: float) -> List[TenantSpec]:
    return [
        # the sale: a severe Zipf head on a tiny pool — the heat spike
        # the reshard controller and admission must absorb together
        TenantSpec("sale", target_tps=55 * scale, s=1.5, n_keys=128),
        TenantSpec("browse", target_tps=30 * scale, s=0.6, n_keys=1024,
                   reads_per_txn=3, writes_per_txn=1),
    ]


def _payment_ledger(scale: float, duration_s: float) -> List[TenantSpec]:
    return [
        # balance rows: every write read first at the same snapshot
        TenantSpec("ledger", target_tps=50 * scale, s=1.1, n_keys=96,
                   writes_per_txn=2, shape="rmw"),
        # read-only audit scans over the same rows
        TenantSpec("audit", target_tps=20 * scale, s=0.3, n_keys=512,
                   reads_per_txn=3, writes_per_txn=0),
    ]


def _secondary_index(scale: float, duration_s: float) -> List[TenantSpec]:
    return [
        # one base-row update -> three index entries, disjoint prefixes
        TenantSpec("index", target_tps=45 * scale, s=0.9, n_keys=256,
                   writes_per_txn=3, shape="fanout"),
        TenantSpec("lookup", target_tps=30 * scale, s=0.9, n_keys=256,
                   reads_per_txn=2, writes_per_txn=0),
    ]


def _task_queue(scale: float, duration_s: float) -> List[TenantSpec]:
    return [
        # producers append at the tail, consumers claim at the head
        TenantSpec("workers", target_tps=55 * scale, s=0.0, n_keys=256,
                   shape="queue"),
        TenantSpec("bg", target_tps=20 * scale, s=0.0, n_keys=512),
    ]


def _timeseries_ingest(scale: float, duration_s: float) -> List[TenantSpec]:
    return [
        # monotone tail appends: the hottest range is always the newest
        TenantSpec("ingest", target_tps=55 * scale, s=0.8, n_keys=512,
                   shape="monotone"),
        TenantSpec("dash", target_tps=20 * scale, s=0.9, n_keys=512,
                   reads_per_txn=3, writes_per_txn=0),
    ]


def _session_cache(scale: float, duration_s: float) -> List[TenantSpec]:
    return [
        # read-mostly point gets; one commit in ttl_sweep_every is a
        # (begin, end) RANGE delete clearing a cold segment
        TenantSpec("sessions", target_tps=60 * scale, s=0.9, n_keys=512,
                   reads_per_txn=3, shape="ttl_cache",
                   ttl_sweep_every=24, ttl_sweep_span=64),
        TenantSpec("writer", target_tps=15 * scale, s=0.9, n_keys=512,
                   reads_per_txn=1, writes_per_txn=1),
    ]


#: the atlas, in scorecard order. Every scenario runs through the
#: elastic resolver group (host-fed heat -> a real signature) with one
#: injected partition, watchdog + spans + the standard parity replay.
SCENARIOS: Dict[str, ScenarioSpec] = {
    s.name: s for s in (
        ScenarioSpec(
            "flash_sale", "flash-sale hotspot",
            "heat spike on a tiny pool: reshard + admission interplay",
            _flash_sale,
            profile={"reshard": True},
            max_abort_frac=0.35, max_throttle_frac=0.50),
        ScenarioSpec(
            "payment_ledger", "payment ledger",
            "read-modify-write chains over balance rows, conflict-heavy",
            _payment_ledger,
            profile={"sched": True},
            max_abort_frac=0.40, max_throttle_frac=0.45),
        ScenarioSpec(
            "secondary_index", "secondary-index maintenance",
            "write fan-out: one base update, multi-range index txns",
            _secondary_index,
            max_abort_frac=0.30, max_throttle_frac=0.45),
        ScenarioSpec(
            "task_queue", "task queue",
            "append/claim streams — the commutative-lane showcase",
            _task_queue,
            profile={"sched": True},
            max_abort_frac=0.35, max_throttle_frac=0.45),
        ScenarioSpec(
            "timeseries_ingest", "time-series ingest",
            "monotone tail keys, adversarial for key-range splits",
            _timeseries_ingest,
            profile={"reshard": True},
            max_abort_frac=0.30, max_throttle_frac=0.45),
        ScenarioSpec(
            "session_cache", "session cache",
            "read-mostly with cadenced TTL range deletes",
            _session_cache,
            # TTL sweeps are exactly the range-deletion GC lane the
            # tiered history structure turns into O(batch) work
            # (docs/perf.md "Incremental history maintenance") — the
            # atlas pins that lane on device engine modes
            profile={"history_structure": "tiered"},
            max_abort_frac=0.20, max_throttle_frac=0.45),
    )
}


def scenario_config(name: str, seed: int, engine_mode: str = "oracle",
                    duration_s: float = 3.5, **kw) -> NemesisConfig:
    """The named recipe as a NemesisConfig: atlas defaults (elastic
    group, one short partition, watchdog + spans), the scenario's tenant
    mix and profile overrides, and the `scenario` stamp that makes
    run_campaign record the signature + black-box event. Explicit `kw`
    wins over the scenario profile (tests pin budgets and toggle layers
    the same way drift_config callers do)."""
    spec = SCENARIOS[name]
    scale = 1.0 if engine_mode == "oracle" else 0.4
    merged = {
        "partitions": 1, "partition_s": 0.4,
        "device_faults": False, "kill_child": False,
        "elastic": True, "watchdog": True,
    }
    merged.update(spec.profile)
    merged.update(kw)
    if merged.get("reshard"):
        merged.setdefault("reshard_spares", 1)
    merged.setdefault(
        "budget_ms",
        float(SERVER_KNOBS.resolver_p99_budget_ms)
        * float(SERVER_KNOBS.real_chaos_budget_factor)
        * ATLAS_BUDGET_FACTOR)
    return NemesisConfig(
        seed=seed, engine_mode=engine_mode, duration_s=duration_s,
        tenants=spec.tenants(scale, duration_s), scenario=name, **merged)


def build_signature(report: CampaignReport) -> dict:
    """The scenario's heat/abort signature, from fields the campaign
    already measured: load concentration and top-range shares (the
    group's host-fed heat snapshot), the verdict mix, witness count, and
    the abort/throttle fractions of the served stream. Engines without
    the heat layer yield an honest all-zero heat half — the scorecard
    rows that read it stay rendered, never KeyError."""
    heat = report.heat or {}
    counts = report.counts or {}
    offered = max(counts.get("offered", 0), 1)
    served = counts.get("committed", 0) + counts.get("conflicted", 0)
    hot = heat.get("hot_ranges") or []
    return {
        "concentration": round(float(heat.get("concentration", 0.0)), 4),
        "top_range": hot[0]["begin"] if hot else None,
        "top_share": round(float(hot[0]["share"]), 4) if hot else 0.0,
        "top_ranges": [{"begin": r.get("begin"),
                        "share": round(float(r.get("share", 0.0)), 4)}
                       for r in hot[:3]],
        "verdicts": dict(heat.get("verdicts") or {}),
        "witnesses": len(heat.get("recent_attribution") or []),
        "abort_frac": round(counts.get("conflicted", 0) / max(served, 1), 4),
        "throttle_frac": round(counts.get("throttled", 0) / offered, 4),
        # GC + history-maintenance half: rows reclaimed by the horizon /
        # TTL range-delete lane, and the tiered structure's append/merge
        # counters (all-zero on monolithic engines — honest, not absent)
        "gc_reclaimed": int(heat.get("gc_reclaimed", 0)),
        "history": {k: int(v) for k, v in
                    (heat.get("history") or {}).items()},
    }


def publish_scenario(name: str, report: CampaignReport) -> None:
    """The scorecard's measured half as `scenario.<name>.*` gauges
    (`fdbtpu_scenario` Prometheus family; fractions x1000 fixed-point,
    the heat-family convention). `score()` adds the verdict gauge."""
    td = telemetry.hub().tdmetrics
    sig = report.signature or {}
    p99 = report.p99_outside_ms
    td.int64(f"scenario.{name}.p99_us").set(
        int(p99 * 1000) if p99 == p99 else -1)
    td.int64(f"scenario.{name}.abort_frac_x1000").set(
        int(sig.get("abort_frac", 0.0) * 1000))
    td.int64(f"scenario.{name}.throttle_frac_x1000").set(
        int(sig.get("throttle_frac", 0.0) * 1000))
    td.int64(f"scenario.{name}.concentration_x1000").set(
        int(sig.get("concentration", 0.0) * 1000))
    td.int64(f"scenario.{name}.committed").set(
        int((report.counts or {}).get("committed", 0)))
    td.int64(f"scenario.{name}.gc_reclaimed").set(
        int(sig.get("gc_reclaimed", 0)))


def score(report: CampaignReport, cfg: NemesisConfig) -> dict:
    """One scorecard row: every SLO budget row of the scenario judged
    against the measured campaign, verdict-first so `cli atlas` renders
    a pass/fail column per contract row. `slo_pass` is the AND of every
    row — the integer the bench section records and the trend gate
    guards per scenario."""
    spec = SCENARIOS[cfg.scenario]
    sig = report.signature or build_signature(report)
    budget = cfg.resolved_budget_ms()
    p99 = report.p99_outside_ms
    p99_ok = bool(p99 == p99 and p99 <= budget)
    abort_ok = bool(sig["abort_frac"] <= spec.max_abort_frac)
    throttle_ok = bool(sig["throttle_frac"] <= spec.max_throttle_frac)
    commits_ok = bool(
        (report.counts or {}).get("committed", 0) >= spec.min_commits)
    parity_ok = bool(report.parity_checked > 0
                     and report.parity_mismatches == 0)
    unexplained = sum(1 for inc in report.incidents or []
                      if not inc.get("explained"))
    rs = report.reshard or {}
    bo_budget = float(SERVER_KNOBS.reshard_blackout_budget_ms)
    blackout_ok = all(
        op.get("blackout_ms", 0.0) <= bo_budget
        for op in rs.get("ops", []) if op.get("state") == "done")
    row = {
        "scenario": cfg.scenario,
        "title": spec.title,
        "seed": cfg.seed,
        "engine_mode": cfg.engine_mode,
        "p99_ms": round(p99, 3) if p99 == p99 else None,
        "budget_ms": round(budget, 1),
        "p99_ok": p99_ok,
        "abort_frac": sig["abort_frac"],
        "max_abort_frac": spec.max_abort_frac,
        "abort_ok": abort_ok,
        "throttle_frac": sig["throttle_frac"],
        "max_throttle_frac": spec.max_throttle_frac,
        "throttle_ok": throttle_ok,
        "committed": (report.counts or {}).get("committed", 0),
        "min_commits": spec.min_commits,
        "commits_ok": commits_ok,
        "sustained_tps": report.sustained_tps,
        "parity_checked": report.parity_checked,
        "parity_mismatches": report.parity_mismatches,
        "parity_ok": parity_ok,
        "incidents_unexplained": unexplained,
        "incidents_ok": unexplained == 0,
        "reshards_executed": rs.get("executed", 0),
        "blackout_ok": blackout_ok,
        "signature": sig,
        "slo_pass": int(p99_ok and abort_ok and throttle_ok and commits_ok
                        and parity_ok and unexplained == 0 and blackout_ok),
    }
    telemetry.hub().tdmetrics.int64(
        f"scenario.{cfg.scenario}.slo_pass").set(row["slo_pass"])
    return row


def assert_scenario_slos(report: CampaignReport, cfg: NemesisConfig,
                         min_outside: int = 50) -> dict:
    """The standard campaign SLO contract (assert_slos) PLUS the
    scenario's own budget rows; returns the scorecard row on success so
    callers assert and render from the same judgment."""
    assert_slos(report, cfg, min_outside=min_outside)
    row = score(report, cfg)
    failed = [k for k in ("p99_ok", "abort_ok", "throttle_ok",
                          "commits_ok", "parity_ok", "incidents_ok",
                          "blackout_ok") if not row[k]]
    assert not failed, (
        f"scenario {cfg.scenario} failed contract rows {failed}: {row}")
    return row


def run_scenario(name: str, seed: int = 4026, engine_mode: str = "oracle",
                 duration_s: float = 3.5, **kw):
    """One named scenario end-to-end: campaign + scorecard. Returns
    (CampaignReport, scorecard row); the row's `slo_pass` is the
    machine verdict (use assert_scenario_slos to raise instead)."""
    cfg = scenario_config(name, seed, engine_mode, duration_s, **kw)
    report = run_campaign(cfg)
    return report, score(report, cfg)


def run_scenario_atlas(seconds: float = 3.5, seed: int = 4026,
                       engine_mode: str = "oracle",
                       names: Optional[List[str]] = None,
                       **kw) -> dict:
    """The whole atlas, one campaign per scenario (bench.py
    `scenario_atlas`, recorded from BENCH_r11 on): per-scenario headline
    metrics under `scenarios.<name>.*` — the dotted paths
    tools/bench_history.py registers so an induced regression in ANY
    one scenario fails the trend gate — plus the full scorecard rows
    `cli atlas` renders from the artifact."""
    names = list(names or SCENARIOS)
    scorecard = []
    for i, name in enumerate(names):
        cfg = scenario_config(name, seed + i * 10, engine_mode, seconds,
                              **kw)
        report = run_campaign(cfg)
        scorecard.append(score(report, cfg))
    return {
        "seconds": seconds,
        "seed": seed,
        "engine_mode": engine_mode,
        "scenarios": {
            row["scenario"]: {
                "slo_pass": row["slo_pass"],
                "p99_ms": row["p99_ms"],
                "budget_ms": row["budget_ms"],
                "sustained_tps": row["sustained_tps"],
                "abort_frac": row["abort_frac"],
                "throttle_frac": row["throttle_frac"],
                "concentration": row["signature"]["concentration"],
                "committed": row["committed"],
                "parity_mismatches": row["parity_mismatches"],
                "incidents_unexplained": row["incidents_unexplained"],
                "reshards_executed": row["reshards_executed"],
            } for row in scorecard},
        "scorecard": scorecard,
        "all_green": int(all(r["slo_pass"] for r in scorecard)),
    }
