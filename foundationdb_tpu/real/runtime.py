"""Real-mode runtime: the sim seam implemented over wall clock + TCP + disk.

The whole server stack (worker, master, proxy, resolver, tlog, storage,
coordination) is written against three seams — the cooperative scheduler
(sim/loop.py), the token-addressed network (sim/network.py's surface), and
the async file API (sim/disk.py's surface). In simulation those are
virtual-time and in-process; here the SAME role code runs over:

  * RealScheduler — the identical (time, priority, seq) run loop, but
    `time` is the monotonic wall clock and the loop is driven by an
    asyncio task that sleeps until the next timer and wakes on IO;
  * RealNetClient — request/one_way returning scheduler Futures, bridged
    onto real/transport.py's asyncio TCP frames (with the protocol
    handshake and per-request timeouts);
  * RealDisk — the SimDisk file surface over actual files in a data dir
    (write-through; sync maps to flush+fsync).

This is the reference's architecture inverted: FDB virtualizes the real
world for simulation (INetwork/Sim2); we realize the simulated world for
production (fdbserver/fdbserver.actor.cpp:1607 fdbd() over
FlowTransport.actor.cpp:964). One seam, two worlds, one body of role code.
"""
from __future__ import annotations

import asyncio
import os
import time as _time
from typing import Any, Callable, Dict, List, Optional

from ..core import error, trace
from ..sim.actors import ActorCollection
from ..sim.loop import Future, Scheduler, Task, TaskPriority
from .transport import RealNetwork, RealProcess


class RealScheduler(Scheduler):
    """The cooperative run loop on the wall clock. Single-threaded: it runs
    inside one asyncio task, so scheduler state needs no locks — network
    callbacks fire on the same loop and just push queue entries + wake."""

    def __init__(self, seed: int = 0):
        super().__init__(seed=seed, start_time=_time.monotonic())
        self._wake: Optional[asyncio.Event] = None
        self._running = False

    def at(self, when: float, fn: Callable[[], None], priority: int = TaskPriority.DEFAULT_DELAY) -> None:
        # wall clock: a caller's `self.time + dt` can be marginally behind
        # monotonic now — clamp instead of asserting
        self._seq += 1
        import heapq

        heapq.heappush(self._queue, (max(when, self.time), -int(priority), self._seq, fn))
        if self._wake is not None:
            self._wake.set()

    async def run_async(self) -> None:
        """Drive the queue forever: execute everything due, then sleep
        until the next timer or an external wake (network callback)."""
        import heapq

        self._wake = asyncio.Event()
        self._running = True
        while self._running:
            self.time = max(self.time, _time.monotonic())
            drained = 0
            while self._queue and self._queue[0][0] <= self.time:
                _when, _negp, _seq, fn = heapq.heappop(self._queue)
                self.tasks_run += 1
                fn()
                drained += 1
                if drained >= 10_000:
                    # a zero-delay chain must not starve socket IO
                    await asyncio.sleep(0)
                    drained = 0
                self.time = max(self.time, _time.monotonic())
            self._wake.clear()
            if self._queue:
                dt = self._queue[0][0] - _time.monotonic()
                if dt > 0:
                    try:
                        await asyncio.wait_for(self._wake.wait(), timeout=dt)
                    except asyncio.TimeoutError:
                        pass
            else:
                await self._wake.wait()

    def shutdown(self) -> None:
        self._running = False
        if self._wake is not None:
            self._wake.set()


def sim_to_aio(fut: Future) -> "asyncio.Future":
    """Await a scheduler Future from asyncio (the transport's dispatcher)."""
    af = asyncio.get_running_loop().create_future()

    def done(f: Future) -> None:
        if af.cancelled():
            return
        if f.is_error:
            try:
                f.get()
            except BaseException as e:  # noqa: BLE001 — relay verbatim
                af.set_exception(e)
        else:
            af.set_result(f.get())

    fut.on_ready(done)
    return af


def aio_to_sim(coro, tasks: set) -> Future:
    """Bridge an asyncio coroutine to a scheduler Future (sim_to_aio's
    inverse). `tasks` must outlive the call and holds a strong ref until
    completion — asyncio keeps only weak ones, and a GC'd task would
    strand the Future unresolved forever. FDBErrors relay verbatim;
    anything else surfaces as transport loss."""
    out = Future()

    async def go() -> None:
        try:
            r = await coro
        except error.FDBError as e:
            if not out.is_ready:
                out._set_error(e)
        except Exception as e:  # noqa: BLE001 — surface as transport loss
            if not out.is_ready:
                out._set_error(error.connection_failed(str(e)))
        else:
            if not out.is_ready:
                out._set(r)

    t = asyncio.ensure_future(go())
    tasks.add(t)
    t.add_done_callback(tasks.discard)
    return out


class RealNetClient:
    """The sim network's request/one_way surface over real sockets,
    returning scheduler Futures so role code can await them. One instance
    per OS process."""

    class _Monitor:
        """Failure-monitor stub: real failure detection rides request
        timeouts and the wait-failure protocol; nothing is pre-declared."""

        def is_failed(self, _addr: str) -> bool:
            return False

        def on_failed(self, _addr: str, _cb) -> None:
            return None

    def __init__(self, sched: RealScheduler, name: str = ""):
        self.sched = sched
        self.raw = RealNetwork(name=name)
        self.monitor = RealNetClient._Monitor()
        #: strong refs — asyncio keeps only weak ones; a GC'd RPC task
        #: would leave its scheduler Future unresolved forever
        self._tasks: set = set()

    def _track(self, t) -> None:
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    def request(self, src: str, ep, payload: Any,
                priority: int = TaskPriority.DEFAULT_ENDPOINT,
                timeout: Optional[float] = None) -> Future:
        # None defers to the real_rpc_timeout_s knob (transport default);
        # explicit timeouts also ride the frame as a propagated deadline
        return aio_to_sim(
            self.raw.request(src, ep, payload, priority, timeout=timeout),
            self._tasks)

    def transport_degraded(self) -> bool:
        """Transport-level degradation signal (reconnect backoff active on
        any peer) — the depth-collapse input for wall-clock pipelines."""
        return self.raw.transport_degraded()

    def one_way(self, src: str, ep, payload: Any,
                priority: int = TaskPriority.DEFAULT_ENDPOINT) -> None:
        self._track(asyncio.ensure_future(self.raw.one_way(src, ep, payload, priority)))


class RealFile:
    """sim/disk.py's SimFile surface over one actual file. IO is performed
    inline (the files are small role metadata/logs; a thread-pool tier can
    slot in behind this surface without touching callers)."""

    def __init__(self, path: str):
        self.path = path
        if not os.path.exists(path):
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "wb"):
                pass
        self._f = open(path, "r+b")

    def size(self) -> int:
        self._f.seek(0, os.SEEK_END)
        return self._f.tell()

    async def read(self, offset: int, length: int) -> bytes:
        self._f.seek(offset)
        return self._f.read(length)

    async def write(self, offset: int, data: bytes) -> None:
        self._f.seek(0, os.SEEK_END)
        end = self._f.tell()
        if offset > end:
            self._f.write(b"\x00" * (offset - end))
        self._f.seek(offset)
        self._f.write(data)

    async def truncate(self, size: int) -> None:
        self._f.truncate(size)

    async def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


class RealDisk:
    """sim/disk.py's SimDisk surface over a data directory. File names map
    to path-safe escapes of the role store names."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._open: Dict[str, RealFile] = {}

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name.replace("/", "_").replace(":", "_"))

    def open(self, name: str, create: bool = True) -> RealFile:
        f = self._open.get(name)
        if f is not None:
            return f
        p = self._path(name)
        if not create and not os.path.exists(p):
            raise error.file_not_found(name)
        f = self._open[name] = RealFile(p)
        return f

    def exists(self, name: str) -> bool:
        return name in self._open or os.path.exists(self._path(name))

    def delete(self, name: str) -> None:
        f = self._open.pop(name, None)
        if f is not None:
            f.close()
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass

    def rename(self, src: str, dst: str) -> None:
        fs = self._open.pop(src, None)
        if fs is not None:
            fs.close()
        fd = self._open.pop(dst, None)
        if fd is not None:
            fd.close()
        os.replace(self._path(src), self._path(dst))

    def list(self, prefix: str = "") -> List[str]:
        esc = prefix.replace("/", "_").replace(":", "_")
        return sorted(n for n in os.listdir(self.root) if n.startswith(esc))


class NodeProcess(RealProcess):
    """The transport listener fleshed out to the SimProcess surface the
    role code expects (handlers registry it already has; actors, locality,
    per-process globals added here)."""

    def __init__(self, host: str, port: int, machine_id: str, dc_id: str):
        super().__init__(host, port)
        self.machine_id = machine_id
        self.dc_id = dc_id
        self.name = f"{host}:{port}"
        self.alive = True
        self.actors = ActorCollection()
        self.globals: Dict[str, Any] = {}
        self.reboots = 0

    def register(self, token: str, handler: Callable):
        super().register(token, handler)
        from ..sim.network import Endpoint

        return Endpoint(self.address, token)


class RealWorld:
    """The `sim` handle roles receive: .net, .sched, .disk_for() — the
    world seam with the real implementations plugged in."""

    def __init__(self, sched: RealScheduler, net: RealNetClient, datadir: str):
        self.sched = sched
        self.net = net
        self.datadir = datadir
        self._disks: Dict[str, RealDisk] = {}

    def disk_for(self, addr: str) -> RealDisk:
        d = self._disks.get(addr)
        if d is None:
            safe = addr.replace("/", "_").replace(":", "_")
            d = self._disks[addr] = RealDisk(os.path.join(self.datadir, safe))
        return d


async def _run_with_trace_context(ctx, handler, body):
    """Install the inbound trace context (possibly None) around a
    scheduler-dispatched handler. Scheduler tasks interleave inside ONE
    asyncio task (run_async drives every step in its own context), so the
    ambient context is only guaranteed during the handler's synchronous
    prefix — the set here runs in the same step as that prefix, and
    handlers capture the context at entry, before their first await
    (core/trace.py's discipline). The finally CLEARS the variable rather
    than token-resetting it: interleaved handlers pop out of LIFO order,
    and a token reset would re-install a completed sibling's context as
    the shared ambient value — a context-less handler dispatched after it
    would then record spans under a foreign trace id."""
    from ..core import trace

    trace.push_trace_context(ctx)
    try:
        return await handler(body)
    finally:
        trace.push_trace_context(None)


def make_dispatcher(sched: RealScheduler):
    """Transport dispatcher: run a role handler on the node's cooperative
    scheduler and hand asyncio an awaitable for the reply. The scheduler
    Task rides on the future as `sim_task` so deadline shedding
    (real/transport.RealProcess._answer) can cancel the HANDLER, not just
    the asyncio bridge — expired work stops running, it doesn't finish
    into a reply nobody awaits. With tracing active every handler is
    wrapped so its synchronous prefix sees exactly its own request's
    inbound context (or None) — never a sibling's leftovers; with spans
    off nothing wraps and nothing allocates."""

    def dispatch(handler, body):
        ctx = trace.current_trace_context()
        coro = (_run_with_trace_context(ctx, handler, body)
                if (ctx is not None or trace.spans_enabled())
                else handler(body))
        t = sched.spawn(coro, TaskPriority.DEFAULT_ENDPOINT,
                        name=f"rpc:{getattr(handler, '__name__', 'handler')}")
        af = sim_to_aio(t)
        af.sim_task = t
        return af

    return dispatch
