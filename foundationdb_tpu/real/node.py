"""One real cluster node: `python -m foundationdb_tpu.real.node ...`.

The fdbd() analog (fdbserver/fdbserver.actor.cpp:1607, worker.actor.cpp:997):
one OS process composing, over the real transport,

  * a coordination server (when this node is in the coordinator list) —
    durable generation + leader registers on the node's data dir;
  * a worker — registers with the elected cluster controller, stands for
    CC leadership itself, and constructs recruited roles (master, proxy,
    resolver, tlog, storage) on Initialize* RPCs;

all running the UNCHANGED role code on the wall-clock cooperative
scheduler (real/runtime.py). The conflict engine is the C++ native one
when the library is built, else the oracle.
"""
from __future__ import annotations

import argparse
import asyncio
import os
import sys


def make_engine_factory(kind: str):
    """Conflict-engine family for a wall-clock node. "auto" consults the
    engine-mode router (ops/host_engine.py): the `resolver_device_loop`
    knob promotes the single-chip device engine to the device-resident
    loop (docs/perf.md "Device-resident loop"); unset, it stays step
    dispatch ("jax")."""
    if kind in ("jax", "device_loop", "auto"):
        from ..ops.conflict_kernel import KernelConfig
        from ..ops.host_engine import default_engine_mode, make_engine

        mode = default_engine_mode() if kind == "auto" else kind
        return lambda: make_engine(mode, KernelConfig())
    if kind == "native":
        try:
            from ..ops.native_engine import NativeConflictEngine

            NativeConflictEngine()   # probe: raises if the lib is missing
            return NativeConflictEngine
        except Exception:
            pass
    from ..ops.oracle import OracleConflictEngine

    return OracleConflictEngine


async def amain(args) -> None:
    from ..server.cluster import DynamicClusterConfig
    from ..server.coordination import CoordinationServer
    from ..server.worker import Worker
    from ..sim.loop import TaskPriority, set_scheduler
    from .runtime import (
        NodeProcess,
        RealNetClient,
        RealScheduler,
        RealWorld,
        make_dispatcher,
    )

    sched = RealScheduler(seed=(os.getpid() << 16) ^ args.port)
    set_scheduler(sched)
    proc = NodeProcess(args.host, args.port, machine_id=f"m{args.port}", dc_id="dc0")
    proc.dispatcher = make_dispatcher(sched)
    await proc.start()
    net = RealNetClient(sched, name=proc.address)
    world = RealWorld(sched, net, args.datadir)

    coords = args.coordinators.split(",")
    cfg = DynamicClusterConfig(
        n_coordinators=len(coords),
        n_workers=args.workers,
        n_tlogs=args.tlogs,
        n_resolvers=args.resolvers,
        n_proxies=args.proxies,
        n_storage=args.storage,
        engine_factory=make_engine_factory(args.engine),
    )

    async def boot():
        if proc.address in coords:
            await CoordinationServer.create(proc, world.disk_for(proc.address))
        Worker(world, proc, coords, cfg.engine_factory,
               cc_priority=args.cc_priority, cluster_cfg=cfg)

    sched.spawn(boot(), TaskPriority.CLUSTER_CONTROLLER, name="fdbd-boot")
    print(f"node up on {proc.address}", flush=True)
    await sched.run_async()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="one real cluster node (fdbd)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--coordinators", required=True,
                    help="comma-separated host:port list (the cluster file)")
    ap.add_argument("--datadir", required=True)
    ap.add_argument("--cc-priority", type=int, default=None,
                    help="stand for cluster controllership at this priority")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--tlogs", type=int, default=2)
    ap.add_argument("--resolvers", type=int, default=2)
    ap.add_argument("--proxies", type=int, default=1)
    ap.add_argument("--storage", type=int, default=2)
    ap.add_argument("--engine", default="native",
                    choices=["native", "oracle", "jax", "device_loop", "auto"])
    ap.add_argument("--tls-cert", default=None)
    ap.add_argument("--tls-key", default=None)
    ap.add_argument("--tls-ca", default=None)
    ap.add_argument("--tls-verify", default="",
                    help='subject DSL, e.g. "Check.Valid=1,O=MyOrg"')
    args = ap.parse_args(argv)
    if args.tls_cert or args.tls_key or args.tls_ca or args.tls_verify:
        if not (args.tls_cert and args.tls_key and args.tls_ca):
            # --tls-verify alone must not silently run plaintext while
            # the operator believes subject checks are enforced
            ap.error("--tls-cert, --tls-key and --tls-ca must be "
                     "given together (required for any TLS option)")
        from .tls import TLSConfig, set_tls
        set_tls(TLSConfig(cert_path=args.tls_cert, key_path=args.tls_key,
                          ca_path=args.tls_ca,
                          verify_rules=args.tls_verify))
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
