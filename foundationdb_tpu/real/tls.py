"""TLS for the real transport: mutual auth + a subject-check DSL.

Re-design of FDBLibTLS (FDBLibTLS/*.cpp, ~2.6k LoC over libtls): every
connection is MUTUALLY authenticated against a shared CA, and an
optional verification DSL constrains the peer certificate's subject
(the reference's `Check.Valid=1,O=...` strings,
FDBLibTLS/FDBLibTLSVerify.cpp). Python's ssl module supplies the
handshake; this module supplies context construction, the DSL, and
self-signed test credentials (via `cryptography`).

Process-wide configuration (`set_tls`) mirrors the reference's plugin
model: fdbserver loads one TLS policy per process, not per connection.
"""
from __future__ import annotations

import os
import ssl
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class TLSConfig:
    cert_path: str           # this process's PEM cert chain
    key_path: str            # its private key
    ca_path: str             # the CA bundle peers must chain to
    verify_rules: str = ""   # e.g. "Check.Valid=1,O=TestCluster"


def _base_context(cfg: TLSConfig, server: bool) -> ssl.SSLContext:
    ctx = ssl.SSLContext(
        ssl.PROTOCOL_TLS_SERVER if server else ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    ctx.load_verify_locations(cfg.ca_path)
    # identity comes from the CA plus the subject DSL, not hostnames
    # (cluster members are addressed by ip:port) — FDB's model
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED   # MUTUAL: both sides present
    return ctx


class ActiveTLS:
    """An immutable snapshot of the process TLS policy: the config plus
    BOTH contexts built once (PEMs parsed at set_tls time, not per
    connection). Callers grab one snapshot and use it for a whole
    connection, so a concurrent set_tls() can't desync the context a
    socket was opened with from the rules its peer is checked against."""

    def __init__(self, cfg: TLSConfig):
        self.cfg = cfg
        self.client_ctx = _base_context(cfg, server=False)
        self.server_ctx = _base_context(cfg, server=True)


_active: Optional[ActiveTLS] = None


def set_tls(cfg: Optional[TLSConfig]) -> None:
    global _active
    _active = ActiveTLS(cfg) if cfg is not None else None


def current() -> Optional[ActiveTLS]:
    return _active


def client_context() -> Optional[ssl.SSLContext]:
    return _active.client_ctx if _active is not None else None


def server_context() -> Optional[ssl.SSLContext]:
    return _active.server_ctx if _active is not None else None


_SUBJECT_KEYS = {
    "O": "organizationName",
    "OU": "organizationalUnitName",
    "CN": "commonName",
    "C": "countryName",
}


def check_peer(peercert: Optional[dict], rules: str = "") -> bool:
    """Apply the verification DSL to a peer cert as returned by
    `SSLObject.getpeercert()`. Rules: comma-separated `Field=value`
    pairs; `Check.Valid=1` asserts a cert is present (chain validity is
    already enforced by the handshake), `O=`/`OU=`/`CN=`/`C=` match the
    subject. Empty rules accept any CA-validated peer."""
    if not rules:
        return True
    import re

    # multi-valued attributes (two OU= RDNs) collect into sets: a rule
    # matches if ANY value matches, like the reference's verifier
    subject: Dict[str, set] = {}
    for rdn in (peercert or {}).get("subject", ()):
        for key, value in rdn:
            subject.setdefault(key, set()).add(value)
    # backslash-escaped commas let a subject value contain one
    # ("O=Acme\, Inc."), matching FDBLibTLSVerify's escape syntax
    for clause in re.split(r"(?<!\\),", rules):
        clause = clause.replace("\\,", ",").strip()
        if not clause:
            continue
        field, _, want = clause.partition("=")
        field = field.strip()
        want = want.strip()
        if field == "Check.Valid":
            if want not in ("0", "1"):
                return False   # malformed security input: fail closed
            if want == "1" and not peercert:
                return False
        elif field in _SUBJECT_KEYS:
            if want not in subject.get(_SUBJECT_KEYS[field], ()):
                return False
        else:
            return False   # unknown clause: fail closed
    return True


def verify_peer(writer, snap: ActiveTLS) -> bool:
    """Apply `snap`'s subject DSL to the peer behind an established TLS
    stream — the ONE verification sequence both directions of the mutual
    check share, so the client- and server-side policies can't drift."""
    ssl_obj = writer.get_extra_info("ssl_object")
    return ssl_obj is not None and check_peer(ssl_obj.getpeercert(),
                                              snap.cfg.verify_rules)


def generate_test_credentials(out_dir: str,
                              org: str = "TestCluster") -> TLSConfig:
    """Self-signed CA + one leaf cert (subject O=`org`) shared by every
    process — enough for mutual-auth tests and dev clusters. PEM files
    land under `out_dir`."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime(2020, 1, 1)
    until = datetime.datetime(2120, 1, 1)

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    ca_key = _key()
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "fdb-tpu-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now).not_valid_after(until)
               .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    leaf_key = _key()
    leaf_name = x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, "fdb-tpu-node"),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
    ])
    leaf_cert = (x509.CertificateBuilder()
                 .subject_name(leaf_name).issuer_name(ca_name)
                 .public_key(leaf_key.public_key())
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(now).not_valid_after(until)
                 .sign(ca_key, hashes.SHA256()))

    paths = {}
    for fname, data in (
        ("ca.pem", ca_cert.public_bytes(serialization.Encoding.PEM)),
        ("cert.pem", leaf_cert.public_bytes(serialization.Encoding.PEM)),
        ("key.pem", leaf_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption())),
    ):
        paths[fname] = os.path.join(out_dir, fname)
        with open(paths[fname], "wb") as f:
            f.write(data)
    os.chmod(paths["key.pem"], 0o600)   # the one shared private key
    return TLSConfig(cert_path=paths["cert.pem"], key_path=paths["key.pem"],
                     ca_path=paths["ca.pem"],
                     verify_rules=f"Check.Valid=1,O={org}")
