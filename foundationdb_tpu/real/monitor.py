"""fdbmonitor: the process supervisor for real clusters.

Re-design of fdbmonitor/fdbmonitor.cpp (:267 Command struct, fd watching,
conf hot-reload): a plain (non-scheduler) daemon that reads an ini-style
conf, spawns one real.node process per [node.PORT] section, restarts dead
children with exponential backoff (reset after a stable-uptime window),
re-reads the conf on mtime change (added sections spawn, removed sections
stop, changed sections restart), and tears everything down on SIGTERM.

    python -m foundationdb_tpu.real.monitor --conf cluster.conf

conf format:

    [general]
    coordinators = 127.0.0.1:4500,127.0.0.1:4501,127.0.0.1:4502
    datadir = /var/lib/fdb_tpu
    workers = 4
    engine = native

    [node.4500]
    cc_priority = 0

    [node.4501]
    cc_priority = 1
"""
from __future__ import annotations

import argparse
import configparser
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

INITIAL_BACKOFF = 1.0
MAX_BACKOFF = 60.0
#: uptime after which a child's backoff resets (fdbmonitor's
#: restart_backoff reset window)
STABLE_SECONDS = 10.0


class Child:
    def __init__(self, section: str, argv: list):
        self.section = section
        self.argv = argv
        self.proc: Optional[subprocess.Popen] = None
        self.backoff = INITIAL_BACKOFF
        self.started_at = 0.0
        self.restart_at = 0.0   # 0 = running or start now
        #: consecutive sub-stable-uptime exits (the crash-loop counter,
        #: surfaced in every restart status line; resets once the child
        #: stays up past STABLE_SECONDS)
        self.crash_count = 0
        #: lifetime restarts (monitoring/tests; never reset)
        self.restarts = 0

    def spawn(self, log_dir: str) -> None:
        log = open(os.path.join(log_dir, f"{self.section}.log"), "ab")
        try:
            self.proc = subprocess.Popen(
                self.argv, stdout=log, stderr=subprocess.STDOUT)
        finally:
            log.close()   # the child holds its own dup; keeping ours open
            #               would leak one fd per restart of a crash-looper
        self.started_at = time.monotonic()
        self.restart_at = 0.0
        print(f"fdbmonitor: started {self.section} (pid {self.proc.pid})"
              + (f" [crash loop x{self.crash_count}]" if self.crash_count
                 else ""),
              flush=True)

    def note_stable(self, now: float) -> None:
        """Uptime past the stable window resets backoff AND the crash-loop
        counter — a recovered child is no longer crash-looping."""
        if now - self.started_at > STABLE_SECONDS:
            self.backoff = INITIAL_BACKOFF
            self.crash_count = 0

    def note_exit(self, now: float) -> int:
        """Record an exit: schedule the restart after the CURRENT backoff,
        then widen it for the next one. A fast-crashing child therefore
        never respawns hot — every consecutive exit at least doubles the
        wait, and the status line carries the crash-loop count."""
        rc = self.proc.returncode if self.proc is not None else None
        self.proc = None
        self.crash_count += 1
        self.restart_at = now + self.backoff
        print(f"fdbmonitor: {self.section} exited rc={rc}; "
              f"crash loop x{self.crash_count}; "
              f"restart in {self.backoff:.1f}s", flush=True)
        self.backoff = min(self.backoff * 2, MAX_BACKOFF)
        return rc if rc is not None else -1

    def due(self, now: float) -> bool:
        """True when a scheduled restart's backoff has elapsed."""
        return bool(self.restart_at) and now >= self.restart_at

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.proc = None


def poll_children(children, log_dir: str, now: Optional[float] = None) -> bool:
    """One supervision pass (extracted from main's loop so the restart
    policy is unit-testable and reusable by the wall-clock nemesis,
    real/nemesis.py): reap exits into backoff-scheduled restarts, respawn
    the due, reset backoff on stable uptime. Returns whether any child is
    alive or pending restart."""
    from ..core import telemetry

    if now is None:
        now = time.monotonic()
    any_alive = False
    for c in children.values() if isinstance(children, dict) else children:
        if c.proc is not None and c.proc.poll() is None:
            any_alive = True
            c.note_stable(now)
            continue
        if c.proc is not None:
            rc = c.note_exit(now)
            # supervised-process churn lands in the chaos timeline
            # (telemetry hub event ring -> campaign reports and the
            # Chrome trace's nemesis track), so a child death is
            # correlatable with the SLO windows around it
            telemetry.hub().chaos_event("child_exit", section=c.section,
                                        rc=rc, crash_count=c.crash_count)
        if c.due(now):
            c.restarts += 1
            c.spawn(log_dir)
            telemetry.hub().chaos_event("child_respawn", section=c.section,
                                        crash_count=c.crash_count,
                                        restarts=c.restarts)
            any_alive = True
        # NB: a child merely WAITING OUT its backoff does not count as
        # alive — preserving --once's original "every child has exited"
        # exit condition
    return any_alive


def parse_conf(path: str):
    cp = configparser.ConfigParser()
    cp.read(path)
    if "general" not in cp:
        raise ValueError(f"{path}: missing [general] section")
    g = cp["general"]
    coordinators = g.get("coordinators")
    datadir = g.get("datadir")
    workers = g.getint("workers")
    if not coordinators or not datadir or workers is None:
        raise ValueError(
            f"{path}: [general] must set coordinators, datadir, workers")
    engine = g.get("engine", "native")
    nodes: Dict[str, list] = {}
    for section in cp.sections():
        if not section.startswith("node."):
            continue
        port = section[len("node."):]
        s = cp[section]
        argv = [
            sys.executable, "-m", "foundationdb_tpu.real.node",
            "--port", port,
            "--coordinators", coordinators,
            "--datadir", os.path.join(datadir, port),
            "--workers", str(workers),
            "--engine", s.get("engine", engine),
        ]
        if s.get("cc_priority") is not None:
            argv += ["--cc-priority", s.get("cc_priority")]
        nodes[section] = argv
    return datadir, nodes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="process supervisor (fdbmonitor)")
    ap.add_argument("--conf", required=True)
    ap.add_argument("--once", action="store_true",
                    help="exit when every child has exited (testing)")
    args = ap.parse_args(argv)

    datadir, node_argvs = parse_conf(args.conf)
    os.makedirs(datadir, exist_ok=True)
    log_dir = os.path.join(datadir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    children: Dict[str, Child] = {}
    conf_mtime = os.path.getmtime(args.conf)
    stopping = {"flag": False}

    def on_term(_sig, _frm):
        stopping["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    for section, node_argv in node_argvs.items():
        c = Child(section, node_argv)
        c.spawn(log_dir)
        children[section] = c

    while not stopping["flag"]:
        time.sleep(0.5)
        now = time.monotonic()
        # conf hot-reload (fdbmonitor's kqueue/inotify, reduced to mtime)
        try:
            mt = os.path.getmtime(args.conf)
        except OSError:
            mt = conf_mtime
        if mt != conf_mtime:
            conf_mtime = mt
            try:
                _dd, new_argvs = parse_conf(args.conf)
            except (ValueError, configparser.Error) as e:
                # a half-written or malformed conf must never take the
                # supervisor down; keep running on the previous config
                print(f"fdbmonitor: conf reload failed ({e}); keeping old",
                      flush=True)
                continue
            for section in list(children):
                if section not in new_argvs:
                    print(f"fdbmonitor: section {section} removed; stopping",
                          flush=True)
                    children.pop(section).stop()
                elif children[section].argv != new_argvs[section]:
                    print(f"fdbmonitor: section {section} changed; restarting",
                          flush=True)
                    children[section].stop()
                    children[section].argv = new_argvs[section]
                    children[section].backoff = INITIAL_BACKOFF
                    children[section].crash_count = 0
                    children[section].spawn(log_dir)
            for section, node_argv in new_argvs.items():
                if section not in children:
                    c = Child(section, node_argv)
                    c.spawn(log_dir)
                    children[section] = c
        # child liveness + crash-loop-counted backoff restarts
        any_alive = poll_children(children, log_dir, now)
        if args.once and not any_alive:
            break

    for c in children.values():
        c.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
