"""fdbmonitor: the process supervisor for real clusters.

Re-design of fdbmonitor/fdbmonitor.cpp (:267 Command struct, fd watching,
conf hot-reload): a plain (non-scheduler) daemon that reads an ini-style
conf, spawns one real.node process per [node.PORT] section, restarts dead
children with exponential backoff (reset after a stable-uptime window),
re-reads the conf on mtime change (added sections spawn, removed sections
stop, changed sections restart), and tears everything down on SIGTERM.

    python -m foundationdb_tpu.real.monitor --conf cluster.conf

conf format:

    [general]
    coordinators = 127.0.0.1:4500,127.0.0.1:4501,127.0.0.1:4502
    datadir = /var/lib/fdb_tpu
    workers = 4
    engine = native

    [node.4500]
    cc_priority = 0

    [node.4501]
    cc_priority = 1
"""
from __future__ import annotations

import argparse
import configparser
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

INITIAL_BACKOFF = 1.0
MAX_BACKOFF = 60.0
#: uptime after which a child's backoff resets (fdbmonitor's
#: restart_backoff reset window)
STABLE_SECONDS = 10.0


class Child:
    def __init__(self, section: str, argv: list):
        self.section = section
        self.argv = argv
        self.proc: Optional[subprocess.Popen] = None
        self.backoff = INITIAL_BACKOFF
        self.started_at = 0.0
        self.restart_at = 0.0   # 0 = running or start now

    def spawn(self, log_dir: str) -> None:
        log = open(os.path.join(log_dir, f"{self.section}.log"), "ab")
        try:
            self.proc = subprocess.Popen(
                self.argv, stdout=log, stderr=subprocess.STDOUT)
        finally:
            log.close()   # the child holds its own dup; keeping ours open
            #               would leak one fd per restart of a crash-looper
        self.started_at = time.monotonic()
        self.restart_at = 0.0
        print(f"fdbmonitor: started {self.section} (pid {self.proc.pid})",
              flush=True)

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.proc = None


def parse_conf(path: str):
    cp = configparser.ConfigParser()
    cp.read(path)
    if "general" not in cp:
        raise ValueError(f"{path}: missing [general] section")
    g = cp["general"]
    coordinators = g.get("coordinators")
    datadir = g.get("datadir")
    workers = g.getint("workers")
    if not coordinators or not datadir or workers is None:
        raise ValueError(
            f"{path}: [general] must set coordinators, datadir, workers")
    engine = g.get("engine", "native")
    nodes: Dict[str, list] = {}
    for section in cp.sections():
        if not section.startswith("node."):
            continue
        port = section[len("node."):]
        s = cp[section]
        argv = [
            sys.executable, "-m", "foundationdb_tpu.real.node",
            "--port", port,
            "--coordinators", coordinators,
            "--datadir", os.path.join(datadir, port),
            "--workers", str(workers),
            "--engine", s.get("engine", engine),
        ]
        if s.get("cc_priority") is not None:
            argv += ["--cc-priority", s.get("cc_priority")]
        nodes[section] = argv
    return datadir, nodes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="process supervisor (fdbmonitor)")
    ap.add_argument("--conf", required=True)
    ap.add_argument("--once", action="store_true",
                    help="exit when every child has exited (testing)")
    args = ap.parse_args(argv)

    datadir, node_argvs = parse_conf(args.conf)
    os.makedirs(datadir, exist_ok=True)
    log_dir = os.path.join(datadir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    children: Dict[str, Child] = {}
    conf_mtime = os.path.getmtime(args.conf)
    stopping = {"flag": False}

    def on_term(_sig, _frm):
        stopping["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    for section, node_argv in node_argvs.items():
        c = Child(section, node_argv)
        c.spawn(log_dir)
        children[section] = c

    while not stopping["flag"]:
        time.sleep(0.5)
        now = time.monotonic()
        # conf hot-reload (fdbmonitor's kqueue/inotify, reduced to mtime)
        try:
            mt = os.path.getmtime(args.conf)
        except OSError:
            mt = conf_mtime
        if mt != conf_mtime:
            conf_mtime = mt
            try:
                _dd, new_argvs = parse_conf(args.conf)
            except (ValueError, configparser.Error) as e:
                # a half-written or malformed conf must never take the
                # supervisor down; keep running on the previous config
                print(f"fdbmonitor: conf reload failed ({e}); keeping old",
                      flush=True)
                continue
            for section in list(children):
                if section not in new_argvs:
                    print(f"fdbmonitor: section {section} removed; stopping",
                          flush=True)
                    children.pop(section).stop()
                elif children[section].argv != new_argvs[section]:
                    print(f"fdbmonitor: section {section} changed; restarting",
                          flush=True)
                    children[section].stop()
                    children[section].argv = new_argvs[section]
                    children[section].backoff = INITIAL_BACKOFF
                    children[section].spawn(log_dir)
            for section, node_argv in new_argvs.items():
                if section not in children:
                    c = Child(section, node_argv)
                    c.spawn(log_dir)
                    children[section] = c
        # child liveness + backoff restarts
        any_alive = False
        for c in children.values():
            if c.proc is not None and c.proc.poll() is None:
                any_alive = True
                if now - c.started_at > STABLE_SECONDS:
                    c.backoff = INITIAL_BACKOFF
                continue
            if c.proc is not None:
                rc = c.proc.returncode
                c.proc = None
                c.restart_at = now + c.backoff
                print(f"fdbmonitor: {c.section} exited rc={rc}; "
                      f"restart in {c.backoff:.1f}s", flush=True)
                c.backoff = min(c.backoff * 2, MAX_BACKOFF)
            if c.restart_at and now >= c.restart_at:
                c.spawn(log_dir)
                any_alive = True
        if args.once and not any_alive:
            break

    for c in children.values():
        c.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
