"""Wall-clock network nemesis: seeded fault injection for the real transport.

The sim cluster's nemesis coverage stops at the process boundary — the
wall-clock layer (real/transport.py) that actually fronts traffic had no
fault injection at all (ROADMAP item 4). This module is the missing
counterpart of sim2's clogging/partition machinery for REAL sockets:

  * `NetworkNemesis` — one seeded decision engine per campaign, shared by
    every endpoint in the process. It draws background faults (added
    latency, frame drops, connection resets, handshake stalls) from knob
    defaults (`chaos_net_*`, core/knobs.py) and holds the asymmetric
    partition schedule between NAMED processes ("client-a" -> "resolver"
    blocked while the reverse direction flows — the classic one-way
    blackhole the sim's symmetric clogs never model).
  * `ChaosTransport` — the shim over a `RealNetwork`: same request /
    one_way surface, faults applied around the inner call. Requests inside
    a partition window fail as `connection_failed`; drops surface as
    `request_maybe_delivered` (the transport's redelivery semantics);
    resets tear the peer connection down mid-flight so reconnect backoff
    (real/transport.py) is exercised for real.

Every injected fault is recorded in the telemetry hub (`chaos.<kind>`
counters + the bounded event ring) — `tools/cli.py chaos-status` renders
them — and every partition/stall window is logged with wall timestamps so
the SLO assertion (real/nemesis.py) can exclude exactly the injected
windows and hold p99 to budget everywhere else (docs/real_cluster.md).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core import error, telemetry
from ..core.knobs import SERVER_KNOBS
from ..core.rng import DeterministicRandom
from .transport import RealNetwork


@dataclass
class ChaosConfig:
    """Background fault mix. Defaults come from the `chaos_net_*` knobs so
    campaigns are steered by knob overrides, not code edits."""

    latency_prob: float = field(
        default_factory=lambda: float(SERVER_KNOBS.chaos_net_latency_prob))
    latency_ms: float = field(
        default_factory=lambda: float(SERVER_KNOBS.chaos_net_latency_ms))
    drop_prob: float = field(
        default_factory=lambda: float(SERVER_KNOBS.chaos_net_drop_prob))
    reset_prob: float = field(
        default_factory=lambda: float(SERVER_KNOBS.chaos_net_reset_prob))
    handshake_stall_prob: float = field(
        default_factory=lambda: float(SERVER_KNOBS.chaos_handshake_stall_prob))
    #: how long a dropped request burns before the typed error surfaces
    #: (a real drop costs the client its timeout; campaigns keep this low
    #: so wall clock goes to load, not waiting)
    drop_detect_s: float = 0.05
    #: injected handshake stall length. The stall runs INSIDE the
    #: handshake-bounded region of _Peer.connect, so a stall below the
    #: real_handshake_timeout_s knob is a slow connect (window recorded)
    #: and one above it surfaces as connection_failed within the knob
    #: bound — never an unbounded hang either way
    stall_s: float = 0.25


class NetworkNemesis:
    """Seeded fault schedule shared by every ChaosTransport of a campaign.

    All decisions draw from one DeterministicRandom stream, so a campaign
    seed reproduces the same fault sequence against the same traffic
    interleaving (wall-clock runs are not bit-reproducible like the sim,
    but the INJECTION schedule is)."""

    def __init__(self, seed: int, cfg: Optional[ChaosConfig] = None):
        self.seed = seed
        self.rng = DeterministicRandom(seed)
        self.cfg = cfg or ChaosConfig()
        #: (src, dst) -> wall time the one-way partition heals
        self._partitions: Dict[Tuple[str, str], float] = {}
        #: every injected window, for SLO exclusion: {kind, src, dst, t0, t1}
        self.windows: List[dict] = []
        self.enabled = True

    # -- partitions ----------------------------------------------------------
    def partition(self, src: str, dst: str, duration_s: float,
                  symmetric: bool = False) -> None:
        """Block src->dst requests for `duration_s` (both directions when
        `symmetric`). Named-process asymmetric partitions are the point:
        a client that cannot reach the resolver while the resolver's
        replies to OTHERS still flow."""
        t0 = time.monotonic()
        pairs = [(src, dst)] + ([(dst, src)] if symmetric else [])
        for a, b in pairs:
            self._partitions[(a, b)] = t0 + duration_s
            self.windows.append({"kind": "partition", "src": a, "dst": b,
                                 "t0": t0, "t1": t0 + duration_s})
        telemetry.hub().chaos_event(
            "partition", src=src, dst=dst, seconds=round(duration_s, 3),
            symmetric=symmetric)

    def heal(self, src: Optional[str] = None, dst: Optional[str] = None) -> None:
        """Heal matching partitions now (None = wildcard)."""
        t = time.monotonic()
        for (a, b), until in list(self._partitions.items()):
            if (src in (None, a)) and (dst in (None, b)) and until > t:
                self._partitions[(a, b)] = t
                for w in self.windows:
                    if (w["kind"] == "partition" and w["src"] == a
                            and w["dst"] == b and w["t1"] > t):
                        w["t1"] = t

    def partitioned(self, src: str, dst: str) -> bool:
        until = self._partitions.get((src, dst))
        return until is not None and time.monotonic() < until

    def fault_windows(self, pad_s: float = 0.0) -> List[Tuple[float, float]]:
        """(t0, t1) of every injected window, padded — requests SUBMITTED
        up to `pad_s` before a window can still be caught by it (they're
        in flight when it lands), so SLO exclusion pads backwards."""
        return [(w["t0"] - pad_s, w["t1"]) for w in self.windows]

    # -- background fault draws ---------------------------------------------
    def decide(self, src: str, dst: str) -> Optional[Tuple[str, float]]:
        """One seeded draw per request: (kind, magnitude) or None."""
        if not self.enabled:
            return None
        c, r = self.cfg, self.rng
        x = r.random01()
        for kind, p in (("latency", c.latency_prob), ("drop", c.drop_prob),
                        ("reset", c.reset_prob)):
            if x < p:
                mag = (c.latency_ms / 1e3 * (0.5 + r.random01())
                       if kind == "latency" else 0.0)
                telemetry.hub().chaos_event(kind, src=src, dst=dst)
                return kind, mag
            x -= p
        return None

    async def on_connect(self, src: str, dst: str) -> None:
        """Connect-time hook (real/transport._Peer.connect): an injected
        handshake stall sleeps past the handshake bound, which must then
        surface as connection_failed within the knob window."""
        if not self.enabled:
            return
        if self.partitioned(src, dst):
            telemetry.hub().chaos_event("connect_blackhole", src=src, dst=dst)
            raise error.connection_failed(
                f"injected partition {src}->{dst} (connect)")
        if self.rng.random01() < self.cfg.handshake_stall_prob:
            t0 = time.monotonic()
            stall = max(self.cfg.stall_s, 0.0)
            self.windows.append({"kind": "handshake_stall", "src": src,
                                 "dst": dst, "t0": t0, "t1": t0 + stall})
            telemetry.hub().chaos_event("handshake_stall", src=src, dst=dst,
                                        seconds=round(stall, 3))
            await asyncio.sleep(stall)


class ChaosTransport:
    """The fault-injecting shim over a RealNetwork: same surface, seeded
    faults applied around the inner call. One per named client process."""

    def __init__(self, inner: RealNetwork, nemesis: NetworkNemesis,
                 name: str = ""):
        self.inner = inner
        self.nemesis = nemesis
        self.name = name or inner.name or "client"
        # hand identity + the connect-time hook down to the peers
        inner.name = self.name
        inner.chaos = nemesis
        for p in inner._peers.values():
            p.src, p.chaos = self.name, nemesis
        #: what this endpoint suffered, by kind (campaign report fodder)
        self.suffered: Dict[str, int] = {}

    def _count(self, kind: str) -> None:
        self.suffered[kind] = self.suffered.get(kind, 0) + 1

    def transport_degraded(self) -> bool:
        return self.inner.transport_degraded()

    async def request(self, src: str, ep, payload, priority: int = 0,
                      timeout: Optional[float] = None):
        nem = self.nemesis
        if nem.partitioned(self.name, ep.address):
            # one-way blackhole: the frame leaves and dies. A real client
            # burns its timeout; we charge a bounded detection cost so
            # campaign wall clock goes to load, then raise the same typed
            # error an unreachable peer produces.
            self._count("partitioned")
            await asyncio.sleep(min(timeout or 1.0, nem.cfg.drop_detect_s))
            raise error.connection_failed(
                f"injected partition {self.name}->{ep.address}")
        fault = nem.decide(self.name, ep.address)
        if fault is not None:
            kind, mag = fault
            self._count(kind)
            if kind == "latency":
                await asyncio.sleep(mag)
            elif kind == "drop":
                await asyncio.sleep(min(timeout or 1.0, nem.cfg.drop_detect_s))
                raise error.request_maybe_delivered(
                    f"injected frame drop {self.name}->{ep.address}")
            elif kind == "reset":
                peer = self.inner._peers.get(ep.address)
                if peer is not None:
                    peer._fail_all()
                raise error.connection_failed(
                    f"injected connection reset {self.name}->{ep.address}")
        return await self.inner.request(src, ep, payload, priority,
                                        timeout=timeout)

    async def one_way(self, src: str, ep, payload, priority: int = 0) -> None:
        nem = self.nemesis
        if nem.partitioned(self.name, ep.address):
            self._count("partitioned")
            return   # one-ways are unreliable by contract: silently eaten
        fault = nem.decide(self.name, ep.address)
        if fault is not None:
            # every counted fault is APPLIED — the injected-fault
            # inventory must match what the system actually suffered
            kind, mag = fault
            self._count(kind)
            if kind == "drop":
                return
            if kind == "latency":
                await asyncio.sleep(mag)
            elif kind == "reset":
                peer = self.inner._peers.get(ep.address)
                if peer is not None:
                    peer._fail_all()
                return   # the frame died with the connection
        await self.inner.one_way(src, ep, payload, priority)

    def close(self) -> None:
        self.inner.close()


class DiskNemesis:
    """Seeded disk-fault nemesis for the durability surfaces — the
    campaign-facing wrapper over `fault/inject.DiskFaults`, shaped like
    NetworkNemesis: one seeded decision engine per campaign, every
    injection counted in the telemetry hub (`chaos.disk_*` counters +
    event ring, so `cli chaos-status` renders them) and every stall
    logged as a wall-clock window for SLO exclusion.

    A DiskNemesis IS the `disk=` hook the black-box journal
    (core/blackbox.py), the snapshot writer (fault/recovery.py) and the
    AOT program cache (core/progcache.py) accept: `apply(surface, data)`
    per durable write. The serving path's contract is that every fault
    this injects degrades gracefully — shed-to-memory journaling, a
    skipped snapshot, a compile instead of a cache hit — never a crash
    or silent corruption (crc framing catches the bit-rot at read)."""

    def __init__(self, seed: int, rates: Optional["object"] = None,
                 surface_rates: Optional[Dict[str, "object"]] = None):
        from ..fault.inject import DiskFaultRates, DiskFaults

        self.seed = seed
        self.rates = rates or DiskFaultRates.from_knobs()
        #: every injected fault: {kind, surface, t0, t1} (stalls have
        #: real width; point faults are zero-width windows)
        self.windows: List[dict] = []
        self.faults = DiskFaults(rates=self.rates, seed=seed,
                                 on_fault=self._on_fault)
        #: per-surface overrides: the crash campaign keeps the JOURNAL
        #: surface stall-only (no record loss, so post-recovery replay
        #: parity stays provable) while the snapshot and progcache
        #: surfaces take the destructive kinds their readers must
        #: tolerate by design (torn-tail fallback, poisoned-entry miss)
        self._by_surface = {
            s: DiskFaults(rates=r, seed=seed + 1 + i,
                          on_fault=self._on_fault)
            for i, (s, r) in enumerate(sorted(
                (surface_rates or {}).items()))}
        self.enabled = True

    def _on_fault(self, surface: str, kind: str) -> None:
        t0 = time.monotonic()
        width = (self.rates.stall_ms / 1e3) if kind == "stall" else 0.0
        self.windows.append({"kind": f"disk_{kind}", "surface": surface,
                             "t0": t0, "t1": t0 + width})
        telemetry.hub().chaos_event(f"disk_{kind}", surface=surface)

    def apply(self, surface: str, data: bytes) -> bytes:
        """The durable-write hook (see DiskFaults.apply): returns the
        bytes to write (possibly bit-rotted), sleeps through a stall, or
        raises OSError/TornWrite for the caller's degraded path."""
        if not self.enabled:
            return data
        return self._by_surface.get(surface, self.faults).apply(
            surface, data)

    def fault_windows(self, pad_s: float = 0.0) -> List[Tuple[float, float]]:
        """(t0, t1) of every injected disk window, padded backwards like
        NetworkNemesis.fault_windows — a write submitted just before a
        stall lands inside it."""
        return [(w["t0"] - pad_s, w["t1"]) for w in self.windows]

    def summary(self) -> dict:
        """Campaign-report fragment: the seeded rates and what actually
        got injected, per (surface, kind) — the `disk-fault incidents
        explained` half of the chaos-crash acceptance gate."""
        injected = dict(self.faults.injected)
        for df in self._by_surface.values():
            for k, n in df.injected.items():
                injected[k] = injected.get(k, 0) + n
        return {"seed": self.seed,
                "rates": {"stall": self.rates.stall,
                          "stall_ms": self.rates.stall_ms,
                          "torn": self.rates.torn,
                          "enospc": self.rates.enospc,
                          "rot": self.rates.rot},
                "injected": injected}


def chaos_status_lines() -> List[str]:
    """Render this process's nemesis activity from the telemetry hub —
    the body of `tools/cli.py chaos-status` and the campaign's summary
    printer (real/nemesis.py). Counters first, then the recent event ring
    with details."""
    hub = telemetry.hub()
    counts = hub.chaos_counts()
    lines: List[str] = []
    if not counts and not hub.chaos_events:
        return ["no nemesis activity recorded in this process"]
    lines.append("nemesis event counts:")
    for kind in sorted(counts):
        lines.append(f"  {kind:<18} {counts[kind]}")
    recent = list(hub.chaos_events)[-10:]
    if recent:
        lines.append(f"recent events ({len(recent)} of {len(hub.chaos_events)}):")
        for ev in recent:
            detail = ", ".join(f"{k}={v}" for k, v in sorted(ev.items())
                               if k not in ("kind", "t"))
            lines.append(f"  t={ev['t']:.3f} {ev['kind']}"
                         + (f" ({detail})" if detail else ""))
    return lines
